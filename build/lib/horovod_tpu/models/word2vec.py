"""Skip-gram word2vec — the reference's sparse-gradient example workload
(examples/tensorflow_word2vec.py): embedding + NCE-style loss whose
embedding gradients are sparse rows, exchanged via the allgather-based
sparse allreduce (tensorflow/__init__.py:67-78).

TPU-first layout: embedding dim a multiple of 128 by default so lookups and
the NCE matmul tile onto the MXU; negative sampling via a fixed-size random
draw (static shapes for XLA).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Word2VecParams(NamedTuple):
    embeddings: jax.Array   # [vocab, dim]
    nce_weights: jax.Array  # [vocab, dim]
    nce_biases: jax.Array   # [vocab]


def init_params(vocab_size: int, dim: int = 128, seed: int = 0
                ) -> Word2VecParams:
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    # Uniform [-1, 1] embeddings, truncated-normal NCE weights — the
    # standard word2vec init (≙ examples/tensorflow_word2vec.py:137-148).
    emb = jax.random.uniform(k1, (vocab_size, dim), jnp.float32, -1.0, 1.0)
    nce_w = jax.random.truncated_normal(
        k2, -2.0, 2.0, (vocab_size, dim), jnp.float32) / np.sqrt(dim)
    return Word2VecParams(emb, nce_w, jnp.zeros((vocab_size,), jnp.float32))


def nce_loss(params: Word2VecParams, centers: jax.Array,
             targets: jax.Array, neg_samples: jax.Array) -> jax.Array:
    """Sampled-softmax / NCE objective over one skip-gram batch.

    centers: [B] int32 — input word ids
    targets: [B] int32 — context word ids (positives)
    neg_samples: [K] int32 — shared negative draw
    """
    h = params.embeddings[centers]                      # [B, D]
    pos_w = params.nce_weights[targets]                 # [B, D]
    pos_b = params.nce_biases[targets]                  # [B]
    pos_logit = jnp.sum(h * pos_w, axis=-1) + pos_b     # [B]
    neg_w = params.nce_weights[neg_samples]             # [K, D]
    neg_b = params.nce_biases[neg_samples]              # [K]
    neg_logit = h @ neg_w.T + neg_b[None, :]            # [B, K]
    pos_loss = jax.nn.softplus(-pos_logit)
    neg_loss = jnp.sum(jax.nn.softplus(neg_logit), axis=-1)
    return jnp.mean(pos_loss + neg_loss)


def skipgram_batch(rng: np.random.RandomState, corpus: np.ndarray,
                   batch_size: int, window: int = 2
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Sample (center, context) pairs from a token array."""
    idx = rng.randint(window, len(corpus) - window, size=batch_size)
    offs = rng.randint(1, window + 1, size=batch_size)
    sign = rng.choice([-1, 1], size=batch_size)
    centers = corpus[idx]
    targets = corpus[idx + sign * offs]
    return centers.astype("int32"), targets.astype("int32")


def synthetic_corpus(vocab_size: int, length: int, seed: int = 0
                     ) -> np.ndarray:
    """Zipf-distributed token stream (word frequencies are Zipfian, which
    is what makes the sparse path worthwhile)."""
    rng = np.random.RandomState(seed)
    ranks = np.arange(1, vocab_size + 1, dtype="float64")
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    return rng.choice(vocab_size, size=length, p=probs).astype("int32")
