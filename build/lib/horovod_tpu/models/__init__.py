"""horovod_tpu.models"""
