"""Checkpoint/resume with the reference's rank-0 + broadcast conventions.

The reference delegates serialization to the frameworks but fixes two
conventions (SURVEY.md §5): save on rank 0 only (README.md:102-104,
examples/keras_imagenet_resnet50.py:126-127) and, on resume, load on rank 0
then broadcast — including the scalar ``resume_from_epoch``
(examples/keras_imagenet_resnet50.py:47-56, :130-133).

Serialization uses flax msgpack (``flax.serialization``) — a single
self-contained file, atomic-renamed into place.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

from ..core import state as _state
from ..parallel.data import broadcast_parameters


def _is_saving_process() -> bool:
    return _state.process_index() == 0


def save_checkpoint(path: str, tree: Any, step: Optional[int] = None) -> bool:
    """Save ``tree`` at ``path`` from the coordinating process only
    (≙ the rank-0 guard in every reference example).  Returns True if this
    process performed the save."""
    if not _is_saving_process():
        return False
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    from flax import serialization

    host_tree = jax.tree_util.tree_map(np.asarray, tree)
    blob = serialization.to_bytes(host_tree)
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)  # atomic publish
    if step is not None:
        with open(f"{path}.step", "w") as f:
            f.write(str(step))
    return True


def restore_checkpoint(path: str, target: Any, broadcast: bool = True) -> Any:
    """Load ``path`` into the structure of ``target`` and (by default)
    broadcast from root so all replicas resume identically
    (≙ load-on-rank-0-then-broadcast, keras_imagenet_resnet50.py:130-133).

    Only the coordinating process reads the file — non-root processes keep
    ``target`` and receive root's values through the broadcast, so a
    checkpoint that exists only on the coordinator's disk restores
    everywhere (the reference's save-on-rank-0 convention implies exactly
    this asymmetry)."""
    from flax import serialization

    if not _state.is_initialized() or _is_saving_process():
        with open(path, "rb") as f:
            blob = f.read()
        tree = serialization.from_bytes(target, blob)
    else:
        tree = target
    if broadcast and _state.is_initialized():
        tree = broadcast_parameters(tree, root_rank=0)
    return tree


def resume_epoch(path: str) -> int:
    """Determine the epoch to resume from and agree on it across replicas —
    the reference broadcasts this scalar explicitly
    (keras_imagenet_resnet50.py:47-56)."""
    epoch = 0
    step_file = f"{path}.step"
    if os.path.exists(step_file):
        with open(step_file) as f:
            epoch = int(f.read().strip())
    if _state.is_initialized():
        from ..ops import collective as C

        epoch = int(np.asarray(C.broadcast(
            np.asarray(epoch, np.int32), root_rank=0,
            name="resume_from_epoch")))
    return epoch
