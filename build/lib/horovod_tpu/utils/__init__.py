"""horovod_tpu.utils"""
