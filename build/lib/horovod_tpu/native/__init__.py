"""horovod_tpu.native"""
