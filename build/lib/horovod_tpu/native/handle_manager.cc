// Async-handle bookkeeping — TPU-native equivalent of the reference's
// HandleManager (horovod/torch/handle_manager.{h,cc}): an atomic handle
// counter plus a mutex-guarded done-map backing the Python-visible
// poll/synchronize API. The in-flight payloads (JAX array futures) stay on
// the Python side; this owns only identity and completion state, exactly
// like the reference owns only handle→Status.

#include <atomic>
#include <mutex>
#include <unordered_map>

namespace hvdtpu {
namespace {

class HandleManager {
 public:
  int Allocate() {
    int h = next_.fetch_add(1);
    std::lock_guard<std::mutex> g(mu_);
    done_[h] = false;
    return h;
  }
  void MarkDone(int h) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = done_.find(h);
    if (it != done_.end()) it->second = true;
  }
  bool Poll(int h) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = done_.find(h);
    return it != done_.end() && it->second;
  }
  void Release(int h) {
    std::lock_guard<std::mutex> g(mu_);
    done_.erase(h);
  }

 private:
  std::atomic<int> next_{0};
  std::mutex mu_;
  std::unordered_map<int, bool> done_;
};

}  // namespace
}  // namespace hvdtpu

extern "C" {

void* hvd_handle_manager_create() { return new hvdtpu::HandleManager(); }
void hvd_handle_manager_destroy(void* hm) {
  delete static_cast<hvdtpu::HandleManager*>(hm);
}
int hvd_handle_manager_allocate(void* hm) {
  return static_cast<hvdtpu::HandleManager*>(hm)->Allocate();
}
void hvd_handle_manager_mark_done(void* hm, int h) {
  static_cast<hvdtpu::HandleManager*>(hm)->MarkDone(h);
}
int hvd_handle_manager_poll(void* hm, int h) {
  return static_cast<hvdtpu::HandleManager*>(hm)->Poll(h) ? 1 : 0;
}
void hvd_handle_manager_release(void* hm, int h) {
  static_cast<hvdtpu::HandleManager*>(hm)->Release(h);
}

}  // extern "C"
