// Chrome-tracing timeline writer — C++ twin of utils/timeline.py, itself
// the TPU-native equivalent of the reference Timeline
// (horovod/common/timeline.{h,cc}): per-tensor trace rows ("processes"
// with pid metadata, timeline.cc:59-76), mutex-guarded writes, 1 s flush
// cadence (timeline.h:32).

#include <chrono>
#include <cstdio>
#include <mutex>
#include <string>
#include <unordered_map>

namespace hvdtpu {
namespace {

double NowUs(double start) {
  double t = std::chrono::duration<double>(
                 std::chrono::steady_clock::now().time_since_epoch())
                 .count();
  return (t - start) * 1e6;
}

// Event phase codes shared with the Python binding:
// 0 = "B" (begin), 1 = "E" (end), 2 = "i" (instant), 3 = "M" (metadata).
const char* PhChar(int ph) {
  switch (ph) {
    case 0: return "B";
    case 1: return "E";
    case 2: return "i";
    default: return "M";
  }
}

std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

class Timeline {
 public:
  explicit Timeline(const std::string& path)
      : start_(std::chrono::duration<double>(
                   std::chrono::steady_clock::now().time_since_epoch())
                   .count()),
        last_flush_(start_) {
    file_ = std::fopen(path.c_str(), "w");
    if (file_) std::fputs("[\n", file_);
  }

  ~Timeline() { Close(); }

  void Event(int ph, const std::string& tensor, const std::string& name,
             const std::string& args_json) {
    std::lock_guard<std::mutex> g(mu_);
    if (!file_) return;
    int pid = Pid(tensor);
    std::fprintf(file_, "{\"ph\": \"%s\", \"ts\": %.3f, \"pid\": %d",
                 PhChar(ph), NowUs(start_), pid);
    if (!name.empty())
      std::fprintf(file_, ", \"name\": \"%s\"", JsonEscape(name).c_str());
    if (!args_json.empty() && args_json != "{}")
      std::fprintf(file_, ", \"args\": %s", args_json.c_str());
    std::fputs("},\n", file_);
    MaybeFlush();
  }

  void Close() {
    std::lock_guard<std::mutex> g(mu_);
    if (!file_) return;
    std::fprintf(file_,
                 "{\"ph\": \"i\", \"ts\": %.3f, \"pid\": 0, \"name\": "
                 "\"shutdown\"}\n]\n",
                 NowUs(start_));
    std::fclose(file_);
    file_ = nullptr;
  }

 private:
  int Pid(const std::string& tensor) {
    auto it = pids_.find(tensor);
    if (it != pids_.end()) return it->second;
    int pid = next_pid_++;
    pids_[tensor] = pid;
    // Name the per-tensor trace row (≙ timeline.cc:59-76).
    std::fprintf(file_,
                 "{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
                 "\"args\": {\"name\": \"%s\"}},\n",
                 pid, JsonEscape(tensor).c_str());
    std::fprintf(file_,
                 "{\"name\": \"process_sort_index\", \"ph\": \"M\", "
                 "\"pid\": %d, \"args\": {\"sort_index\": %d}},\n",
                 pid, pid);
    return pid;
  }

  void MaybeFlush() {
    // 1 s flush cadence (≙ TIMELINE_FLUSH_TIME, timeline.h:32).
    double now = std::chrono::duration<double>(
                     std::chrono::steady_clock::now().time_since_epoch())
                     .count();
    if (now - last_flush_ > 1.0) {
      std::fflush(file_);
      last_flush_ = now;
    }
  }

  std::FILE* file_ = nullptr;
  double start_;
  double last_flush_;
  std::mutex mu_;
  std::unordered_map<std::string, int> pids_;
  int next_pid_ = 1;
};

}  // namespace
}  // namespace hvdtpu

extern "C" {

void* hvd_timeline_create(const char* path) {
  return new hvdtpu::Timeline(path);
}

void hvd_timeline_event(void* t, int ph, const char* tensor, const char* name,
                        const char* args_json, double ts_unused) {
  (void)ts_unused;
  static_cast<hvdtpu::Timeline*>(t)->Event(ph, tensor ? tensor : "",
                                           name ? name : "",
                                           args_json ? args_json : "");
}

void hvd_timeline_close(void* t) {
  auto* tl = static_cast<hvdtpu::Timeline*>(t);
  tl->Close();
  delete tl;
}

}  // extern "C"
