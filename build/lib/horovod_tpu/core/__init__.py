"""horovod_tpu.core"""
