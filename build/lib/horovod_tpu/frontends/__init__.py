"""horovod_tpu.frontends"""
