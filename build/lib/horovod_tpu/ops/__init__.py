"""horovod_tpu.ops"""
