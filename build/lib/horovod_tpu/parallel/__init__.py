"""horovod_tpu.parallel"""
