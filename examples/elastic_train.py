"""Elastic training: commit/rollback state + automatic relaunch.

Demonstrates ``horovod_tpu.elastic`` (≙ post-v0.13 ``horovod.elastic``;
the v0.13 reference has no recovery story — a lost rank hung the MPI job
until the scheduler killed it).  The training function is wrapped in
``@elastic.run``; the state it mutates is committed every few steps.  If
a worker dies, the survivors diagnose the failure, exit EX_TEMPFAIL, and
the elastic launcher relaunches the job — which resumes from the last
commit instead of from scratch.

Run (2 processes, CPU, with a simulated failure):

    HVD_TPU_EXAMPLE_DIE_AT=5 \\
    python -m horovod_tpu.run --elastic -np 2 --platform cpu \\
        examples/elastic_train.py

Env knobs: ``HVD_TPU_EXAMPLE_STEPS`` (default 8),
``HVD_TPU_EXAMPLE_DIE_AT`` (step at which rank 1 dies, once, in the
first incarnation; unset = no failure).
"""

import os

import numpy as np

import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu import elastic


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    total = int(os.environ.get("HVD_TPU_EXAMPLE_STEPS", "8"))
    die_at = os.environ.get("HVD_TPU_EXAMPLE_DIE_AT")
    edir = os.environ.get("HVD_TPU_ELASTIC_DIR")
    if die_at is not None and edir is None:
        # Without the elastic launcher there is no relaunch (and no
        # incarnation-scoped place for the die-once marker): the death
        # would just kill the job.
        if rank == 0:
            print("elastic_train: HVD_TPU_EXAMPLE_DIE_AT ignored — "
                  "run under `python -m horovod_tpu.run --elastic`")
        die_at = None
    marker = os.path.join(edir, "example_victim_died") if edir else None

    # Deterministic per-rank data so every incarnation sees the same
    # stream and a recovered run converges to the uninterrupted result.
    w_true = np.array([1.5, -0.5], dtype="float32")
    rng = np.random.RandomState(100 + rank)
    X = rng.normal(size=(total, 16, 2)).astype("float32")
    y = X @ w_true

    state = elastic.State(w=jnp.zeros((2,)), step=0)

    @elastic.run
    def train(state):
        if state.step > 0:
            print(f"elastic_train: resumed rank={rank} "
                  f"from committed step {state.step}")
        while state.step < total:
            i = state.step
            if (die_at is not None and rank == 1 and i == int(die_at)
                    and not os.path.exists(marker)):
                open(marker, "w").close()
                print(f"elastic_train: rank 1 dying at step {i}",
                      flush=True)
                os._exit(1)  # simulated hard failure, no handshake
            xb, yb = jnp.asarray(X[i]), jnp.asarray(y[i])
            grad = 2.0 * xb.T @ (xb @ state.w - yb) / xb.shape[0]
            grad = hvd.allreduce(grad, average=True, name=f"el.grad.{i}")
            state.w = state.w - 0.1 * grad
            state.step += 1
            if state.step % 2 == 0:
                state.commit()
        state.commit()
        return np.asarray(state.w)

    w = train(state)
    err = float(np.abs(w - w_true).sum())
    print(f"elastic_train: OK rank={rank} size={size} steps={total} "
          f"w={w.round(4).tolist()} err={err:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
