"""Uneven-workload training with ``hvd.join()``.

The post-v0.13 Horovod API this demonstrates: when ranks have different
amounts of data, the fast ranks call ``join()`` after their last batch
and contribute zeros to the slow ranks' remaining allreduces (which
still divide by the full size — Horovod's documented Join semantics).
``join()`` returns the LAST rank to join, i.e. the rank that saw every
one of its batches — the natural source for the final model broadcast.
The v0.13 reference predates Join and could only hang here.

Run (2 processes, CPU):

    python -m horovod_tpu.run -np 2 --platform cpu examples/uneven_join.py

Env knobs: ``HVD_TPU_EXAMPLE_STEPS`` (base step count, default 4; rank r
runs base + 2*r steps).
"""

import os

import numpy as np

import jax.numpy as jnp

import horovod_tpu as hvd


def main():
    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    base = int(os.environ.get("HVD_TPU_EXAMPLE_STEPS", "4"))
    steps = base + 2 * rank  # genuinely uneven: rank r has 2r extra batches

    w_true = np.array([2.0, -1.0], dtype="float32")
    rng = np.random.RandomState(rank)
    X = rng.normal(size=(steps, 16, 2)).astype("float32")
    y = X @ w_true

    w = hvd.broadcast(jnp.zeros((2,)), root_rank=0, name="w.init")
    for i in range(steps):
        xb, yb = jnp.asarray(X[i]), jnp.asarray(y[i])
        grad = 2.0 * xb.T @ (xb @ w - yb) / xb.shape[0]
        # Ranks that already joined contribute zeros here.
        grad = hvd.allreduce(grad, average=True, name=f"grad.{i}")
        w = w - 0.1 * grad

    last = hvd.join()
    # The last joiner consumed every one of its batches — broadcast its
    # weights as the final model so all ranks agree.
    w = hvd.broadcast(w, root_rank=last, name="w.final")
    err = float(jnp.sum(jnp.abs(w - jnp.asarray(w_true))))
    print(f"uneven_join: OK rank={rank} size={size} steps={steps} "
          f"last_joined={last} w={np.asarray(w).round(3).tolist()} "
          f"err={err:.3f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
