"""Tour of the full collective API over real processes.

Every collective family the framework carries — allreduce (with reduce
operators), allgather (ragged), broadcast, reducescatter, alltoall
(ragged splits), barrier, grouped variants, object collectives, and a
process-set leg — each self-verified the way the reference's tests do
(result compared against the closed-form expectation).

Run (2 processes, CPU):

    python -m horovod_tpu.run -np 2 --platform cpu \\
        examples/collectives_tour.py
"""

import numpy as np

import jax.numpy as jnp

import horovod_tpu as hvd


def main():
    hvd.init()
    r, n = hvd.rank(), hvd.size()

    # allreduce: sum / average / min / max (rank r contributes r+1).
    x = jnp.full((4,), float(r + 1))
    assert float(hvd.allreduce(x, average=False)[0]) == n * (n + 1) / 2
    assert float(hvd.allreduce(x, op=hvd.Min)[0]) == 1.0
    assert float(hvd.allreduce(x, op=hvd.Max)[0]) == float(n)

    # ragged allgather: rank r contributes r+1 rows.
    g = np.asarray(hvd.allgather(jnp.full((r + 1, 2), float(r))))
    assert g.shape[0] == n * (n + 1) // 2

    # broadcast from the last rank.
    b = hvd.broadcast(jnp.full((3,), float(r)), n - 1)
    np.testing.assert_allclose(np.asarray(b), float(n - 1))

    # reducescatter: my chunk of the summed arange.
    rs = np.asarray(hvd.reducescatter(jnp.arange(float(2 * n)) + r,
                                      average=False))
    want = (n * np.arange(float(2 * n))
            + sum(range(n)))[2 * r:2 * r + 2]
    np.testing.assert_allclose(rs, want)

    # ragged alltoall: rank r sends r+1 rows to each destination.
    rows = jnp.full(((r + 1) * n, 1), float(r))
    recv = np.asarray(hvd.alltoall(rows, splits=[r + 1] * n))
    assert recv.shape[0] == n * (n + 1) // 2
    # received rows from sender s carry value s, in rank order.
    off = 0
    for s in range(n):
        np.testing.assert_allclose(recv[off:off + s + 1], float(s))
        off += s + 1

    # grouped + async.
    outs = hvd.grouped_allreduce([jnp.ones((2,)), jnp.ones((3,))],
                                 average=False)
    assert all(float(o[0]) == n for o in outs)

    # object collectives.
    objs = hvd.allgather_object({"rank": r})
    assert [o["rank"] for o in objs] == list(range(n))

    # a singleton process set coexists with world ops.
    ps = hvd.add_process_set([0])
    if ps.included():
        assert float(hvd.allreduce(jnp.ones((1,)), average=False,
                                   process_set=ps)[0]) == 1.0
    hvd.remove_process_set(ps)

    hvd.barrier()
    print(f"collectives_tour: OK rank={r} size={n}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
