"""Skip-gram word2vec with sparse gradient exchange — ≙ the reference's
examples/tensorflow_word2vec.py (the workload that exercises the
IndexedSlices → allgather sparse allreduce path,
tensorflow/__init__.py:67-78).

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/word2vec.py
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

import horovod_tpu as hvd
from horovod_tpu.models import word2vec as W
from horovod_tpu.ops import sparse as S


def main():
    hvd.init()
    vocab, dim = 2000, 128
    params = W.init_params(vocab, dim)
    corpus = W.synthetic_corpus(vocab, 100_000)
    rng = np.random.RandomState(hvd.rank())
    lr = 0.2

    @jax.jit
    def grads_fn(emb, nce_w, nce_b, centers, targets, negs):
        def loss(emb, nce_w, nce_b):
            p = W.Word2VecParams(emb, nce_w, nce_b)
            return W.nce_loss(p, centers, targets, negs)
        return jax.value_and_grad(loss, argnums=(0, 1, 2))(emb, nce_w, nce_b)

    n_steps = max(1, int(os.environ.get("HVD_TPU_EXAMPLE_STEPS", "100")))
    for step in range(n_steps):
        centers, targets = W.skipgram_batch(rng, corpus, batch_size=128)
        negs = rng.randint(0, vocab, size=64).astype("int32")
        loss, (g_emb, g_w, g_b) = grads_fn(
            params.embeddings, params.nce_weights, params.nce_biases,
            jnp.asarray(centers), jnp.asarray(targets), jnp.asarray(negs))

        # Embedding gradient: hvd.allreduce dispatches IndexedSlices to the
        # sparse exchange (touched rows only) transparently, exactly like
        # the reference (tensorflow/__init__.py:67-78).
        sl = S.sparse_grad_from_dense(g_emb, jnp.asarray(centers))
        sl = hvd.allreduce(sl, average=True, name=f"w2v.emb.{step}")
        new_emb = S.apply_to(params.embeddings, sl, scale=-lr)

        # NCE weights/biases: dense averaged allreduce.
        g_w = hvd.allreduce(g_w, name=f"w2v.w.{step}")
        g_b = hvd.allreduce(g_b, name=f"w2v.b.{step}")
        params = W.Word2VecParams(
            new_emb, params.nce_weights - lr * g_w,
            params.nce_biases - lr * g_b)
        if step % 20 == 0:
            print(f"step {step}: loss={float(loss):.4f}")
    print(f"final loss={float(loss):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
