"""ResNet-50 distributed training — ≙ examples/keras_imagenet_resnet50.py,
the reference's flagship: checkpoint-resume with broadcast, LR warmup +
staircase decay, rank-0 checkpointing, verbose on rank 0 only.

Synthetic ImageNet data (as the reference's published benchmarks use,
docs/benchmarks.md:28-33).  Sized down by default so it runs anywhere; pass
--full for benchmark shapes.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/resnet50_synthetic.py
"""

import argparse
import os
import sys

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

import horovod_tpu as hvd
import horovod_tpu.callbacks as callbacks
from horovod_tpu.frontends.loop import Trainer
from horovod_tpu.models import resnet as R
from horovod_tpu.utils.checkpoint import (restore_checkpoint, resume_epoch,
                                          save_checkpoint)

CKPT = "/tmp/horovod_tpu_resnet50/ckpt.msgpack"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="benchmark shapes (224px ResNet-50)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1: shard optimizer state across replicas")
    ap.add_argument("--fsdp", action="store_true",
                    help="FSDP/ZeRO-3: shard parameters AND optimizer "
                         "state across replicas")
    args = ap.parse_args()

    hvd.init()
    verbose = hvd.rank() == 0

    if args.full:
        model = R.ResNet50(num_classes=1000)
        image_size, num_classes, per_chip = 224, 1000, 32
    else:
        model = R.ResNet18Thin(num_classes=16)
        image_size, num_classes, per_chip = 32, 16, 8

    params, stats = R.init_resnet(model, image_size=image_size)

    # Resume: restore on the coordinator, broadcast, and agree on the epoch
    # (≙ keras_imagenet_resnet50.py:47-56, :130-133).  Both params and BN
    # statistics are checkpointed.
    start_epoch = 0
    if os.path.exists(CKPT):
        restored = restore_checkpoint(
            CKPT, {"params": params, "batch_stats": stats})
        params, stats = restored["params"], restored["batch_stats"]
        start_epoch = resume_epoch(CKPT)
        if verbose:
            print(f"resumed from epoch {start_epoch}")

    loss_fn = R.resnet_loss_fn(model)
    steps_per_epoch = 8
    base_lr = 0.0125 * hvd.size()  # linear LR scaling (README.md:90-91)

    trainer = Trainer(
        loss_fn, params, lr=base_lr, optimizer_kwargs={"momentum": 0.9},
        model_state=stats, zero=args.zero, fsdp=args.fsdp,
        callbacks=[
            callbacks.BroadcastGlobalVariablesCallback(0),
            callbacks.MetricAverageCallback(),
            callbacks.LearningRateWarmupCallback(
                warmup_epochs=1, steps_per_epoch=steps_per_epoch,
                verbose=int(verbose)),
            # 30/60/80-style staircase, scaled to the toy epoch count.
            callbacks.LearningRateScheduleCallback(
                multiplier=0.1, start_epoch=2),
        ])

    global_batch = per_chip * hvd.size()
    images, labels = R.synthetic_imagenet(
        4 * global_batch, image_size=image_size, num_classes=num_classes)

    def batches(epoch, step):
        rng = np.random.RandomState(epoch * 131 + step)
        idx = rng.randint(0, len(images), size=global_batch)
        return (jnp.asarray(images[idx]), jnp.asarray(labels[idx]))

    history = trainer.fit(batches, epochs=args.epochs,
                          steps_per_epoch=steps_per_epoch,
                          initial_epoch=start_epoch)
    if verbose:
        for e, logs in enumerate(history):
            print(f"epoch {start_epoch + e}: {logs}")

    if save_checkpoint(CKPT, {"params": trainer.params,
                              "batch_stats": trainer.model_state},
                       step=args.epochs):
        print("checkpoint saved")
    hvd.shutdown()


if __name__ == "__main__":
    main()
