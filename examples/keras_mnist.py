"""Keras MNIST through the Keras frontend — ≙ the reference's
examples/keras_mnist.py: scaled LR, DistributedOptimizer, broadcast +
metric-average callbacks, rank-0 checkpointing.

Usage (8 virtual replicas on CPU):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      KERAS_BACKEND=jax python examples/keras_mnist.py
"""

import os
import sys

os.environ.setdefault("KERAS_BACKEND", "jax")

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

import keras  # noqa: E402

import horovod_tpu.frontends.keras as hvd  # noqa: E402
from horovod_tpu.models.mnist import synthetic_mnist  # noqa: E402


def main():
    hvd.init()

    images, labels = synthetic_mnist(4096, seed=hvd.rank())
    x = np.asarray(images, "float32").reshape(-1, 28 * 28)
    y = np.asarray(labels, "int32")

    model = keras.Sequential([
        keras.layers.Input(shape=(784,)),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dropout(0.2),
        keras.layers.Dense(10),
    ])

    # Scale the learning rate by the number of replicas
    # (reference examples/keras_mnist.py:26-28).
    opt = hvd.DistributedOptimizer(
        keras.optimizers.Adam(learning_rate=1e-3 * hvd.size()))
    model.compile(
        optimizer=opt,
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"])

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(warmup_epochs=2),
    ]
    epochs = max(1, int(os.environ.get("HVD_TPU_EXAMPLE_EPOCHS", "4")))
    hist = model.fit(x, y, batch_size=128, epochs=epochs, verbose=0,
                     callbacks=callbacks)
    for e, (loss, acc) in enumerate(zip(hist.history["loss"],
                                        hist.history["accuracy"])):
        if hvd.rank() == 0:
            print(f"epoch {e}: loss={loss:.4f} acc={acc:.4f}")

    # Rank-0 checkpoint (reference keras_mnist.py:42-44).
    if hvd.rank() == 0:
        model.save("/tmp/keras_mnist_hvd.keras")
        print("saved /tmp/keras_mnist_hvd.keras")
    if epochs > 1:  # single-epoch CI runs have nothing to compare
        assert hist.history["loss"][-1] < hist.history["loss"][0]
    hvd.shutdown()
    print("keras_mnist: OK")


if __name__ == "__main__":
    main()
