"""Transformer language-model training — the beyond-parity stack on one
model: multi-axis mesh (`core/topology.make_mesh`), Pallas flash
attention (`ops/flash_attention.py`), and `make_parallel_train_step`.
The reference has no transformer workload (it predates them); this is
the workload behind docs/benchmarks.md's tokens/sec table.

Usage:
  # tiny LM on 8 virtual CPU replicas (dp4 x tp2):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/transformer_lm.py

  # single real TPU chip, GPT-2-small shape, throughput JSON:
  python examples/transformer_lm.py --bench
"""

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, ".")

from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.core.topology import make_mesh  # noqa: E402
from horovod_tpu.models.transformer import (ParallelAxes,  # noqa: E402
                                            TransformerConfig,
                                            init_transformer, make_loss_fn,
                                            synthetic_lm_batch)
from horovod_tpu.parallel.training import (  # noqa: E402
    make_parallel_train_step, shard_parallel_batch)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", action="store_true",
                    help="GPT-2-small shape on the local device(s); print "
                         "one tokens/sec JSON line")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize layers in the backward pass "
                         "(fits much longer sequences; ~1/3 more FLOPs)")
    ap.add_argument("--loss-chunk", type=int, default=0,
                    help="chunked cross-entropy: never materialize the "
                         "full [batch, seq, vocab] logits")
    ap.add_argument("--export", type=str, default=None, metavar="DIR",
                    help="after training, write a serving-ready "
                         "checkpoint (params + model config + tokenizer "
                         "metadata) that examples/serve_lm.py loads "
                         "end-to-end")
    args = ap.parse_args()

    hvd.init()
    n_dev = len(jax.devices())

    if args.bench:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12,
                                n_layers=12, d_ff=3072,
                                max_seq_len=args.seq or 1024,
                                dtype=jnp.bfloat16, block_q=256,
                                block_k=256, remat=args.remat,
                                loss_chunk=args.loss_chunk)
        batch, seq, steps = args.batch or 8, args.seq or 1024, \
            args.steps or 20
        mesh = make_mesh(data=n_dev)
        ax = ParallelAxes(data="data")
    else:
        cfg = TransformerConfig(vocab_size=512, d_model=64, n_heads=4,
                                n_layers=2, d_ff=128,
                                max_seq_len=max(args.seq or 128, 128),
                                block_q=32, block_k=32, remat=args.remat,
                                loss_chunk=args.loss_chunk)
        batch, seq = args.batch or 2 * n_dev, args.seq or 64
        steps = args.steps or int(
            os.environ.get("HVD_TPU_EXAMPLE_STEPS", "30"))
        # Model-parallel degree 2 when the device count allows — the
        # same program shape the multi-chip dryrun validates.
        tp = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
        mesh = make_mesh(data=n_dev // tp, model=tp)
        ax = ParallelAxes(data="data", model="model" if tp > 1 else None)

    params = init_transformer(jax.random.PRNGKey(0), cfg)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    loss_fn = make_loss_fn(cfg, ax, mesh_axes=mesh.axis_names)
    opt = optax.adamw(3e-4)
    step = make_parallel_train_step(loss_fn, opt, mesh, P("data", None),
                                    donate=False)
    tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(1), batch, seq,
                                         cfg.vocab_size)
    data = shard_parallel_batch((tokens, targets), mesh, P("data", None))
    opt_state = opt.init(params)

    params, opt_state, loss = step(params, opt_state, data)
    first = float(loss)  # also the compile barrier

    if args.bench:
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, data)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, data)
        float(loss)
        dt = time.perf_counter() - t0
        print(json.dumps({
            "metric": "transformer_lm_tokens_per_sec",
            "value": round(batch * seq * steps / dt, 1),
            "unit": "tokens/sec",
            "params_millions": round(n_params / 1e6, 1),
            "batch": batch, "seq": seq, "remat": args.remat,
            "loss_chunk": args.loss_chunk,
            "step_ms": round(dt / steps * 1000, 1),
        }))
    else:
        for s in range(1, steps):
            params, opt_state, loss = step(params, opt_state, data)
            if s % 10 == 0:
                print(f"step {s}: loss={float(loss):.4f}")
        final = float(loss)
        print(f"loss {first:.4f} -> {final:.4f} "
              f"({n_params/1e6:.1f}M params, mesh={dict(zip(mesh.axis_names, mesh.devices.shape))})")
        assert final < first, "loss did not improve"
        print("transformer_lm: OK")
    if args.export:
        from horovod_tpu.utils.checkpoint import save_serving_checkpoint

        tokenizer = "byte" if cfg.vocab_size >= 256 else "ids"
        w = save_serving_checkpoint(args.export, params, cfg,
                                    tokenizer=tokenizer,
                                    extra={"trained_steps": steps},
                                    block=True)
        if w:
            print(f"serving checkpoint exported: {args.export}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
