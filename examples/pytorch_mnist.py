"""PyTorch MNIST through the Torch frontend — ≙ the reference's
examples/pytorch_mnist.py: DistributedOptimizer with named parameters,
broadcast_parameters before training, per-epoch metric allreduce.

Usage (8 virtual replicas on CPU):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/pytorch_mnist.py
"""

import os
import sys

sys.path.insert(0, ".")

import numpy as np  # noqa: E402
import torch  # noqa: E402
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

import horovod_tpu.frontends.torch as hvd  # noqa: E402
from horovod_tpu.models.mnist import synthetic_mnist  # noqa: E402


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def main():
    hvd.init()
    torch.manual_seed(1 + hvd.rank())

    images, labels = synthetic_mnist(2048, seed=hvd.rank())
    x = torch.from_numpy(np.asarray(images, "float32").reshape(-1, 784))
    y = torch.from_numpy(np.asarray(labels, "int64"))

    model = Net()
    # Scale LR by replica count (reference pytorch_mnist.py:33-35).
    opt = torch.optim.SGD(model.parameters(), lr=0.05 * hvd.size(),
                          momentum=0.5)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    # Consistent initialization (reference pytorch_mnist.py:41-42).
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    first_loss = None
    epochs = max(1, int(os.environ.get("HVD_TPU_EXAMPLE_EPOCHS", "3")))
    for epoch in range(epochs):
        losses = []
        for i in range(0, len(x), 128):
            xb, yb = x[i:i + 128], y[i:i + 128]
            opt.zero_grad()
            loss = F.cross_entropy(model(xb), yb)
            loss.backward()
            opt.step()
            losses.append(float(loss))
        # Average the epoch metric across replicas (reference
        # pytorch_mnist.py metric_average, :70-74).
        avg = float(hvd.allreduce(
            torch.tensor([np.mean(losses)]), average=True,
            name=f"epoch.loss.{epoch}"))
        if first_loss is None:
            first_loss = avg
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss={avg:.4f}")
    if epochs > 1:  # single-epoch CI runs have nothing to compare
        assert avg < first_loss
    hvd.shutdown()
    print("pytorch_mnist: OK")


if __name__ == "__main__":
    main()
