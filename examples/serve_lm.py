"""Serve a transformer LM with hvd-serve (docs/inference.md).

Loads the serving-ready checkpoint `examples/transformer_lm.py --export`
writes (params + model config + tokenizer metadata), builds a
continuous-batching InferenceEngine over the local devices (tensor-
parallel over a `model` mesh axis when --tp > 1), warm-starts it, and
either answers one prompt (--prompt / --tokens) or runs the HTTP front
door (--serve) with /generate, /metrics and /healthz on one port.

Usage:
  # train tiny + export, then one-shot generate:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/transformer_lm.py --export /tmp/lm-ckpt
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/serve_lm.py /tmp/lm-ckpt --tokens 5,3,8,1 -n 16

  # HTTP server (POST {"text": ..., "max_tokens": N} to /generate):
  python examples/serve_lm.py /tmp/lm-ckpt --serve --port 9100
"""

import argparse
import json
import sys

sys.path.insert(0, ".")

import jax  # noqa: E402

from horovod_tpu.core.topology import make_mesh  # noqa: E402
from horovod_tpu.serving import InferenceEngine, LMServer  # noqa: E402
from horovod_tpu.serving.server import (decode_tokens,  # noqa: E402
                                        encode_text)
from horovod_tpu.utils.checkpoint import (  # noqa: E402
    load_serving_checkpoint)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("checkpoint", help="directory written by "
                                       "transformer_lm.py --export")
    ap.add_argument("--prompt", type=str, default=None,
                    help="text prompt (byte tokenizer; needs a "
                         "vocab_size >= 256 model)")
    ap.add_argument("--tokens", type=str, default=None,
                    help="comma-separated token-id prompt")
    ap.add_argument("-n", "--max-tokens", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--serve", action="store_true",
                    help="run the HTTP front door instead of one shot")
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (shards KV heads + "
                         "attention/FFN over a 'model' mesh axis)")
    ap.add_argument("--slots", type=int, default=8,
                    help="decode batch slots (continuous batching)")
    ap.add_argument("--draft", type=str, default=None,
                    help="serving checkpoint of a DRAFT model (same "
                         "vocab): arms speculative decoding with the "
                         "bitwise-greedy acceptance rule "
                         "(docs/inference.md)")
    ap.add_argument("--spec-tokens", type=int, default=None,
                    help="draft proposals per iteration (with --draft; "
                         "default HVD_TPU_SPEC_TOKENS)")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable the shared-prefix page cache")
    args = ap.parse_args()

    params, cfg, meta = load_serving_checkpoint(args.checkpoint)
    mesh = None
    if args.tp > 1:
        mesh = make_mesh(data=1, model=args.tp,
                         devices=jax.devices()[:args.tp])
    draft = None
    if args.draft is not None:
        dparams, dcfg, _ = load_serving_checkpoint(args.draft)
        draft = (dparams, dcfg)
    engine = InferenceEngine(params, cfg, mesh=mesh,
                             max_slots=args.slots, draft=draft,
                             spec_tokens=args.spec_tokens,
                             prefix_cache=(False if args.no_prefix_cache
                                           else None))

    if args.serve:
        server = LMServer(engine, port=args.port).start()
        print(f"serve_lm: listening on :{server.port} "
              f"(/generate /metrics /healthz), "
              f"{meta['tokenizer']['kind']} tokenizer, "
              f"tp={args.tp}, slots={args.slots}", flush=True)
        try:
            server._thread.join()
        except KeyboardInterrupt:
            server.close()
        return

    if args.tokens:
        prompt = [int(t) for t in args.tokens.split(",")]
    elif args.prompt is not None:
        prompt = encode_text(args.prompt, cfg.vocab_size)
    else:
        ap.error("need --prompt or --tokens (or --serve)")
    engine.warm_start()
    out = engine.generate(prompt, max_new_tokens=args.max_tokens,
                          temperature=args.temperature)
    text = decode_tokens(out, cfg.vocab_size)
    print(json.dumps({"prompt": prompt, "tokens": out, "text": text}))
    print("serve_lm: OK")


if __name__ == "__main__":
    main()
