"""Distill a DRAFT model from a served transformer LM (docs/inference.md).

Speculative decoding needs a small draft model that agrees with the
target often enough to pay for itself.  This example closes that loop
end-to-end: load the serving checkpoint `examples/transformer_lm.py
--export` writes, derive a half-size draft config (same vocab, so the
bitwise-greedy acceptance rule applies verbatim), train the draft by
temperature-softened KL against the frozen teacher's logits on
synthetic batches, report the greedy-agreement rate on held-out data,
and `--export` a serving checkpoint pair consumable by
`examples/serve_lm.py CKPT --draft DRAFT`.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/transformer_lm.py --export /tmp/lm-ckpt
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/distill_draft.py /tmp/lm-ckpt --export /tmp/lm-draft
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/serve_lm.py /tmp/lm-ckpt --draft /tmp/lm-draft \
      --tokens 5,3,8,1 -n 16
"""

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, ".")

from horovod_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                            forward, init_transformer,
                                            synthetic_lm_batch)
from horovod_tpu.utils.checkpoint import (load_serving_checkpoint,  # noqa: E402
                                          save_serving_checkpoint)


def draft_config(cfg: TransformerConfig) -> TransformerConfig:
    """Half the teacher along every axis that costs decode latency —
    same vocab and max_seq_len so draft proposals are interchangeable
    token streams for the acceptance rule."""
    return TransformerConfig(
        vocab_size=cfg.vocab_size,
        d_model=max(32, cfg.d_model // 2),
        n_heads=max(1, cfg.n_heads // 2),
        n_layers=max(1, cfg.n_layers // 2),
        d_ff=max(64, cfg.d_ff // 2),
        max_seq_len=cfg.max_seq_len,
        block_q=cfg.block_q, block_k=cfg.block_k)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("checkpoint", help="TARGET serving checkpoint "
                                       "(transformer_lm.py --export)")
    ap.add_argument("--export", type=str, default=None, metavar="DIR",
                    help="write the distilled draft's serving "
                         "checkpoint here (serve_lm.py --draft DIR)")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=2.0,
                    help="distillation softening temperature")
    args = ap.parse_args()

    tparams, tcfg, meta = load_serving_checkpoint(args.checkpoint)
    tparams = jax.tree_util.tree_map(jnp.asarray, tparams)
    dcfg = draft_config(tcfg)
    steps = args.steps or int(
        os.environ.get("HVD_TPU_EXAMPLE_STEPS", "60"))
    seq = min(args.seq or 64, tcfg.max_seq_len)
    temp = args.temperature

    dparams = init_transformer(jax.random.PRNGKey(2), dcfg)
    t_size = sum(x.size for x in jax.tree_util.tree_leaves(tparams))
    d_size = sum(x.size for x in jax.tree_util.tree_leaves(dparams))

    teacher_logits = jax.jit(lambda toks: forward(tparams, toks, tcfg)[0])

    def distill_loss(params, toks, tlogits):
        slogits, aux = forward(params, toks, dcfg)
        soft_t = jax.nn.softmax(tlogits / temp, axis=-1)
        log_s = jax.nn.log_softmax(slogits / temp, axis=-1)
        log_t = jax.nn.log_softmax(tlogits / temp, axis=-1)
        kl = jnp.sum(soft_t * (log_t - log_s), axis=-1)
        return jnp.mean(kl) * temp * temp + aux

    opt = optax.adamw(1e-3)
    opt_state = opt.init(dparams)

    @jax.jit
    def step(params, opt_state, toks, tlogits):
        loss, grads = jax.value_and_grad(distill_loss)(params, toks,
                                                       tlogits)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for s in range(steps):
        toks, _ = synthetic_lm_batch(jax.random.PRNGKey(100 + s),
                                     args.batch, seq, dcfg.vocab_size)
        tlogits = teacher_logits(toks)
        dparams, opt_state, loss = step(dparams, opt_state, toks,
                                        tlogits)
        if first is None:
            first = float(loss)
        if (s + 1) % 20 == 0:
            print(f"step {s + 1}: distill_kl={float(loss):.4f}")
    final = float(loss)

    # Held-out greedy agreement — the quantity speculative decoding's
    # acceptance rate tracks (docs/inference.md).
    etoks, _ = synthetic_lm_batch(jax.random.PRNGKey(9), args.batch,
                                  seq, dcfg.vocab_size)
    t_pick = jnp.argmax(teacher_logits(etoks), axis=-1)
    d_pick = jnp.argmax(forward(dparams, etoks, dcfg)[0], axis=-1)
    agreement = float(jnp.mean(t_pick == d_pick))

    print(f"distill_kl {first:.4f} -> {final:.4f} "
          f"(teacher {t_size / 1e6:.1f}M -> draft {d_size / 1e6:.1f}M "
          f"params, greedy agreement {agreement:.2f})")
    assert final < first, "distillation loss did not improve"

    if args.export:
        w = save_serving_checkpoint(
            args.export, dparams, dcfg,
            tokenizer=meta["tokenizer"]["kind"],
            extra={"distilled_from": os.path.abspath(args.checkpoint),
                   "distill_steps": steps,
                   "greedy_agreement": round(agreement, 4)},
            block=True)
        if w:
            print(f"draft checkpoint exported: {args.export}")
    print("distill_draft: OK")


if __name__ == "__main__":
    main()
