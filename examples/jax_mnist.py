"""Data-parallel MNIST — ≙ the reference's examples/tensorflow_mnist.py.

Usage (8 virtual replicas on CPU):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/jax_mnist.py

The reference structure (examples/tensorflow_mnist.py:83-119): init, build
model, wrap optimizer in DistributedOptimizer, broadcast initial variables,
train, checkpoint on rank 0.  Same flow here, with the step compiled as one
SPMD program.
"""

import os
import sys

import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, ".")

import horovod_tpu as hvd
from horovod_tpu.models.mnist import (MnistCNN, cross_entropy_loss, accuracy,
                                      init_params, synthetic_mnist)
from horovod_tpu.parallel.input import prefetch_to_device
from horovod_tpu.parallel.training import make_train_step, make_eval_step, \
    shard_batch
from horovod_tpu.utils.checkpoint import save_checkpoint


def main():
    hvd.init()
    print(f"replicas={hvd.size()} local={hvd.local_size()}")

    model = MnistCNN()
    params = init_params(model)
    # Replica-consistent start (≙ BroadcastGlobalVariablesHook).
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(params, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": params}, images),
                                  labels)

    # Scale LR by replica count, as the reference README prescribes
    # (README.md:90-91).
    opt = optax.sgd(0.01 * hvd.size(), momentum=0.9)
    opt_state = opt.init(params)
    step = make_train_step(loss_fn, opt)

    # Overridable so CI can shrink the run (≙ the reference patching its
    # examples smaller with sed, .travis.yml:105-109).  Clamped so at
    # least one full global batch and one epoch always run.
    global_batch = 16 * hvd.size()
    n_data = max(int(os.environ.get("HVD_TPU_EXAMPLE_DATA", "2048")),
                 global_batch)
    epochs = max(1, int(os.environ.get("HVD_TPU_EXAMPLE_EPOCHS", "2")))
    images, labels = synthetic_mnist(n_data)
    steps_per_epoch = len(images) // global_batch

    for epoch in range(epochs):
        perm = np.random.RandomState(epoch).permutation(len(images))

        def epoch_batches(perm=perm):
            for s in range(steps_per_epoch):
                idx = perm[s * global_batch:(s + 1) * global_batch]
                yield (images[idx], labels[idx])

        # Host-overlapped input (hvd-pipeline): batch N+1 stages
        # host→device on a background thread while step N computes, and
        # the loss stays an un-fetched device array until the per-epoch
        # log — the only host sync in the loop.
        with prefetch_to_device(epoch_batches(), depth=2) as staged:
            for batch in staged:
                params, opt_state, loss = step(params, opt_state, batch)
        print(f"epoch {epoch}: loss={float(loss):.4f}")

    def metric_fn(params, batch):
        imgs, lbls = batch
        return accuracy(model.apply({"params": params}, imgs), lbls)

    ev = make_eval_step(metric_fn)
    acc = ev(params, shard_batch((jnp.asarray(images[:512]),
                                  jnp.asarray(labels[:512]))))
    print(f"train-set accuracy: {float(acc):.3f}")

    # Checkpoint from the coordinating process only (README.md:102-104).
    # The write runs on the background writer thread; wait() is the
    # durability point (a bare `if save_checkpoint(...)` still works —
    # pending writes also flush at interpreter exit).
    ckpt = save_checkpoint("/tmp/horovod_tpu_mnist/ckpt.msgpack", params)
    if ckpt:
        ckpt.wait()
        print("checkpoint saved")
    hvd.shutdown()


if __name__ == "__main__":
    main()
