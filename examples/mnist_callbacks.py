"""MNIST with the callback suite — ≙ examples/keras_mnist_advanced.py:
broadcast-init, metric averaging, gradual LR warmup, LR schedule.

Usage:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/mnist_callbacks.py
"""

import os
import sys

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

import horovod_tpu as hvd
import horovod_tpu.callbacks as callbacks
from horovod_tpu.frontends.loop import Trainer
from horovod_tpu.models.mnist import (MnistMLP, cross_entropy_loss,
                                      init_params, synthetic_mnist)


def main():
    hvd.init()
    model = MnistMLP(hidden=128)
    params = init_params(model)

    def loss_fn(params, batch):
        images, labels = batch
        return cross_entropy_loss(model.apply({"params": params}, images),
                                  labels)

    steps_per_epoch = 16
    trainer = Trainer(
        loss_fn, params, lr=0.1 * hvd.size(),
        optimizer_kwargs={"momentum": 0.9},
        callbacks=[
            # ≙ keras_mnist_advanced.py's callback stack.
            callbacks.BroadcastGlobalVariablesCallback(0),
            callbacks.MetricAverageCallback(),
            callbacks.LearningRateWarmupCallback(
                warmup_epochs=2, steps_per_epoch=steps_per_epoch, verbose=1),
            callbacks.LearningRateScheduleCallback(
                multiplier=0.1, start_epoch=4),
        ])

    images, labels = synthetic_mnist(4096)
    global_batch = 32 * hvd.size()

    def batches(epoch, step):
        rng = np.random.RandomState(epoch * 1000 + step)
        idx = rng.randint(0, len(images), size=global_batch)
        return (jnp.asarray(images[idx]), jnp.asarray(labels[idx]))

    epochs = max(1, int(os.environ.get("HVD_TPU_EXAMPLE_EPOCHS", "6")))
    history = trainer.fit(batches, epochs=epochs,
                          steps_per_epoch=steps_per_epoch)
    for e, logs in enumerate(history):
        print(f"epoch {e}: {logs}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
