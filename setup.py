"""Build system for horovod_tpu (≙ reference setup.py, SURVEY.md §2.2 P9).

The reference probes compilers/MPI/CUDA/NCCL at build time and gates
plugins with HOROVOD_WITH[OUT]_* env vars (reference setup.py:63-384,
:541-577).  The TPU build has exactly one native artifact — the host-side
runtime library ``horovod_tpu/native/libhvdtpu.so`` (coordinator, wire,
timeline, handle manager) — so the probing reduces to:

* C++ flag probing (``-std=c++17``, falling back only on error) via a
  test compile, mirroring the reference's ``test_compile`` approach
  (setup.py:63-87);
* ``HOROVOD_TPU_WITHOUT_NATIVE=1`` skips the native build (pure-Python
  fallbacks keep full behavior);
* ``HOROVOD_TPU_WITH_NATIVE=1`` makes a native build failure fatal
  instead of a warning (≙ the skip-vs-require logic, setup.py:541-577).

The library is an ordinary ``g++ -shared`` product, not a Python
extension: Python binds via ctypes (no pybind11 in the image).
"""

import os
import subprocess
import sys
import tempfile
import textwrap

from setuptools import Command, find_packages, setup
from setuptools.command.build_py import build_py

NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "horovod_tpu", "native")
NATIVE_SOURCES = ["wire.cc", "coordinator.cc", "handle_manager.cc",
                  "timeline.cc"]
NATIVE_TARGET = "libhvdtpu.so"


def _check_output(cmd, **kw):
    return subprocess.run(cmd, check=True, capture_output=True, **kw)


def probe_cxx_flags(cxx="g++"):
    """Find a working flag set with a test compile (≙ reference
    setup.py:63-87 get_cpp_flags)."""
    base = ["-O3", "-fPIC", "-shared", "-pthread"]
    candidates = [["-std=c++17"], ["-std=c++14"]]
    src = textwrap.dedent("""
        #include <unordered_map>
        #include <mutex>
        int main() { std::unordered_map<int, int> m; m[1] = 2; return 0; }
    """)
    with tempfile.TemporaryDirectory() as td:
        cc = os.path.join(td, "probe.cc")
        with open(cc, "w") as f:
            f.write(src)
        for extra in candidates:
            try:
                _check_output([cxx, *base, *extra, cc, "-o",
                               os.path.join(td, "probe.so")])
                return base + extra
            except Exception:
                continue
    raise RuntimeError(
        "could not find working C++ compile flags; set "
        "HOROVOD_TPU_WITHOUT_NATIVE=1 to skip the native library")


def build_native():
    cxx = os.environ.get("CXX", "g++")
    flags = probe_cxx_flags(cxx)
    out = os.path.join(NATIVE_DIR, NATIVE_TARGET)
    srcs = [os.path.join(NATIVE_DIR, s) for s in NATIVE_SOURCES]
    print(f"building {NATIVE_TARGET}: {cxx} {' '.join(flags)}")
    _check_output([cxx, *flags, *srcs, "-o", out])
    return out


class build_py_with_native(build_py):
    """Compile the native runtime alongside the Python sources."""

    def run(self):
        if os.environ.get("HOROVOD_TPU_WITHOUT_NATIVE"):
            print("HOROVOD_TPU_WITHOUT_NATIVE set - skipping native "
                  "runtime (pure-Python fallbacks will be used)")
        else:
            try:
                build_native()
            except Exception as e:
                if os.environ.get("HOROVOD_TPU_WITH_NATIVE"):
                    raise RuntimeError(
                        f"native runtime build failed and "
                        f"HOROVOD_TPU_WITH_NATIVE is set: {e}") from e
                print(f"warning: native runtime build failed ({e}); "
                      f"falling back to pure-Python runtime",
                      file=sys.stderr)
        super().run()


class build_native_cmd(Command):
    """`python setup.py build_native` - just the .so."""

    description = "build the native runtime library"
    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        build_native()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description=("TPU-native distributed training framework with the "
                 "capabilities of Horovod: named collectives, "
                 "DistributedOptimizer, tensor fusion, timeline, plus "
                 "dp/tp/sp/pp/ep parallelism over JAX/XLA/Pallas"),
    packages=find_packages(include=["horovod_tpu", "horovod_tpu.*"]),
    package_data={"horovod_tpu.native": ["*.cc", "*.h", "Makefile",
                                         "libhvdtpu.so"]},
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
    extras_require={
        "models": ["flax", "optax"],
        "torch": ["torch>=2.1"],
        "test": ["pytest"],
    },
    cmdclass={"build_py": build_py_with_native,
              "build_native": build_native_cmd},
)
