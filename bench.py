"""Benchmark: ResNet-50 data-parallel training throughput (images/sec/chip).

Mirrors the reference's headline benchmark — ResNet training throughput
with synthetic ImageNet data via tf_cnn_benchmarks
(docs/benchmarks.md:22-40): ResNet-101, batch 64/GPU on 16 Pascal GPUs
reached 1656.82 images/sec total = 103.55 images/sec/GPU.  That per-chip
number is the ``vs_baseline`` denominator here.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": N}

Usage:
  python bench.py            # full run (real TPU; batch 128, ~2 min)
  python bench.py --smoke    # tiny shapes (CPU-friendly sanity check)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp

# Reference: 1656.82 images/sec on 16 GPUs (docs/benchmarks.md:22-40).
BASELINE_IMAGES_PER_SEC_PER_CHIP = 1656.82 / 16


def run(batch_size: int, image_size: int, warmup: int, iters: int,
        model_ctor=None, num_classes: int = 1000) -> float:
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import resnet as R
    from horovod_tpu.parallel.training import (make_train_step_with_state,
                                               shard_batch)

    hvd.init()
    n_chips = hvd.size()
    model = (model_ctor or R.ResNet50)(num_classes=num_classes)
    params, stats = R.init_resnet(model, image_size=image_size,
                                  batch_size=batch_size)
    params = hvd.broadcast_parameters(params, root_rank=0)

    # The reference benchmark recipe: SGD with momentum, synthetic data
    # (docs/benchmarks.md:28-33).
    opt = optax.sgd(0.1, momentum=0.9)
    loss_fn = R.resnet_loss_fn(model)
    step = make_train_step_with_state(loss_fn, opt)

    global_batch = batch_size * n_chips
    images, labels = R.synthetic_imagenet(global_batch,
                                          image_size=image_size,
                                          num_classes=num_classes)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    opt_state = opt.init(params)

    for _ in range(warmup):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              batch)
    # Host-fetch the loss as the completion barrier.  On the tunneled
    # `axon` TPU backend block_until_ready() acknowledges dispatch, not
    # completion (measured: chained 8192^3 bf16 matmuls "run" at 13.5
    # PFLOP/s under block_until_ready vs 92 TFLOP/s — physically
    # plausible — with a host fetch).  The scalar transfer itself is
    # negligible.
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              batch)
    float(loss)
    dt = time.perf_counter() - t0

    images_per_sec_total = global_batch * iters / dt
    return images_per_sec_total / n_chips


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CPU sanity checks")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    args = ap.parse_args()

    if args.smoke:
        from horovod_tpu.models.resnet import ResNet18Thin

        value = run(batch_size=8, image_size=32, warmup=1, iters=3,
                    model_ctor=ResNet18Thin, num_classes=16)
    else:
        value = run(batch_size=args.batch_size, image_size=args.image_size,
                    warmup=args.warmup, iters=args.iters)

    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
