"""Benchmark: ResNet-50 data-parallel training throughput (images/sec/chip).

Mirrors the reference's headline benchmark — ResNet training throughput
with synthetic ImageNet data via tf_cnn_benchmarks
(docs/benchmarks.md:22-40): ResNet-101, batch 64/GPU on 16 Pascal GPUs
reached 1656.82 images/sec total = 103.55 images/sec/GPU.  That per-chip
number is the ``vs_baseline`` denominator here.

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": N, "mfu": N, ...}
On persistent failure (e.g. the TPU tunnel is down) it still prints one
structured JSON line with an ``error`` field instead of a traceback.

MFU ceiling analysis (v5e, measured 2026-07, round 3):
  * Pure chained 8192^3 bf16 matmuls on this chip/tunnel: 177.8 TFLOP/s
    = 90% of the 197 TFLOP/s bf16 peak, so the environment itself is not
    the cap.
  * The ResNet-50 train step delivers ~60 TFLOP/s (XLA cost analysis) =
    30% of peak / 34% of the achievable matmul rate.  Batch sweep
    (64/128/192/256/512 → 2163/2528/2325/2493/2360 img/s) puts the
    optimum at 128.  The residual gap is ResNet's structural profile on
    MXU-class hardware: the 3-input-channel stem conv cannot fill the
    128-lane systolic array, early layers have small channel depths, and
    BN + elementwise chains are HBM-bound — consistent with the 30-40%
    MFU commonly reported for ResNet-50 training on TPUs.

Supervision (round 4, hardened round 5): the parent enforces a TOTAL
wall-clock budget (``HVD_TPU_BENCH_TOTAL_BUDGET``, default 1500 s) sized
to fit inside the driver's outer timeout, so a dead TPU tunnel produces
the structured failure JSON instead of rc=124.  The tunnel probe (tiny
jitted matmul in a SIGKILL-able child) RETRIES with backoff for up to
~55% of the budget — the tunnel's observed outages recover on the scale
of minutes, and round 4 lost its number to a single 75 s probe.  Every
probe/measurement event is recorded in ``attempt_log`` in the final
JSON, success or failure.  Children share a persistent XLA compilation
cache (``.jax_cache/``) so retries skip recompilation.  On success it
also runs an eager-path smoke on the real chip
(allreduce/allgather/broadcast + a torch-frontend in-place round trip)
and attaches ``eager_tpu_smoke`` to the JSON.

Control-plane microbenchmark (round 6): ``--mode control`` measures
negotiations/sec through the real coordinator facade + response cache
(ops/cache.py) for a 64-tensor synthetic program, cache off vs on —
pure host-side control plane, no XLA and no TPU tunnel, so this number
exists even in rounds where the tunnel takes the headline metric down
(BENCH_r01–r05 all recorded null for exactly that reason).  The default
TPU run attaches the same measurement as ``control_plane`` in its JSON,
success or failure, and ``--check-speedup X`` makes the control mode
exit nonzero when cache-on/cache-off < X (the CI gate).

Usage:
  python bench.py                 # full run (real TPU; batch 128, ~2 min)
  python bench.py --smoke         # tiny shapes (CPU-friendly sanity check)
  python bench.py --mode control  # control-plane negotiations/sec only
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

# Reference: 1656.82 images/sec on 16 GPUs (docs/benchmarks.md:22-40).
BASELINE_IMAGES_PER_SEC_PER_CHIP = 1656.82 / 16

# Peak dense bf16 FLOP/s per chip, for the MFU estimate.  Keyed by the
# substring jax reports in device_kind / the PALLAS_AXON_TPU_GEN env var.
PEAK_BF16_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# Analytic fallback when the compiled cost analysis is unavailable (e.g.
# remote-compile backends): ResNet-50 fwd at 224x224 is ~4.09 GFLOP/image
# (2 FLOPs/MAC); fwd+bwd ~= 3x fwd.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.09e9


def _chip_peak_flops() -> float | None:
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform == "cpu":
            return None  # MFU vs a TPU peak is meaningless on CPU
        kind = dev.device_kind.lower()
    except Exception:
        return None
    for key, peak in PEAK_BF16_FLOPS.items():
        if key in kind:
            return peak
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    for key, peak in PEAK_BF16_FLOPS.items():
        if key in gen:
            return peak
    return None


def _cost_analysis_flops(compiled) -> float | None:
    """Per-chip per-step FLOPs from XLA's cost analysis, if exposed.

    ``cost_analysis()`` reads the SPMD-partitioned per-device HLO module,
    so the number is already per-chip — do NOT divide by n_chips again.
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def run(batch_size: int, image_size: int, warmup: int, iters: int,
        model_ctor=None, num_classes: int = 1000) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import resnet as R
    from horovod_tpu.parallel.training import (make_train_step_with_state,
                                               shard_batch)

    hvd.init()
    n_chips = hvd.size()
    model = (model_ctor or R.ResNet50)(num_classes=num_classes)
    params, stats = R.init_resnet(model, image_size=image_size,
                                  batch_size=batch_size)
    params = hvd.broadcast_parameters(params, root_rank=0)

    # The reference benchmark recipe: SGD with momentum, synthetic data
    # (docs/benchmarks.md:28-33).
    opt = optax.sgd(0.1, momentum=0.9)
    loss_fn = R.resnet_loss_fn(model)
    step = make_train_step_with_state(loss_fn, opt)

    global_batch = batch_size * n_chips
    images, labels = R.synthetic_imagenet(global_batch,
                                          image_size=image_size,
                                          num_classes=num_classes)
    batch = shard_batch((jnp.asarray(images), jnp.asarray(labels)))
    opt_state = opt.init(params)

    # AOT-compile once and reuse the executable for both the cost analysis
    # and the run loops (jit's dispatch cache is not shared with .lower()).
    step = step.lower(params, stats, opt_state, batch).compile()
    flops_per_chip_step = _cost_analysis_flops(step)

    for _ in range(warmup):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              batch)
    # Host-fetch the loss as the completion barrier.  On the tunneled
    # `axon` TPU backend block_until_ready() acknowledges dispatch, not
    # completion (measured: chained 8192^3 bf16 matmuls "run" at 13.5
    # PFLOP/s under block_until_ready vs 92 TFLOP/s — physically
    # plausible — with a host fetch).  The scalar transfer itself is
    # negligible.
    float(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        params, stats, opt_state, loss = step(params, stats, opt_state,
                                              batch)
    float(loss)
    dt = time.perf_counter() - t0

    images_per_sec_total = global_batch * iters / dt
    result = {"value": images_per_sec_total / n_chips, "n_chips": n_chips}

    if flops_per_chip_step is not None:
        result["flops_source"] = "xla_cost_analysis"
    elif image_size == 224:
        flops_per_chip_step = RESNET50_TRAIN_FLOPS_PER_IMAGE * batch_size
        result["flops_source"] = "analytic"

    peak = _chip_peak_flops()
    if flops_per_chip_step is not None:
        delivered = flops_per_chip_step * iters / dt
        result["tflops_per_chip"] = round(delivered / 1e12, 2)
        if peak:
            result["mfu"] = round(delivered / peak, 4)
    return result


def _control_bench(tensors: int = 64, ranks: int = 4,
                   seconds: float = 1.0) -> dict:
    """Negotiations/sec through the real control plane, cache off vs on.

    Models the rank-0 controller's steady-state tick for a 64-tensor
    program (the multi-process hot path of ops/collective._drain +
    ops/transport._handle_request_batch): rank 0's own requests go
    through the Coordinator facade; the workers' arrivals are, cache
    OFF, wire-parsed full requests fed to submit (table accumulation +
    validation + response construction + fusion planning) and, cache
    ON, decoded bit-vector hits fed to ``hit_from_wire`` followed by
    the memoized-plan replay — exactly what each tick costs on the
    production code path.
    """
    from horovod_tpu import trace as _hvd_trace
    from horovod_tpu.ops import cache as hvd_cache
    from horovod_tpu.ops import wire
    from horovod_tpu.ops.coordinator import Coordinator

    threshold = 64 << 20

    def request_of(t: int, r: int) -> "wire.Request":
        return wire.Request(
            request_rank=r, request_type=wire.RequestType.ALLREDUCE,
            tensor_type=wire.DataType.FLOAT32, tensor_name=f"grad.{t}",
            tensor_shape=(1024,), reduce_op=wire.ReduceOp.SUM)

    # The workers' frames as they sit in the receive buffer: packed wire
    # bytes (parsing them is part of the cache-off cost, exactly as in
    # transport._serve).
    packed = [[request_of(t, r).pack() for r in range(1, ranks)]
              for t in range(tensors)]

    def drain(coord, cache) -> int:
        # Mirrors collective._drain's per-tick hvd-trace work (cycle
        # advance + negotiate span + the 16-byte context trailer) so
        # the trace on/off A/B below prices the span layer on the same
        # path production ticks pay it.
        t0 = time.monotonic() if _hvd_trace.enabled() else 0.0
        resps = []
        if cache is not None:
            marker = cache.take_flush_marker()
            if marker is not None:
                resps.append(marker)
            replayed, _g, _e, _c = cache.take_ready(lambda psid: threshold)
            resps += replayed
        resps += coord.poll_responses({})
        if cache is not None:
            for resp in resps:
                cache.observe_response(resp)
        if resps and _hvd_trace.enabled():
            _hvd_trace.next_cycle()
            _hvd_trace.span("negotiate.tick", "negotiate", t0,
                            time.monotonic(),
                            args={"responses": len(resps)})
            _hvd_trace.pack_ctx()
        return sum(len(r.tensor_names) for r in resps
                   if r.response_type == wire.ResponseType.ALLREDUCE)

    def measure(cache_on: bool):
        cache = hvd_cache.ResponseCache(rank=0) if cache_on else None
        coord = Coordinator(size=ranks, fusion_threshold=threshold,
                            cache=cache)

        # Warmup cycle = the first (cold) negotiation; populates the
        # cache and yields the entry indices the workers' bits name.
        for t in range(tensors):
            coord.submit(request_of(t, 0))
            for buf in packed[t]:
                req, _ = wire.Request.unpack(buf)
                coord.submit(req)
        n = drain(coord, cache)
        assert n == tensors, (n, tensors)
        idxs = None
        if cache is not None:
            idxs = [cache.entry_index(f"grad.{t}") for t in range(tensors)]
            assert all(i is not None for i in idxs), idxs
            epoch = cache.epoch

        def one_cycle() -> int:
            if cache is None:
                for t in range(tensors):
                    coord.submit(request_of(t, 0))
                    for buf in packed[t]:
                        req, _ = wire.Request.unpack(buf)
                        coord.submit(req)
            else:
                for t in range(tensors):
                    coord.submit(request_of(t, 0))
                    for r in range(1, ranks):
                        down = cache.hit_from_wire(idxs[t], r, epoch)
                        assert down is None, down
            return drain(coord, cache)

        done = 0
        cycles = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            got = one_cycle()
            assert got == tensors, (got, tensors)
            done += got
            cycles += 1
        dt = time.perf_counter() - t0
        if cache is not None:
            s = cache.stats
            assert s.replayed_tensors >= done, \
                ("cache-on run must serve from replay", s)
        coord.close()
        return done / dt, cycles

    off_rate, off_cycles = measure(cache_on=False)
    on_rate, on_cycles = measure(cache_on=True)

    # Telemetry overhead A/B (the hvd-telemetry acceptance gate,
    # docs/metrics.md): the SAME steady-state measurement with the
    # whole subsystem (registry + flight recorder) disabled.  Recorded
    # in the JSON — ≤ 5 % regression is the contract; the boolean is
    # informational (a loaded box can fake either direction).
    from horovod_tpu import telemetry as _telemetry

    was_enabled = _telemetry.enabled()
    _telemetry.set_enabled(False)
    try:
        notel_on_rate, _ = measure(cache_on=True)
        notel_off_rate, _ = measure(cache_on=False)
    finally:
        _telemetry.set_enabled(was_enabled)

    def overhead_pct(with_tel, without_tel):
        if not without_tel:
            return None
        return round((1.0 - with_tel / without_tel) * 100.0, 2)

    tel_pct = overhead_pct(on_rate, notel_on_rate)

    # hvd-trace overhead A/B (same contract as telemetry's): the same
    # steady-state measurement with span recording disabled.  The
    # baseline legs above ran with tracing at its default (on), so
    # trace-off minus trace-on is the span layer's whole cost.
    trace_was = _hvd_trace.enabled()
    _hvd_trace.set_enabled(False)
    try:
        notrace_on_rate, _ = measure(cache_on=True)
    finally:
        _hvd_trace.set_enabled(trace_was)
    trace_pct = overhead_pct(on_rate, notrace_on_rate)

    tel_counters = {
        name: m.get("value")
        for name, m in _telemetry.metrics().items()
        if m.get("type") in ("counter", "gauge")
    }
    return {
        "metric": "control_plane_negotiations_per_sec",
        "value": round(on_rate, 1),
        "unit": "negotiations/sec",
        "cache_on": round(on_rate, 1),
        "cache_off": round(off_rate, 1),
        "speedup": round(on_rate / off_rate, 2) if off_rate else None,
        "vs_baseline": round(on_rate / off_rate, 2) if off_rate else None,
        "tensors": tensors,
        "ranks": ranks,
        "cycles": {"cache_on": on_cycles, "cache_off": off_cycles},
        "telemetry": {
            "cache_on_metrics_on": round(on_rate, 1),
            "cache_on_metrics_off": round(notel_on_rate, 1),
            "cache_off_metrics_on": round(off_rate, 1),
            "cache_off_metrics_off": round(notel_off_rate, 1),
            "overhead_pct": tel_pct,
            "overhead_off_pct": overhead_pct(off_rate, notel_off_rate),
            "overhead_ok": tel_pct is not None and tel_pct <= 5.0,
            "counters": tel_counters,
        },
        "trace": {
            "trace_on": round(on_rate, 1),
            "trace_off": round(notrace_on_rate, 1),
            "overhead_pct": trace_pct,
            "overhead_ok": trace_pct is not None and trace_pct <= 5.0,
        },
    }


def _tree_bench(tensors: int = 16, seconds: float = 0.4) -> dict:
    """Tree-overlay section of ``--mode control``: rank-0 received
    control frames per steady-state negotiation cycle (and per
    metrics/trace pull) at simulated world sizes 64/256/1024, plus the
    root's merged-envelope processing rate.

    Virtual-slice-style dryrun, no XLA and no sockets: the layouts and
    per-child envelopes come from the REAL aggregation code
    (ops/tree.steady_envelope — the same grouping the live interiors
    run), and the root side runs the REAL ResponseCache accounting +
    fused replay per envelope section.  The frame counts are the
    structural quantity the CI gate bounds: rank 0 receives one merged
    envelope per direct child instead of world-1 per-rank frames."""
    import math

    from horovod_tpu.ops import cache as hvd_cache
    from horovod_tpu.ops import tree as hvd_tree
    from horovod_tpu.ops import wire

    # Pinned, not read from HVD_TPU_TREE_FANOUT: the gate's bound and
    # the contract test's flat-vs-tree ratio assume this shape, and an
    # ambient env setting must not fail the bench without a code
    # defect (tests/test_tree.py pins the same way).
    fanout = 8
    threshold = 64 << 20

    def request_of(t: int, r: int) -> "wire.Request":
        return wire.Request(
            request_rank=r, request_type=wire.RequestType.ALLREDUCE,
            tensor_type=wire.DataType.FLOAT32, tensor_name=f"grad.{t}",
            tensor_shape=(1024,), reduce_op=wire.ReduceOp.SUM)

    worlds = []
    for world in (64, 256, 1024):
        layout = hvd_tree.build_layout(world, fanout)
        cache = hvd_cache.ResponseCache(rank=0)
        for t in range(tensors):
            name = f"grad.{t}"
            cache.stage_negotiated(
                name, {r: request_of(t, r) for r in range(world)})
            cache.observe_response(wire.Response(
                wire.ResponseType.ALLREDUCE, tensor_names=[name],
                tensor_shapes=[(1024,)],
                tensor_type=wire.DataType.FLOAT32))
        epoch = cache.epoch
        idxs = list(range(tensors))
        envelopes = [hvd_tree.steady_envelope(layout, c, epoch, idxs)
                     for c in layout.children(0)]

        def one_cycle() -> int:
            for i in idxs:  # rank 0's own hits
                cache.hit_from_wire(i, 0, epoch)
            for env in envelopes:
                for sec in hvd_tree.iter_subtree_sections(env):
                    if sec[0] == "bits":
                        _k, ep, ranks, ii = sec
                        for r in ranks:
                            for i in ii:
                                cache.hit_from_wire(i, r, ep)
            resps, _g, _e, _c = cache.take_ready(lambda _p: threshold)
            for r in resps:
                cache.observe_response(r, replay=True)
            return sum(len(r.tensor_names) for r in resps)

        got = one_cycle()
        assert got == tensors, (got, tensors)
        done = 0
        cycles = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            done += one_cycle()
            cycles += 1
        dt = time.perf_counter() - t0
        # Structural frame accounting comes from the one shared
        # implementation (ops/tree.simulate_cycle_frames) — the bench
        # adds only the measured processing rate and the gate bound.
        stats = hvd_tree.simulate_cycle_frames(world, fanout)
        stats["fanout_log_bound"] = fanout * max(1, math.ceil(
            math.log(world, max(2, fanout))))
        stats["negotiations_per_sec"] = round(done / dt, 1)
        stats["cycles"] = cycles
        worlds.append(stats)
    return {
        "metric": "tree_root_frames_per_cycle",
        "fanout": fanout,
        "tensors": tensors,
        "worlds": worlds,
    }


def _dataplane_bench(tensors: int = 32, elems: int = 256,
                     cycles: int = 30) -> dict:
    """Steady-state fused-cycle latency + dispatches/cycle, eager
    per-tensor executor vs megakernel (``--mode dataplane``).

    Runs the REAL dynamic path end to end on the 8-virtual-CPU-device
    mesh (same trick as tests/conftest.py, no TPU tunnel): a
    ``tensors``-wide AVERAGE allreduce program with stable names, so
    after the cold cycle every cycle is a response-cache replay whose
    fusion plan is memoized — the steady state of a training loop.  The
    eager leg (HVD_TPU_MEGAKERNEL=0) surrounds each fused response with
    the per-tensor pack/slice/divide choreography; the megakernel leg
    launches one donated pack→reduce→unpack executable per fusion group
    (ops/megakernel.py).  Dispatches/cycle are REAL XLA executable
    launches counted at jax's dispatch choke point
    (utils/xla_dispatch.py).  The same run proves the two legs bitwise
    identical and the hierarchical ICI×DCN kernel (2 virtual slices)
    equivalent to the flat psum — the dataplane perf contract of
    docs/performance.md.
    """
    import numpy as np

    os.environ["HVD_TPU_COUNT_DISPATCHES"] = "1"
    # Pin the default compressor: the base legs' bitwise-identity and
    # hierarchical-equivalence gates are contracts of the UNCOMPRESSED
    # path; the quantized codecs get their own measured legs below.
    os.environ["HVD_TPU_COMPRESSION"] = "none"
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd
    from horovod_tpu.ops import megakernel as mk
    from horovod_tpu.utils import xla_dispatch

    hvd.init(devices=jax.devices())
    try:
        n = hvd.size()
        rng = np.random.default_rng(7)
        # Integer-valued floats: exact under any reduction order, so the
        # hierarchical leg can be compared bitwise, not just allclose.
        base = [rng.integers(-8, 8, size=(n, elems)).astype(np.float32)
                for _ in range(tensors)]
        inputs = [hvd.shard(t) for t in base]

        def cycle(tag):
            # quiesce: the background drain tick must not fire between
            # two submissions of one cycle — it would negotiate them as
            # two fused responses and break every ==1-launch contract
            # below.  One explicit drain on exit serves the whole group.
            with hvd.quiesce():
                hs = [hvd.allreduce_async(x, average=True,
                                          name=f"{tag}.{j}")
                      for j, x in enumerate(inputs)]
            return [hvd.synchronize(h) for h in hs]

        def measure(tag, mega):
            mk.set_enabled(mega)
            cycle(tag)   # cold: compile + populate the response cache
            cycle(tag)   # warm: replayed negotiation, memoized plan
            launches0 = mk.stats.launches
            # Dispatch counting needs every launch visible — the
            # exact_scope disables jax's C++ fastpath while counting
            # (measurement-only; the latency loop below runs outside
            # it, at full dispatch speed on both legs).
            with xla_dispatch.exact_scope():
                with xla_dispatch.record(all_threads=True) as scope:
                    results = cycle(tag)
            groups = mk.stats.launches - launches0
            cycle(tag)   # re-warm the fastpath after the exact window
            lats = []
            for _ in range(cycles):
                t0 = time.perf_counter()
                cycle(tag)
                lats.append(time.perf_counter() - t0)
            # Median, not mean: this is a shared box (CI runner, the
            # 1-core dev container) and a single background spike in
            # one leg would otherwise fake — or mask — a regression.
            lats.sort()
            return results, scope.count, lats[len(lats) // 2], groups

        eager_res, eager_disp, eager_lat, _ = measure("eager", False)
        mega_res, mega_disp, mega_lat, groups = measure("mega", True)
        identical = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(eager_res, mega_res))

        # Hierarchical ICI×DCN verification: declare 2 virtual slices on
        # the dryrun mesh and compare against the flat-psum results.
        os.environ["HVD_TPU_HIERARCHICAL"] = "on"
        os.environ["HVD_TPU_VIRTUAL_SLICES"] = "2"
        try:
            hier0 = mk.stats.hier_launches
            hier_res = cycle("hier")
            hier_ran = mk.stats.hier_launches > hier0
            hier_equal = hier_ran and all(
                np.asarray(a).tobytes() == np.asarray(b).tobytes()
                for a, b in zip(eager_res, hier_res))
        finally:
            del os.environ["HVD_TPU_HIERARCHICAL"]
            del os.environ["HVD_TPU_VIRTUAL_SLICES"]

        # Bytes-on-wire accounting + quantized-reduction legs (ISSUE 6):
        # per compressor, the steady-state cycle latency, REAL
        # dispatches/cycle (the quantize→exchange→dequantize pipeline
        # must stay inside the one fused executable), logical vs wire
        # bytes per cycle from the executor's accounting, and — for the
        # int codecs — equality against the eager-quantized REFERENCE
        # (ops/compression.reference_allreduce) at tick 0.
        from horovod_tpu.ops import compression as _compression

        rows = np.concatenate([t.reshape(n, -1) for t in base], axis=1)
        compression_section = {}
        none_lat = None
        for comp_name in ("none", "int8", "int4"):
            hvd.set_compression(default=comp_name)  # flushes exec state
            ref_equal = None
            if comp_name != "none":
                # Fresh names → tick 0, zero residuals: the reference
                # must match the fused kernel BITWISE.  The reference
                # models single-group packing; cycle() quiesces the
                # drain tick, so the cycle lands in exactly one launch
                # deterministically — no retry loop needed.
                got = cycle(f"refq.{comp_name}")
                fmt = _compression.wire_format(comp_name)
                ref, _ = _compression.reference_allreduce(rows, fmt, 0)
                expected = np.asarray(jnp.asarray(ref) / n)  # AVERAGE
                got_flat = np.concatenate(
                    [np.asarray(r)[0].reshape(-1) for r in got])
                ref_equal = bool(
                    expected.tobytes() == got_flat.tobytes())
            _, disp_c, lat_c, grp = measure(f"comp.{comp_name}", True)
            if comp_name == "none":
                # The ADJACENT uncompressed measurement is the
                # throughput baseline — comparing against a leg timed
                # minutes earlier folds the shared box's load drift
                # into the ratio.
                none_lat = lat_c
            w0 = mk.stats.wire_bytes
            l0 = mk.stats.logical_bytes
            cycle(f"comp.{comp_name}")
            wire_b = mk.stats.wire_bytes - w0
            logical_b = mk.stats.logical_bytes - l0
            compression_section[comp_name] = {
                "cycle_us": round(lat_c * 1e6, 1),
                "speedup_vs_uncompressed":
                    round(none_lat / lat_c, 2) if lat_c else None,
                "dispatches_per_cycle": disp_c,
                "logical_bytes_per_cycle": logical_b,
                "wire_bytes_per_cycle": wire_b,
                "compression_ratio":
                    round(logical_b / wire_b, 2) if wire_b else None,
                "reference_equal": ref_equal,
            }
        hvd.set_compression()  # restore the (pinned-none) env default

        # hvd-mem: measured ledger high-watermark of one steady-state
        # fused cycle vs the static planner's prediction (the ±15 %
        # accuracy contract of docs/memory.md; --mode memory owns the
        # CI gate, this section records the figures per round).
        # cycle() quiesces the drain tick, so the watermark always
        # observes a single-launch cycle.
        from horovod_tpu.memory import ledger as _mem_ledger
        from horovod_tpu.memory import planner as _mem_planner

        led = _mem_ledger.ledger
        led.reset()
        cycle("memsec")
        mem_measured = led.watermark()
        mem_predicted = _mem_planner.plan_dataplane(
            tensors, elems, n).framework_bytes
        mem_err_pct = (round(abs(mem_predicted - mem_measured)
                             / mem_measured * 100.0, 2)
                       if mem_measured else None)
        led.reset()

        # Telemetry overhead A/B on the megakernel leg (same contract
        # as --mode control: the hvd-telemetry acceptance gate rides
        # the bench JSON).  The executor instrumentation is per
        # fused-response, so the expected delta is noise-level.
        from horovod_tpu import telemetry as _telemetry

        was_enabled = _telemetry.enabled()
        _telemetry.set_enabled(False)
        try:
            _, _, mega_lat_notel, _ = measure("notel", True)
        finally:
            _telemetry.set_enabled(was_enabled)
            mk.set_enabled(None)
        tel_pct = (round((mega_lat / mega_lat_notel - 1.0) * 100.0, 2)
                   if mega_lat_notel else None)

        # hvd-trace overhead A/B on the same leg: the launch + dispatch
        # spans are per fused response, so the expected delta is
        # noise-level too (the ≤ 5 % gate of docs/tracing.md).
        from horovod_tpu import trace as _hvd_trace

        trace_was = _hvd_trace.enabled()
        _hvd_trace.set_enabled(False)
        try:
            _, _, mega_lat_notrace, _ = measure("notrace", True)
        finally:
            _hvd_trace.set_enabled(trace_was)
            mk.set_enabled(None)
        trace_pct = (round((mega_lat / mega_lat_notrace - 1.0) * 100.0,
                           2) if mega_lat_notrace else None)
        snap = _telemetry.metrics()
        tel_counters = {
            name: m.get("value") for name, m in snap.items()
            if name.startswith(("megakernel.", "collective.", "cache.",
                                "compression."))
            and m.get("type") in ("counter", "gauge")
        }

        reduction = (eager_disp / mega_disp) if mega_disp else None
        return {
            "metric": "dataplane_fused_cycle_latency_us",
            "value": round(mega_lat * 1e6, 1),
            "unit": "us/cycle",
            "eager_us": round(eager_lat * 1e6, 1),
            "megakernel_us": round(mega_lat * 1e6, 1),
            "speedup": round(eager_lat / mega_lat, 2) if mega_lat else None,
            "vs_baseline": round(eager_lat / mega_lat, 2) if mega_lat
            else None,
            "dispatches_per_cycle": {"eager": eager_disp,
                                     "megakernel": mega_disp},
            "dispatch_reduction": round(reduction, 1)
            if reduction else None,
            "fusion_groups_per_cycle": groups,
            "bitwise_identical": identical,
            "hierarchical_equal": hier_equal,
            "compression": compression_section,
            # hvd-mem (docs/memory.md): the ledger's measured peak vs
            # the planner's prediction, plus the ledger's share of the
            # telemetry on/off overhead (the accounting sites gate on
            # telemetry.enabled(), so tel_pct measures them too — the
            # ≤5 % acceptance rides the same A/B).
            "memory": {
                "ledger_peak_bytes": mem_measured,
                "planner_predicted_bytes": mem_predicted,
                "prediction_error_pct": mem_err_pct,
                "prediction_ok": mem_err_pct is not None
                and mem_err_pct <= 15.0,
                "ledger_overhead_pct": tel_pct,
                "ledger_overhead_ok": tel_pct is not None
                and tel_pct <= 5.0,
            },
            "tensors": tensors,
            "elems": elems,
            "replicas": n,
            "telemetry": {
                "megakernel_us_metrics_on": round(mega_lat * 1e6, 1),
                "megakernel_us_metrics_off": round(
                    mega_lat_notel * 1e6, 1),
                "overhead_pct": tel_pct,
                "overhead_ok": tel_pct is not None and tel_pct <= 5.0,
                "counters": tel_counters,
            },
            "trace": {
                "megakernel_us_trace_on": round(mega_lat * 1e6, 1),
                "megakernel_us_trace_off": round(
                    mega_lat_notrace * 1e6, 1),
                "overhead_pct": trace_pct,
                "overhead_ok": trace_pct is not None
                and trace_pct <= 5.0,
            },
        }
    finally:
        hvd.shutdown()


def _input_bench(steps: int = 40, batch: int = 64, dim: int = 512,
                 delay_ms: float = 0.0) -> dict:
    """Input-pipeline microbench (``--mode input``): steps/sec with a
    synthetic SLOW host loader, host-overlap off vs on.

    The off leg is the classic synchronous loop — per-step
    ``shard_batch(next(loader))`` plus a per-step ``float(loss)`` fetch
    (the accidental-synchronization pattern PR 5's audit removes); the
    on leg is the hvd-pipeline steady state — ``prefetch_to_device``
    double buffering plus deferred fetches with one ``barrier_fence()``
    at the end.  The loader's delay is auto-calibrated to the measured
    step time (the worst case for a non-overlapped loop: host work ≈
    device work, so overlap is worth ~2x), unless ``delay_ms`` pins it.
    Both legs consume the identical deterministic batch sequence from
    the same initial params; the final parameters must be BITWISE
    identical — prefetch and async dispatch reorder host work, never
    arithmetic.  CPU-only like ``--mode control``: no XLA collectives
    beyond the 8-virtual-device mesh, no TPU tunnel.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.parallel.input import prefetch_to_device
    from horovod_tpu.parallel.training import (barrier_fence,
                                               make_train_step, shard_batch)

    hvd.init(devices=jax.devices())
    try:
        n = hvd.size()
        gbatch = batch * n

        def loss_fn(params, b):
            x, y = b
            h = jnp.tanh(x @ params["w1"])
            return jnp.mean((h @ params["w2"] - y) ** 2)

        rng = np.random.default_rng(11)
        params0 = {
            "w1": jnp.asarray(rng.normal(0, 0.05, (dim, dim)), jnp.float32),
            "w2": jnp.asarray(rng.normal(0, 0.05, (dim, 1)), jnp.float32),
        }
        opt = optax.sgd(0.01)
        step = make_train_step(loss_fn, opt, donate=False)

        # Precomputed deterministic batches: the loader's cost is then
        # EXACTLY the synthetic delay (decode/augment stand-in), not
        # delay + RNG jitter — which would blur the calibration below.
        data = []
        for i in range(steps):
            r = np.random.default_rng(1000 + i)
            data.append((r.normal(size=(gbatch, dim)).astype(np.float32),
                         r.normal(size=(gbatch, 1)).astype(np.float32)))

        def host_batches(delay_s: float):
            for b in data:
                if delay_s:
                    time.sleep(delay_s)
                yield b

        # Warmup/compile, then calibrate the synchronous per-step cost
        # (shard + step + fetch) over a steady-state window.  The loader
        # delay is pinned to it: host work ≈ device work is the worst
        # case for a non-overlapped loop and the honest one for the
        # overlap claim (a much slower loader would be loader-bound
        # either way; a much faster one hides in async dispatch alone).
        params, opt_state = params0, opt.init(params0)
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state,
                                           shard_batch(data[0]))
            float(loss)
        samples = []
        for i in range(11):
            t0 = time.perf_counter()
            params, opt_state, loss = step(params, opt_state,
                                           shard_batch(data[i % steps]))
            float(loss)
            samples.append(time.perf_counter() - t0)
        # Median, not mean: one background spike during calibration
        # would skew the loader delay.  The delay is pinned slightly
        # ABOVE the step time (1.4x): the overlapped leg then stays
        # producer-bound — its sleep absorbs host/XLA core contention —
        # while the synchronous leg still pays delay + step serially.
        # (Below ~1x the on-leg goes consumer-bound and, on a small-core
        # box, stager/step contention eats the win; far above it the
        # ratio (delay+step)/(delay+transfer) decays toward 1.)
        samples.sort()
        step_s = samples[len(samples) // 2]
        # Cap high enough that 1.4x holds up to ~180 ms steps (a badly
        # loaded CI box); a lower cap would silently break the
        # delay > step invariant and fail the 1.3x gate with no defect.
        delay_s = (delay_ms / 1e3) if delay_ms else min(
            max(1.4 * step_s, 0.002), 0.25)

        def run_off():
            params, opt_state = params0, opt.init(params0)
            t0 = time.perf_counter()
            for b in host_batches(delay_s):
                params, opt_state, loss = step(params, opt_state,
                                               shard_batch(b))
                float(loss)  # the per-step sync under audit
            return params, time.perf_counter() - t0

        def run_on():
            params, opt_state = params0, opt.init(params0)
            t0 = time.perf_counter()
            with prefetch_to_device(host_batches(delay_s),
                                    depth=2) as staged:
                for b in staged:
                    params, opt_state, loss = step(params, opt_state, b)
            barrier_fence(params, loss)
            return params, time.perf_counter() - t0

        # on first, off second: if background load creeps up over the
        # run it penalizes the leg under test, not the baseline.
        params_on, dt_on = run_on()
        params_off, dt_off = run_off()
        identical = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(jax.tree_util.tree_leaves(params_on),
                            jax.tree_util.tree_leaves(params_off)))

        snap = hvd.metrics()
        stall = snap.get("host.stall_seconds", {})
        on_rate = steps / dt_on
        off_rate = steps / dt_off
        return {
            "metric": "input_pipeline_steps_per_sec",
            "value": round(on_rate, 1),
            "unit": "steps/sec",
            "prefetch_on": round(on_rate, 1),
            "prefetch_off": round(off_rate, 1),
            "speedup": round(on_rate / off_rate, 2) if off_rate else None,
            "vs_baseline": round(on_rate / off_rate, 2) if off_rate
            else None,
            "params_identical": identical,
            "loader_delay_ms": round(delay_s * 1e3, 2),
            "calibrated_step_ms": round(step_s * 1e3, 2),
            "steps": steps,
            "replicas": n,
            "telemetry": {
                "host_stall_seconds_sum": round(stall.get("sum", 0.0), 4),
                "host_stall_events": stall.get("count", 0),
                "batches_staged": snap.get(
                    "input.batches_staged", {}).get("value"),
            },
        }
    finally:
        hvd.shutdown()


def _overlap_mp_leg(timeout: float = 300.0) -> dict:
    """The np=2 multi-process overlap leg: launch tests/mp_worker.py
    scenario_overlap under the real launcher — the overlapped mp step
    must be bitwise-identical to the monolithic mp step, replay its
    partial cycles from the response cache on the steady state, and
    recover bitwise through a mid-partial-cycle transport reset.
    Classified honestly: ``ok`` (all markers), ``unavailable`` (this
    jax build cannot execute np>1 CPU collectives — the container's
    0.4.37; CI's jax runs it for real), ``skipped`` (worker not
    shipped / quick shape) or ``failed`` (a real regression — the CI
    gate fails on it)."""
    import subprocess

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "mp_worker.py")
    if not os.path.exists(worker):
        return {"status": "skipped", "detail": "tests/mp_worker.py "
                                               "not shipped"}
    env = dict(os.environ)
    # One CPU device per process: strip the 8-virtual-device override
    # the bench parent set for its own mesh.
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
             "--platform", "cpu", worker, "overlap"],
            env=env, capture_output=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return {"status": "failed",
                "detail": f"timed out after {timeout:.0f}s"}
    out = proc.stdout.decode(errors="replace") \
        + proc.stderr.decode(errors="replace")
    markers = [f"OVERLAP_{leg}_OK rank={r}"
               for leg in ("SEG", "PLAIN") for r in (0, 1)] \
        + [f"OVERLAP_OK rank={r}" for r in (0, 1)]
    if proc.returncode == 0 and all(m in out for m in markers):
        return {"status": "ok", "bitwise_identical": True,
                "steady_state_cache_replay": True}
    # Narrow env-limit match: ONLY the XLA CPU backend's own wording
    # for missing cross-process collectives — a generic
    # NotImplementedError from our code must classify as a FAILURE
    # (the CI gate trips on it), not as an environment limit.
    env_limit = ("aren't implemented on the CPU backend",
                 "not implemented on the CPU backend",
                 "Multiprocess computations",
                 "MultiProcess collectives")
    if any(s in out for s in env_limit):
        return {"status": "unavailable",
                "detail": "this jax build cannot execute np>1 CPU "
                          "collectives (container jax; the CI "
                          "overlap-bench job runs this leg for real)"}
    return {"status": "failed", "rc": proc.returncode,
            "detail": out[-1500:]}


def _overlap_bench(steps: int = 12, warmup: int = 3, batch_per: int = 8,
                   seq: int = 64) -> dict:
    """Backward/communication-overlap microbench (``--mode overlap``):
    steps/sec on a compute-heavy transformer-LM chain, monolithic vs
    bucketed-backward, plus the bitwise param-identity gates.

    Legs, all over one transformer-LM chain, one batch, one initial
    state:

    * ``monolithic`` — the pre-overlap static step (HVD_TPU_OVERLAP=off):
      ONE compiled program, in-program bucketed psum.
    * ``serialized`` — the same bucketed sub-programs with hard fences:
      reduction strictly after backward (the "reduction serialized
      after backward" symptom of docs/performance.md — what a
      non-overlapped dynamic path would do).
    * ``overlapped`` — streaming dispatch: each backward segment's
      buckets hand their megakernel to the device while earlier
      segments are still executing.

    ``speedup`` is overlapped/serialized — the scheduling win at equal
    device work (the honest overlap measure); the timed legs run as
    ALTERNATING blocks and report the per-leg median so background load
    hits both legs symmetrically.  ``vs_monolithic`` rides along for
    context (on a CPU mesh the single-program static step may win it).
    On the CPU mesh there is no comm/compute concurrency to exploit —
    the 8 virtual devices and the host share one thread pool, which is
    exactly why ``HVD_TPU_OVERLAP=auto`` resolves to ``off`` there — so
    the CI floor asserts the streamed schedule costs at most a
    scheduling-noise margin over the serialized one (parity on a quiet
    box; same contract as the dataplane bench's int8 throughput floor),
    not a CPU win.

    Identity gates:

    * ``bitwise_identical`` — the overlapped step's params ≡ the
      monolithic step's, bitwise, via the single-backward streaming
      schedule (same model, plain-callable loss).  The segmented
      schedule's params are additionally gated ``serial_identical``
      (≡ the serialized dispatch of the same sub-programs, bitwise —
      structural: same programs, different interleaving) and reported/
      checked against the monolithic step as ``segmented_close``
      (allclose, rtol 1e-4 / atol 1e-5: Adam's per-coordinate
      normalization can amplify a 1-ULP backward drift on a
      near-zero-gradient coordinate to ~1e-6 after a few steps) +
      ``segmented_bitwise`` (informational: XLA:CPU compiles a
      per-stage backward program a ULP apart from the same jaxpr
      inside one big program; the reduction/apply layers are bitwise
      by construction — see parallel/overlap.py).
    * ``int8`` — under HVD_TPU_COMPRESSION=int8 the monolithic static
      path does not quantize at all, so the comparator is the
      serialized schedule: same bucket partition ⇒ same pow2-scale
      blocks, same stochastic-rounding ticks, same per-bucket
      error-feedback residual keys ⇒ bitwise-identical params.

    CPU-only like ``--mode control``: 8-virtual-device mesh, no TPU
    tunnel.  ``HVD_TPU_BENCH_OVERLAP_QUICK=1`` (set by the supervised
    run's child invocation) shrinks the chain and the timed blocks —
    compile time dominates the full-size run, and the supervised JSON
    carries these numbers for context while the CI `overlap-bench` job
    owns the full-size gates.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.transformer import (
        TransformerConfig, chained_lm_loss, chained_lm_params,
        init_transformer, synthetic_lm_batch)
    from horovod_tpu.parallel.training import (barrier_fence,
                                               make_train_step, shard_batch)

    quick = os.environ.get("HVD_TPU_BENCH_OVERLAP_QUICK") == "1"
    layers, blocks = (2, 1) if quick else (4, 3)
    if quick:
        steps, seq = 6, 32
    hvd.init(devices=jax.devices())
    try:
        n = hvd.size()
        cfg = TransformerConfig(vocab_size=128, d_model=64, n_heads=4,
                                n_layers=layers, d_ff=256,
                                max_seq_len=seq)
        chain = chained_lm_loss(cfg)

        def plain_loss(p, b):  # not a ChainedLoss ⇒ unsegmented schedule
            return chain(p, b)

        key = jax.random.PRNGKey(0)
        params0 = chained_lm_params(init_transformer(key, cfg), cfg)
        tokens, targets = synthetic_lm_batch(jax.random.PRNGKey(1),
                                             batch_per * n, seq,
                                             cfg.vocab_size)
        batch = shard_batch((jnp.asarray(tokens), jnp.asarray(targets)))
        opt = optax.adam(1e-3)
        # Threshold sized so each decoder layer splits into several
        # dispatch buckets — the granularity the overlap streams at.
        threshold = 16 * 1024

        def build(mode, loss=chain):
            return make_train_step(loss, opt, donate=False,
                                   fusion_threshold=threshold,
                                   overlap=mode)

        def run(step, n_steps, wu=warmup):
            p, s = params0, opt.init(params0)
            for _ in range(wu):
                p, s, loss = step(p, s, batch)
            barrier_fence(p, loss)
            t0 = time.perf_counter()
            for _ in range(n_steps):
                p, s, loss = step(p, s, batch)
            barrier_fence(p, loss)
            return p, time.perf_counter() - t0

        def identical(a, b):
            return all(
                np.asarray(x).tobytes() == np.asarray(y).tobytes()
                for x, y in zip(jax.tree_util.tree_leaves(a),
                                jax.tree_util.tree_leaves(b)))

        # Identity legs first (short, untimed).
        step_on = build("on")
        step_serial = build("serial")
        step_off = build("off")
        params_on, _ = run(step_on, 2, wu=2)
        params_serial, _ = run(step_serial, 2, wu=2)
        params_off, _ = run(step_off, 2, wu=2)
        params_u_on, _ = run(build("on", plain_loss), 2, wu=2)
        params_u_off, _ = run(build("off", plain_loss), 2, wu=2)

        bitwise = identical(params_u_on, params_u_off)
        serial_eq = identical(params_on, params_serial)
        seg_bitwise = identical(params_on, params_off)
        seg_close = all(np.allclose(np.asarray(a), np.asarray(b),
                                    rtol=1e-4, atol=1e-5)
                        for a, b in zip(
                            jax.tree_util.tree_leaves(params_on),
                            jax.tree_util.tree_leaves(params_off)))

        # Timed legs: alternating blocks, median per leg (background
        # load hits both symmetrically — same policy as the dataplane
        # bench's paired cycles).
        rates = {"on": [], "serial": [], "off": []}
        for _ in range(blocks):
            for mode, step in (("on", step_on), ("serial", step_serial),
                               ("off", step_off)):
                _, dt = run(step, steps, wu=1)
                rates[mode].append(steps / dt)

        def median(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        on_rate = median(rates["on"])
        serial_rate = median(rates["serial"])
        off_rate = median(rates["off"])

        # Quantized leg: per-bucket EF residuals must survive the
        # refactor — overlapped ≡ serialized bitwise under int8.
        hvd.set_compression(default="int8")
        try:
            p8_on, dt8_on = run(build("on"), 4, wu=2)
            p8_serial, _ = run(build("serial"), 4, wu=2)
            int8 = {
                "bitwise_identical": identical(p8_on, p8_serial),
                "quantized_active": not identical(p8_on, params_on),
                "overlapped_steps_per_sec": round(4 / dt8_on, 2),
            }
        finally:
            hvd.set_compression(default="none")

        # np=2 multi-process leg (bitwise mp streaming; 'unavailable'
        # under a jax that cannot run np>1 CPU collectives).  Skipped
        # in the supervised quick shape — CI owns the real run.
        mp_leg = ({"status": "skipped", "detail": "quick shape"}
                  if quick else _overlap_mp_leg())

        snap = hvd.metrics()
        exposed = snap.get("overlap.exposed_comm_seconds", {})
        return {
            "metric": "overlap_steps_per_sec",
            "value": round(on_rate, 2),
            "unit": "steps/sec",
            "overlapped": round(on_rate, 2),
            "serialized": round(serial_rate, 2),
            "monolithic": round(off_rate, 2),
            "speedup": round(on_rate / serial_rate, 2) if serial_rate
            else None,
            "vs_monolithic": round(on_rate / off_rate, 2) if off_rate
            else None,
            "vs_baseline": round(on_rate / serial_rate, 2) if serial_rate
            else None,
            "bitwise_identical": bitwise,
            "serial_identical": serial_eq,
            "segmented_bitwise": seg_bitwise,
            "segmented_close": seg_close,
            "int8": int8,
            "mp": mp_leg,
            "buckets": step_on.bucket_count,
            "segments": step_on.segment_count,
            "steps": steps,
            "replicas": n,
            "telemetry": {
                "buckets_dispatched": snap.get(
                    "overlap.buckets_dispatched", {}).get("value"),
                "exposed_comm_seconds_sum": round(
                    exposed.get("sum", 0.0), 4),
                "fallbacks": snap.get(
                    "overlap.fallbacks", {}).get("value", 0),
            },
        }
    finally:
        hvd.shutdown()


def _pipeline_bench(steps: int = 8, warmup: int = 2) -> dict:
    """Pipeline-schedule microbench (``--mode pipeline``): the
    host-scheduled MPMD pipeline train step (parallel/pipeline.py),
    1F1B with streamed partial-cycle gradient reduction vs the
    GPipe-ordered dispatch of the SAME per-stage executables with the
    reduction serialized after a flush fence — equal device work, only
    the interleaving and the reduction dispatch points differ.

    Reported per leg: steps/sec and **exposed-bubble seconds** per
    step (``pipeline.bubble_seconds`` — host time waiting on gradient
    reductions after the last schedule tick; the 1F1B leg streams each
    stage's buckets the moment its last backward dispatches, so its
    reductions ride inside the schedule while the GPipe leg pays the
    whole reduction after the flush).  The headline gate is
    ``bubble_hidden``: 1F1B's exposed-bubble seconds strictly below
    the GPipe leg's.  ``speedup`` (1f1b/gpipe steps/sec) rides with
    the same CPU-floor caveat as ``--mode overlap`` — on the shared
    thread pool the legs tie; the wall-clock win needs a real
    accelerator mesh.

    Identity gates: ``bitwise_identical`` (1F1B params+loss ≡ the
    GPipe-ordered leg after several adam steps — same microbatch
    accumulation order by construction) and ``reference_close`` (one
    SGD step ≡ ``p0 - lr·grad`` of the monolithic microbatch-mean
    loss, allclose).  The schedule SHAPE facts (scheduled bubble
    fraction, peak in-flight activations per schedule) come from the
    dryrun plan — no hardware in that part.

    CPU-only like ``--mode control``: 8-virtual-device mesh, no TPU
    tunnel.  ``HVD_TPU_BENCH_PIPELINE_QUICK=1`` (the supervised run's
    child) shrinks the chain and the timed blocks.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.parallel.training import barrier_fence, shard_batch

    quick = os.environ.get("HVD_TPU_BENCH_PIPELINE_QUICK") == "1"
    S, m, d, blocks = (3, 4, 48, 1) if quick else (4, 8, 96, 3)
    if quick:
        steps = 4
    hvd.init(devices=jax.devices())
    try:
        n = hvd.size()

        def stage_first(p, carry, b):
            x, _y = b
            return jnp.tanh(x @ p["w"] + p["b"])

        def stage_mid(p, carry, b):
            return jnp.tanh(carry @ p["w"] + p["b"])

        def stage_last(p, carry, b):
            _x, y = b
            pred = carry @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)

        chain = hvd.ChainedLoss([stage_first]
                                + [stage_mid] * (S - 2) + [stage_last])
        ks = jax.random.split(jax.random.PRNGKey(0), S)
        params0 = [{"w": jax.random.normal(k, (d, d)) * d ** -0.5,
                    "b": jnp.zeros((d,))} for k in ks]
        B = n * m * 4
        x = jax.random.normal(jax.random.PRNGKey(1), (B, d))
        y = jax.random.normal(jax.random.PRNGKey(2), (B, d))
        batch = shard_batch((x, y))
        opt = optax.adam(1e-3)

        def build(schedule):
            return hvd.make_pipeline_train_step(
                chain, opt, num_microbatches=m, schedule=schedule,
                fusion_threshold=d * d * 4)

        def run(step, n_steps, wu=warmup):
            p, s = params0, opt.init(params0)
            for _ in range(wu):
                p, s, loss = step(p, s, batch)
            barrier_fence(p, loss)
            t0 = time.perf_counter()
            for _ in range(n_steps):
                p, s, loss = step(p, s, batch)
            barrier_fence(p, loss)
            return p, float(loss), time.perf_counter() - t0

        def identical(a, b):
            return all(
                np.asarray(u).tobytes() == np.asarray(v).tobytes()
                for u, v in zip(jax.tree_util.tree_leaves(a),
                                jax.tree_util.tree_leaves(b)))

        step_f = build("1f1b")
        step_g = build("gpipe")

        # Identity legs (short, untimed).
        p_f, l_f, _ = run(step_f, 2, wu=1)
        p_g, l_g, _ = run(step_g, 2, wu=1)
        bitwise = identical(p_f, p_g) and l_f == l_g

        # Reference leg: one SGD step vs the monolithic mean-loss grad.
        sgd = optax.sgd(0.1)
        step_ref = hvd.make_pipeline_train_step(
            chain, sgd, num_microbatches=m, schedule="1f1b",
            fusion_threshold=d * d * 4)
        p1, _, _l1 = step_ref(params0, sgd.init(params0), batch)

        def mb_of(arr, i):
            lb = B // n
            return jnp.concatenate(
                [arr[r * lb:(r + 1) * lb].reshape(
                    m, lb // m, d)[i] for r in range(n)], 0)

        def ref_loss(p):
            tot = 0.0
            for i in range(m):
                tot = tot + chain(p, (mb_of(x, i), mb_of(y, i)))
            return tot / m

        g_ref = jax.grad(ref_loss)(params0)
        reference_close = all(
            np.allclose(np.asarray(a),
                        np.asarray(p0) - 0.1 * np.asarray(g),
                        rtol=2e-5, atol=2e-6)
            for a, p0, g in zip(jax.tree_util.tree_leaves(p1),
                                jax.tree_util.tree_leaves(params0),
                                jax.tree_util.tree_leaves(g_ref)))

        # Timed legs: alternating blocks, per-leg median steps/sec AND
        # per-leg exposed-bubble seconds (the telemetry histogram's sum
        # delta — reduction time NOT hidden inside the schedule).
        def bubble_sum():
            return hvd.metrics().get(
                "pipeline.bubble_seconds", {}).get("sum", 0.0)

        rates = {"1f1b": [], "gpipe": []}
        exposed = {"1f1b": [], "gpipe": []}
        for _ in range(blocks):
            for mode, step in (("1f1b", step_f), ("gpipe", step_g)):
                b0 = bubble_sum()
                _, _, dt = run(step, steps, wu=1)
                # wu step's bubble rides the delta too: normalize per
                # step over everything the block ran.
                exposed[mode].append((bubble_sum() - b0) / (steps + 1))
                rates[mode].append(steps / dt)

        def median(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        f_rate, g_rate = median(rates["1f1b"]), median(rates["gpipe"])
        f_exp, g_exp = median(exposed["1f1b"]), median(exposed["gpipe"])

        # hvd-mem: per-schedule measured activation peak (the ledger's
        # pipeline.activations category) vs the planner's prediction
        # (schedule_plan peak carries x carry bytes) — bytes, not
        # tensor counts — plus a telemetry-on/off steps/sec A/B (the
        # ledger accounting rides telemetry.enabled()).
        from horovod_tpu import telemetry as _telemetry
        from horovod_tpu.memory import ledger as _mem_ledger
        from horovod_tpu.memory import planner as _mem_planner

        led = _mem_ledger.ledger
        memory_section = {}
        for mode, stepx in (("1f1b", step_f), ("gpipe", step_g)):
            led.reset()
            stepx(params0, opt.init(params0), batch)
            measured = led.peak_by_category().get(
                "pipeline.activations", 0)
            predicted = _mem_planner.pipeline_activation_bytes(
                S, m, microbatch_rows=B // m, width=d, schedule=mode)
            err = (round(abs(predicted - measured) / measured * 100.0,
                         2) if measured else None)
            memory_section[mode] = {
                "ledger_peak_bytes": measured,
                "planner_predicted_bytes": predicted,
                "prediction_error_pct": err,
                "prediction_ok": err is not None and err <= 15.0,
            }
        led.reset()
        was_enabled = _telemetry.enabled()
        _telemetry.set_enabled(False)
        try:
            _, _, dt_off = run(step_f, max(2, steps // 2), wu=1)
        finally:
            _telemetry.set_enabled(was_enabled)
        _, _, dt_on = run(step_f, max(2, steps // 2), wu=1)
        mem_overhead = (round((dt_on / dt_off - 1.0) * 100.0, 2)
                        if dt_off else None)
        memory_section["ledger_overhead_pct"] = mem_overhead
        memory_section["ledger_overhead_ok"] = (
            mem_overhead is not None and mem_overhead <= 5.0)

        plan_f, plan_g = step_f.plan, step_g.plan
        snap = hvd.metrics()
        return {
            "metric": "pipeline_steps_per_sec",
            "value": round(f_rate, 2),
            "unit": "steps/sec",
            "schedule_1f1b": round(f_rate, 2),
            "schedule_gpipe": round(g_rate, 2),
            "speedup": round(f_rate / g_rate, 2) if g_rate else None,
            "vs_baseline": round(f_rate / g_rate, 2) if g_rate else None,
            "bitwise_identical": bitwise,
            "reference_close": reference_close,
            "exposed_bubble_seconds_per_step": {
                "1f1b": round(f_exp, 5), "gpipe": round(g_exp, 5)},
            "bubble_hidden": f_exp < g_exp,
            "plan": {
                "n_stages": S, "microbatches": m,
                "ticks_1f1b": plan_f.total_ticks,
                "bubble_fraction_1f1b": round(plan_f.bubble_fraction, 3),
                "bubble_fraction_gpipe": round(plan_g.bubble_fraction, 3),
                "peak_activations_1f1b": plan_f.peak_activations,
                "peak_activations_gpipe": plan_g.peak_activations,
            },
            "buckets": step_f.bucket_count,
            "steps": steps,
            "replicas": n,
            "memory": memory_section,
            "telemetry": {
                "microbatches": snap.get(
                    "pipeline.microbatches", {}).get("value"),
                "bubble_seconds_sum": round(snap.get(
                    "pipeline.bubble_seconds", {}).get("sum", 0.0), 4),
                "inflight_activations": snap.get(
                    "pipeline.inflight_activations", {}).get("value"),
                "inflight_activation_bytes": snap.get(
                    "pipeline.inflight_activation_bytes",
                    {}).get("value"),
            },
        }
    finally:
        hvd.shutdown()


def _memory_bench(tensors: int = 16, elems: int = 256,
                  cycles: int = 20) -> dict:
    """hvd-mem microbench (``--mode memory``): the planner-vs-ledger
    accuracy contract plus plan determinism and the seeded-OOM
    forensics path, CPU-only like ``--mode control``.

    Four gates ride the JSON (CI job ``memory``, ``--check-memory-plan``):

    * ``plan_deterministic`` — identical configs produce byte-identical
      plan JSON (CLI determinism);
    * ``dataplane.prediction_error_pct`` — the static framework-bytes
      prediction lands within the bound of the measured ledger
      high-watermark for a steady-state fused allreduce cycle;
    * ``pipeline.prediction_error_pct`` — same contract for the MPMD
      schedule's activation carries;
    * ``oom_dump.ok`` — a simulated small capacity
      (``HVD_TPU_MEM_CAPACITY``) produces a flight dump naming the
      failing executable and the top ledger categories.
    """
    import glob as _glob
    import tempfile

    os.environ["HVD_TPU_COMPRESSION"] = "none"
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.memory import ledger as _mem_ledger
    from horovod_tpu.memory import planner as _mem_planner
    from horovod_tpu.ops import megakernel as mk
    from horovod_tpu.telemetry import flight as _flight

    hvd.init(devices=jax.devices())
    try:
        n = hvd.size()
        led = _mem_ledger.ledger
        rng = np.random.default_rng(11)
        base = [rng.standard_normal((n, elems)).astype(np.float32)
                for _ in range(tensors)]
        inputs = [hvd.shard(t) for t in base]

        def cycle(tag):
            # quiesce: submissions land as ONE fused response (the
            # prediction below models the single fused launch).
            with hvd.quiesce():
                hs = [hvd.allreduce_async(x, average=True,
                                          name=f"{tag}.{j}")
                      for j, x in enumerate(inputs)]
            return [hvd.synchronize(h) for h in hs]

        cycle("warm")
        led.reset()
        cycle("acc")
        dp_measured = led.watermark()
        dp_predicted = _mem_planner.plan_dataplane(
            tensors, elems, n).framework_bytes
        dp_err = (round(abs(dp_predicted - dp_measured)
                        / dp_measured * 100.0, 2)
                  if dp_measured else None)

        # Pipeline accuracy: one step of a small MPMD chain.
        from horovod_tpu.parallel.training import shard_batch

        S, m, d = 3, 4, 32

        def stage_first(p, carry, b):
            x, _y = b
            return jnp.tanh(x @ p["w"])

        def stage_mid(p, carry, b):
            return jnp.tanh(carry @ p["w"])

        def stage_last(p, carry, b):
            _x, y = b
            return jnp.mean((carry @ p["w"] - y) ** 2)

        ks = jax.random.split(jax.random.PRNGKey(0), S)
        params = [{"w": jax.random.normal(k, (d, d)) * d ** -0.5}
                  for k in ks]
        B = n * m
        batch = shard_batch(
            (np.asarray(jax.random.normal(jax.random.PRNGKey(1),
                                          (B, d))),
             np.asarray(jax.random.normal(jax.random.PRNGKey(2),
                                          (B, d)))))
        opt = optax.sgd(0.1)
        step = hvd.make_pipeline_train_step(
            [stage_first] + [stage_mid] * (S - 2) + [stage_last], opt,
            num_microbatches=m, fusion_threshold=d * d * 4)
        led.reset()
        step(params, opt.init(params), batch)
        pl_measured = led.peak_by_category().get(
            "pipeline.activations", 0)
        pl_predicted = _mem_planner.pipeline_activation_bytes(
            S, m, microbatch_rows=B // m, width=d)
        pl_err = (round(abs(pl_predicted - pl_measured)
                        / pl_measured * 100.0, 2)
                  if pl_measured else None)
        led.reset()

        # Plan determinism (the CLI's byte-identity contract).
        det = all(
            _mem_planner.build_plan(name, **kw).to_json()
            == _mem_planner.build_plan(name, **kw).to_json()
            for name, kw in (
                ("dataplane", {"tensors": tensors, "elems": elems,
                               "world": n}),
                ("transformer_lm", {"batch_size": 64, "world": 8}),
                ("serving", {"n_layers": 2, "n_heads": 8,
                             "head_dim": 16, "max_slots": 8,
                             "pages_per_slot": 8, "page_size": 16}),
                ("pipeline", {"n_stages": 4, "num_microbatches": 8,
                              "microbatch_rows": 32, "width": 64,
                              "world": 8})))

        # Seeded OOM: simulated small capacity -> flight dump naming
        # the failing executable + top ledger categories.
        oom = {"ok": False, "executable": None, "top_categories": []}
        with tempfile.TemporaryDirectory() as td:
            with _flight.recorder._dump_lock:
                _flight.recorder._last_dump.clear()
            os.environ["HVD_TPU_FLIGHT_DIR"] = td
            os.environ["HVD_TPU_MEM_CAPACITY"] = "4096"
            led.set("serving.kv_pages", 3000)
            led.set("megakernel.residuals", 2000)
            led.set("input.prefetch", 1000)
            try:
                cycle("oomseed")  # guard raises, eager fallback runs
            finally:
                os.environ.pop("HVD_TPU_FLIGHT_DIR", None)
                os.environ.pop("HVD_TPU_MEM_CAPACITY", None)
                led.reset()
            dumps = _glob.glob(os.path.join(td, "*oom*"))
            if dumps:
                extra = json.load(open(dumps[0])).get("extra", {})
                oom = {
                    "ok": bool(extra.get("executable"))
                    and len(extra.get("top_categories", [])) >= 3,
                    "executable": extra.get("executable"),
                    "top_categories": [t["category"] for t in
                                       extra.get("top_categories",
                                                 [])],
                }

        # Ledger overhead A/B (informational here; the binding ≤5 %
        # gate rides --mode dataplane's telemetry section).
        from horovod_tpu import telemetry as _telemetry

        def timed():
            lats = []
            for _ in range(cycles):
                t0 = time.perf_counter()
                cycle("ovh")
                lats.append(time.perf_counter() - t0)
            lats.sort()
            return lats[len(lats) // 2]

        lat_on = timed()
        was_enabled = _telemetry.enabled()
        _telemetry.set_enabled(False)
        try:
            lat_off = timed()
        finally:
            _telemetry.set_enabled(was_enabled)
        ovh = (round((lat_on / lat_off - 1.0) * 100.0, 2)
               if lat_off else None)

        worst = max(e for e in (dp_err, pl_err) if e is not None) \
            if (dp_err is not None or pl_err is not None) else None
        return {
            "metric": "memory_plan_prediction_error_pct",
            "value": worst,
            "unit": "%",
            "vs_baseline": None,
            "dataplane": {"ledger_peak_bytes": dp_measured,
                          "planner_predicted_bytes": dp_predicted,
                          "prediction_error_pct": dp_err},
            "pipeline": {"ledger_peak_bytes": pl_measured,
                         "planner_predicted_bytes": pl_predicted,
                         "prediction_error_pct": pl_err},
            "plan_deterministic": det,
            "oom_dump": oom,
            "ledger_overhead_pct": ovh,
            "tensors": tensors,
            "elems": elems,
            "replicas": n,
        }
    finally:
        hvd.shutdown()


def _fused_bench(rows: int = 1024, k: int = 512, n_feat: int = 512,
                 cycles: int = 7) -> dict:
    """hvd-fuse microbench (``--mode fused``): the fused
    computation-collective contracts, CPU-only like ``--mode control``
    (8-virtual-device mesh, no TPU tunnel — XLA:CPU's thunk runtime
    genuinely overlaps a chunk's psum with the next chunk's GEMM, so
    the exposed-communication contract measures for real here).

    Four gates ride the JSON (CI job ``fused-bench``, ``--check-speedup``):

    * ``bitwise.*`` — every fused program (tensor-parallel psum closer,
      MoE dispatch→FFN→combine round trip) reproduces its unfused
      reference program's bytes exactly;
    * ``dispatches_per_fused_group`` — one fused group is ONE XLA
      executable launch, counted at jax's dispatch choke point
      (utils/xla_dispatch.py), on both legs;
    * ``exposed_comm.strictly_below`` — the fused leg's exposed
      communication seconds (``max(0, total - compute_only)``, the
      ``fused.exposed_comm_seconds`` figure) land strictly below the
      unfused leg's — i.e. chunking actually hid the collective;
    * ``bitwise.fallback_off_parity`` — ``HVD_TPU_FUSE=off`` pins the
      unfused reference program bytes.
    """
    os.environ["HVD_TPU_COUNT_DISPATCHES"] = "1"
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.core import compat as _compat
    from horovod_tpu.core.topology import (EXPERT_AXIS, MODEL_AXIS,
                                           make_mesh)
    from horovod_tpu.memory import planner as _mem_planner
    from horovod_tpu.ops import fused as F
    from horovod_tpu.parallel.expert import (MoEOutput, init_moe_params,
                                             local_experts, moe_layer)
    from horovod_tpu.utils import xla_dispatch

    n = 8
    mesh = make_mesh(model=n)
    chunks = F.fuse_chunks()
    rng = np.random.default_rng(17)
    x = jnp.asarray(rng.standard_normal((rows, k)).astype(np.float32))
    w = jnp.asarray((rng.standard_normal((k, n_feat)) * 0.05)
                    .astype(np.float32))

    def build_tensor(fuse, with_comm=True):
        # The row-parallel closer body (parallel/tensor.row_parallel's
        # exact dot→psum ordering); with_comm=False elides the
        # collective legs — the compute_only baseline both exposed
        # measurements subtract.
        def body(x, w):
            def leg(xc):
                part = jnp.dot(xc, w,
                               preferred_element_type=jnp.float32)
                if with_comm:
                    part = jax.lax.psum(part, MODEL_AXIS)
                return part
            return F.chunked_map(leg, x, axis=0, chunks=chunks,
                                 fuse=fuse)
        return jax.jit(_compat.shard_map(
            body, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False))

    fused_t = build_tensor(True)
    unfused_t = build_tensor(False)
    tensor_bitwise = bool(
        np.asarray(fused_t(x, w)).tobytes()
        == np.asarray(unfused_t(x, w)).tobytes())

    # Fallback parity: HVD_TPU_FUSE=off must pin the reference program
    # even when the call site passes no explicit override.
    prev = os.environ.get(F.FUSE_ENV)
    os.environ[F.FUSE_ENV] = "off"
    try:
        off_t = build_tensor(None)
        fallback_parity = bool(
            np.asarray(off_t(x, w)).tobytes()
            == np.asarray(unfused_t(x, w)).tobytes())
    finally:
        if prev is None:
            os.environ.pop(F.FUSE_ENV, None)
        else:
            os.environ[F.FUSE_ENV] = prev

    # One fused group == ONE XLA executable launch (warm).
    def count_dispatches(fn, *args):
        jax.block_until_ready(fn(*args))
        with xla_dispatch.exact_scope():
            with xla_dispatch.record(all_threads=True) as scope:
                jax.block_until_ready(fn(*args))
        return scope.count

    tensor_disp = count_dispatches(fused_t, x, w)

    # Exposed communication: both legs against their own compute_only
    # baseline, same clamp + median idiom (ops/fused.measure_exposed_
    # comm) — the unfused leg serializes GEMM→psum, the fused leg hides
    # chunk i's psum under chunk i+1's GEMM.
    exposed_unfused = F.measure_exposed_comm(
        unfused_t, build_tensor(False, with_comm=False), (x, w),
        cycles=cycles)
    exposed_fused = F.measure_exposed_comm(
        fused_t, build_tensor(True, with_comm=False), (x, w),
        cycles=cycles)
    strictly_below = bool(exposed_fused < exposed_unfused)

    # The flagship: the MoE dispatch→FFN→combine round trip, fused vs
    # unfused, bitwise, on its own expert mesh.
    E, D, H, tokens = 8, 16, 32, 256
    mesh_e = make_mesh(expert=n)
    key = jax.random.PRNGKey(5)
    kx, kp = jax.random.split(key)
    from jax.sharding import NamedSharding
    # Pre-place on the expert mesh: an uncommitted input would cost an
    # implicit reshard executable and double the counted dispatches.
    xe = jax.device_put(jax.random.normal(kx, (tokens, D)),
                        NamedSharding(mesh_e, P(EXPERT_AXIS)))
    params = jax.device_put(init_moe_params(kp, E, D, H),
                            NamedSharding(mesh_e, P()))

    def build_moe(fuse):
        def f(x, params):
            mine = local_experts(params, axis_name=EXPERT_AXIS)
            return moe_layer(x, mine, axis_name=EXPERT_AXIS,
                             num_experts=E, top_k=2,
                             capacity_factor=8.0, fuse=fuse,
                             fuse_chunks=chunks)
        return jax.jit(_compat.shard_map(
            f, mesh=mesh_e, in_specs=(P(EXPERT_AXIS), P()),
            out_specs=MoEOutput(P(EXPERT_AXIS), P(), P()),
            check_vma=False))

    moe_f = build_moe(True)
    moe_u = build_moe(False)
    got_f, got_u = moe_f(xe, params), moe_u(xe, params)
    moe_bitwise = all(
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        for a, b in zip(got_f, got_u))
    moe_disp = count_dispatches(moe_f, xe, params)

    # Host-side services: dispatch the tensor group through
    # FusedProgram so the bench exercises the AOT-compile → manifest →
    # ledger-charge path and the run's JSON carries the counters.
    launch_bytes = _mem_planner.fused_group_bytes(
        (rows, n_feat), chunks, dtype="float32")
    prog = F.FusedProgram("bench/row_parallel", fused_t, mesh=mesh,
                          chunks=chunks, launch_bytes=launch_bytes)
    jax.block_until_ready(prog(x, w))
    wrapped_bitwise = bool(
        np.asarray(prog(x, w)).tobytes()
        == np.asarray(unfused_t(x, w)).tobytes())

    hidden_pct = (round((1.0 - exposed_fused / exposed_unfused) * 100.0,
                        1) if exposed_unfused else None)
    return {
        "metric": "fused_exposed_comm_us",
        "value": round(exposed_fused * 1e6, 1),
        "unit": "us/group",
        "vs_baseline": round(exposed_unfused * 1e6, 1),
        "exposed_comm": {
            "unfused_us": round(exposed_unfused * 1e6, 1),
            "fused_us": round(exposed_fused * 1e6, 1),
            "hidden_pct": hidden_pct,
            "strictly_below": strictly_below,
        },
        "bitwise": {
            "tensor_psum": tensor_bitwise,
            "expert_roundtrip": bool(moe_bitwise),
            "fused_program_wrapper": wrapped_bitwise,
            "fallback_off_parity": fallback_parity,
        },
        "dispatches_per_fused_group": {
            "tensor": tensor_disp,
            "expert": moe_disp,
        },
        "chunks": chunks,
        "rows": rows,
        "launch_bytes": launch_bytes,
        "telemetry": {
            "groups_compiled": F._M_GROUPS.value,
            "launches": F._M_LAUNCHES.value,
        },
        "replicas": n,
    }


def _serving_bench(n_requests: int = 40, max_slots: int = 8,
                   seed: int = 7) -> dict:
    """Serving microbench (``--mode serving``): tokens/sec through the
    hvd-serve engine, continuous batching vs static batching, on a
    seeded ragged-arrival trace.

    Both legs run the IDENTICAL engine, executables and trace; the only
    difference is the admission policy — continuous admits into any
    free slot every iteration (``engine.step(admit=True)``), static
    admits only at batch boundaries (all slots empty), the classic
    serve-a-batch-to-completion loop.  Raggedness (prompt 4–24 tokens,
    4–48 generated, staggered logical arrivals) is what continuous
    batching monetizes: static burns decode iterations on mostly-empty
    batches while the longest sequence finishes.

    Also asserted in-bench, because the schedulers may differ ONLY in
    wall time: every request's generated tokens are identical between
    the two legs (``results_identical`` — the batch-composition
    invariance the serving bitwise contract guarantees), and a greedy
    engine rollout equals the token-by-token argmax rollout of the
    jitted non-incremental ``serving_forward`` (``bitwise_identical``).
    CPU-only like ``--mode control``: no XLA collectives, no TPU
    tunnel.  ``HVD_TPU_BENCH_SERVING_QUICK=1`` (the tier-1 test)
    shrinks the traces — the deterministic gates hold at any trace
    size, and the CI `serving-bench` job owns the full-size
    throughput gates.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.models.transformer import (TransformerConfig,
                                                init_transformer,
                                                serving_forward)
    from horovod_tpu.serving import InferenceEngine

    quick = os.environ.get("HVD_TPU_BENCH_SERVING_QUICK") == "1"
    if quick:
        n_requests = 14

    # Sized so the decode dispatch dominates the per-iteration cost
    # (host-side sampling is constant per token and would otherwise
    # dilute the iteration-count advantage under measurement).
    cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=8,
                            n_layers=3, d_ff=256, max_seq_len=128)
    params = init_transformer(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(seed)
    trace = []
    arrival = 0
    for _ in range(n_requests):
        arrival += int(rng.integers(0, 2))
        # Heavy-tailed generation lengths — the real serving shape
        # (most completions short, a tail of long ones) and the case
        # static batching handles worst: one long sequence pins the
        # whole batch while its siblings' slots idle.
        if rng.random() < 0.25:
            max_new = int(rng.integers(48, 65))
        else:
            max_new = int(rng.integers(4, 13))
        trace.append({
            "prompt": [int(t) for t in
                       rng.integers(0, cfg.vocab_size,
                                    size=int(rng.integers(4, 17)))],
            "max_new": max_new,
            "arrival": arrival,
        })

    def run(continuous: bool):
        eng = InferenceEngine(params, cfg, max_slots=max_slots,
                              page_size=16, capacity=128)
        eng.warm_start()
        # Steady-state measurement: pre-build the trace's prefill
        # buckets (a live fleet has them from the manifest warm start;
        # cold XLA compiles would otherwise dominate both legs equally
        # and mask the scheduling difference under test).
        for t in trace:
            eng._prefill_exec(eng._bucket_for(len(t["prompt"])))
        reqs = [eng.submit(t["prompt"], max_new_tokens=t["max_new"],
                           arrival=t["arrival"]) for t in trace]
        it = 0
        t0 = time.perf_counter()
        while not eng.scheduler.idle():
            eng.step(now=it, admit=continuous
                     or eng.scheduler.occupancy() == 0)
            it += 1
        dt = time.perf_counter() - t0
        tokens = sum(len(r.generated) for r in reqs)
        ttft = sorted(r.t_first_token - r.t_submit for r in reqs)
        per_tok = sorted(
            (r.t_done - r.t_first_token) / (len(r.generated) - 1)
            for r in reqs if len(r.generated) > 1)

        def pct(xs, q):
            return round(xs[min(len(xs) - 1,
                                int(q * (len(xs) - 1)))] * 1e3, 3)

        return {
            "tokens_per_sec": round(tokens / dt, 1),
            "tokens": tokens,
            "iterations": it,
            "wall_seconds": round(dt, 3),
            "ttft_ms": {"p50": pct(ttft, 0.5), "p99": pct(ttft, 0.99)},
            "token_ms": {"p50": pct(per_tok, 0.5),
                         "p99": pct(per_tok, 0.99)},
        }, [list(r.generated) for r in reqs]

    cont, cont_out = run(continuous=True)
    stat, stat_out = run(continuous=False)
    results_identical = cont_out == stat_out

    prefix_section = _serving_prefix_bench(
        params, cfg, n_requests=10 if quick else 24, max_slots=max_slots)
    spec_section = _serving_spec_bench(
        n_requests=10 if quick else 24, max_slots=max_slots)

    # Bitwise contract: engine prefill+decode (cached executables) vs
    # the jitted non-incremental forward, as a greedy rollout.
    eng = InferenceEngine(params, cfg, max_slots=max_slots,
                          page_size=16, capacity=128)
    eng.warm_start()
    prompt = trace[0]["prompt"]
    got = eng.generate(list(prompt), max_new_tokens=8)
    sf = jax.jit(serving_forward, static_argnums=(2, 3))
    seq = list(prompt)
    ref = []
    for _ in range(8):
        logits = np.asarray(sf(params, jnp.asarray([seq], jnp.int32),
                               cfg, eng.capacity))
        tok = int(np.argmax(logits[0, -1]))
        ref.append(tok)
        seq.append(tok)
    bitwise = got == ref

    speedup = (round(cont["tokens_per_sec"] / stat["tokens_per_sec"], 2)
               if stat["tokens_per_sec"] else None)
    return {
        "metric": "serving_tokens_per_sec",
        "value": cont["tokens_per_sec"],
        "unit": "tokens/sec",
        "continuous": cont,
        "static": stat,
        "speedup": speedup,
        "vs_baseline": speedup,
        "results_identical": results_identical,
        "bitwise_identical": bitwise,
        "requests": n_requests,
        "slots": max_slots,
        "prefix_cache": prefix_section,
        "speculative": spec_section,
    }


def _serving_prefix_bench(params, cfg, n_requests: int = 24,
                          max_slots: int = 8, seed: int = 13) -> dict:
    """Shared-prefix page-cache leg of ``--mode serving``: a
    repeated-prefix trace (one 32-token system header + per-request
    suffixes — the RAG/few-shot shape the cache monetizes) replayed
    through the IDENTICAL engine with the prefix cache on vs off.
    Gates (CI, --check-spec-speedup): completions BITWISE-equal
    between the legs (cache hits are observably side-effect-free) and
    ``prefill_tokens_saved > 0`` (the header's pages map copy-free
    after the first admission); p50 TTFT per leg rides along — the
    saved prefill work is the TTFT win."""
    import numpy as np

    from horovod_tpu import telemetry as _telemetry
    from horovod_tpu.serving import InferenceEngine

    rng = np.random.default_rng(seed)
    header = [int(t) for t in rng.integers(0, cfg.vocab_size, size=32)]
    trace = []
    arrival = 0
    for _ in range(n_requests):
        arrival += int(rng.integers(0, 2))
        trace.append({
            "prompt": header + [int(t) for t in rng.integers(
                0, cfg.vocab_size, size=int(rng.integers(4, 13)))],
            "max_new": int(rng.integers(4, 13)),
            "arrival": arrival,
        })

    def counter(name):
        return _telemetry.metrics().get(name, {}).get("value", 0)

    def run(prefix: bool):
        eng = InferenceEngine(params, cfg, max_slots=max_slots,
                              page_size=16, capacity=128,
                              prefix_cache=prefix)
        eng.warm_start()
        for t in trace:  # steady state: pre-build the buckets
            eng._prefill_exec(eng._bucket_for(len(t["prompt"])))
            # ...including the suffix-only buckets hits compile to
            # (the 32-token header is page-aligned at page_size=16).
            eng._prefill_exec(eng._bucket_for(len(t["prompt"]) - 32))
        pages_before = counter("serving.prefix_pages_shared")
        reqs = [eng.submit(t["prompt"], max_new_tokens=t["max_new"],
                           arrival=t["arrival"]) for t in trace]
        it = 0
        t0 = time.perf_counter()
        while not eng.scheduler.idle():
            eng.step(now=it)
            it += 1
        dt = time.perf_counter() - t0
        pages = counter("serving.prefix_pages_shared") - pages_before
        ttft = sorted(r.t_first_token - r.t_submit for r in reqs)
        return {
            "tokens_per_sec": round(
                sum(len(r.generated) for r in reqs) / dt, 1),
            "wall_seconds": round(dt, 3),
            "ttft_p50_ms": round(ttft[len(ttft) // 2] * 1e3, 3),
            "prefill_tokens_saved": int(pages) * eng.cache.page_size,
            "prefix_stats": eng.cache.prefix_stats(),
        }, [list(r.generated) for r in reqs]

    on, on_out = run(prefix=True)
    off, off_out = run(prefix=False)
    return {
        "on": on,
        "off": off,
        "bitwise_identical": on_out == off_out,
        "prefill_tokens_saved": on["prefill_tokens_saved"],
        "ttft_p50_improved": on["ttft_p50_ms"] <= off["ttft_p50_ms"],
        "requests": n_requests,
        "header_tokens": 32,
    }


def _serving_spec_bench(n_requests: int = 24, max_slots: int = 8,
                        seed: int = 11, spec_tokens: int = 5) -> dict:
    """Speculative-decoding leg of ``--mode serving``: the same seeded
    heavy-tailed trace through the IDENTICAL target model with and
    without a draft.  The pair is constructed for EXACT greedy
    agreement (every layer's residual contribution is zeroed in both
    models and the embed/unembed halves are shared, so target and
    draft logits are bitwise-identical): acceptance is deterministically
    1.0 and the measured speedup is the *mechanism's* — what the
    dispatch structure buys at full acceptance, the honest upper bound
    a CPU microbench can state (a real distilled draft lands wherever
    its acceptance rate does; serving.spec_acceptance_rate reports it
    live).  Gates (CI): speculative >= 1.3x non-speculative tokens/sec,
    completions BITWISE-equal (the bitwise-greedy acceptance rule —
    holds at ANY acceptance rate), and the steady-state dispatch
    contract: one draft propose + ONE target verify executable call
    per decode iteration, zero eager dispatches."""
    import numpy as np

    from horovod_tpu.models.transformer import TransformerConfig
    from horovod_tpu.serving import InferenceEngine
    from horovod_tpu.serving.harness import (agreement_pair,
                                             count_spec_dispatches)

    # FFN-heavy target, thin draft (~8% of the target's per-token
    # compute): the economics speculative decoding monetizes — the
    # verify's per-token cost is ~C_decode/2 regardless of depth (width
    # scales with the block, amortization scales with it too), so the
    # draft's relative cost decides the ceiling.  Quick mode keeps the
    # deterministic gates (bitwise agreement, dispatch contract) on a
    # small target — the economics gate is CI-only, full-size.
    quick = os.environ.get("HVD_TPU_BENCH_SERVING_QUICK") == "1"
    cfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=8,
                            n_layers=3 if quick else 8,
                            d_ff=256 if quick else 1024, max_seq_len=128)
    dcfg = TransformerConfig(vocab_size=256, d_model=128, n_heads=8,
                             n_layers=1, d_ff=64, max_seq_len=128)
    params, draft = agreement_pair(cfg, dcfg)

    rng = np.random.default_rng(seed)
    trace = []
    arrival = 0
    for _ in range(n_requests):
        arrival += int(rng.integers(0, 2))
        if rng.random() < 0.25:
            max_new = int(rng.integers(48, 65))
        else:
            max_new = int(rng.integers(4, 13))
        trace.append({
            "prompt": [int(t) for t in rng.integers(
                0, cfg.vocab_size, size=int(rng.integers(4, 17)))],
            "max_new": max_new,
            "arrival": arrival,
        })

    def run(speculative: bool):
        kw = {}
        if speculative:
            kw = {"draft": (draft, dcfg), "spec_tokens": spec_tokens}
        eng = InferenceEngine(params, cfg, max_slots=max_slots,
                              page_size=16, capacity=128, **kw)
        eng.warm_start()
        for t in trace:
            eng._prefill_exec(eng._bucket_for(len(t["prompt"])))
            if speculative:
                eng._prefill_exec(eng._bucket_for(len(t["prompt"])),
                                  draft=True)
        reqs = [eng.submit(t["prompt"], max_new_tokens=t["max_new"],
                           arrival=t["arrival"]) for t in trace]
        it = 0
        t0 = time.perf_counter()
        while not eng.scheduler.idle():
            eng.step(now=it)
            it += 1
        dt = time.perf_counter() - t0
        tokens = sum(len(r.generated) for r in reqs)
        return {
            "tokens_per_sec": round(tokens / dt, 1),
            "tokens": tokens,
            "iterations": it,
            "wall_seconds": round(dt, 3),
            "acceptance_rate": (round(eng.spec_acceptance_rate, 4)
                                if eng.spec_acceptance_rate is not None
                                else None),
        }, [list(r.generated) for r in reqs], eng

    # Best-of-2 per leg: the verdicts are deterministic (identical
    # completions every repeat — asserted), only the wall clock on a
    # shared box is not, and a transient load spike on either leg must
    # not flip the CI gate.  Quick mode runs each leg once — the
    # repeat is pure wall-clock insurance for the CI speedup gate.
    spec, spec_out, spec_eng = run(speculative=True)
    if quick:
        spec2, spec_out2, eng2 = spec, spec_out, spec_eng
    else:
        spec2, spec_out2, eng2 = run(speculative=True)
    if spec2["tokens_per_sec"] > spec["tokens_per_sec"]:
        spec, spec_eng = spec2, eng2
    base, base_out, _ = run(speculative=False)
    if quick:
        base2, base_out2 = base, base_out
    else:
        base2, base_out2, _ = run(speculative=False)
    if base2["tokens_per_sec"] > base["tokens_per_sec"]:
        base = base2
    repeats_identical = (spec_out == spec_out2
                         and base_out == base_out2)

    # Steady-state dispatch contract on the spec engine: one propose +
    # ONE verify executable call per decode iteration, nothing eager —
    # the same harness tests/test_speculative.py asserts through.
    for p in ([1, 2, 3], [4, 5, 6, 7]):
        spec_eng.submit(list(p), max_new_tokens=spec_tokens + 3)
    spec_eng.step()  # admissions + prefills
    proposes, verifies, eager = count_spec_dispatches(spec_eng)
    calls = {"verify": verifies, "propose": proposes}
    spec_eng.run_until_idle()

    speedup = (round(spec["tokens_per_sec"] / base["tokens_per_sec"], 2)
               if base["tokens_per_sec"] else None)
    return {
        "speculative": spec,
        "non_speculative": base,
        "speedup": speedup,
        "bitwise_greedy": spec_out == base_out and repeats_identical,
        "spec_tokens": spec_tokens,
        "verify_dispatches_per_iteration": calls["verify"],
        "propose_dispatches_per_iteration": calls["propose"],
        "eager_dispatches_per_iteration": eager,
        "requests": n_requests,
    }


def _tuning_bench(windows: int = 80) -> dict:
    """hvd-tune convergence leg of ``--mode tuning``: the REAL policy
    engine (tuning/policy.py, with the REAL hvd-mem pricing hook)
    closed over a deterministic fleet model, started deliberately
    mis-tuned — compression off on a simulated-DCN hierarchy, in-flight
    depth 1, oversized spec_tokens on a low-acceptance draft.

    The model is the paper's additive critical path: per-step
    milliseconds = compute + dcn(wire format) + dispatch-gap(in-flight
    depth) + speculative overhead(depth x miss rate).  Each decision
    window synthesizes the leg attribution the sensors would measure
    from that model and feeds it to the engine; an applied decision
    changes the model's knobs, which changes the NEXT window's legs —
    the closed loop, minus the hardware.  Gates (CI): converged
    steps/sec >= 1.5x mis-tuned AND within 10% of the hand-tuned
    reference, convergence within a bounded number of windows, and a
    bit-identical decision sequence on replay (the engine is free of
    wall clock and PRNG).  The separate actuation leg
    (tests/test_tuning.py) covers the marker path on the real
    runtime."""
    from horovod_tpu.memory.planner import retune_delta_bytes
    from horovod_tpu.tuning.policy import (PolicyEngine, WindowSnapshot)

    COMPUTE_MS = 10.0
    DCN_MS = {"none": 60.0, "bf16": 30.0, "int8": 14.0, "int4": 11.0}
    # Dispatch-gap vs in-flight depth: queueing-shaped — the gap
    # collapses once the window covers the dispatch latency.
    GAP_MS = {1: 40.0, 2: 24.0, 4: 14.0, 8: 2.0}
    ACCEPTANCE = 0.3
    SPEC_MS_PER_MISS = 0.9

    MIS_TUNED = {"dcn_compress": "none", "max_inflight": 1,
                 "fusion_threshold": 64 << 20, "cycle_time": 0.005,
                 "spec_tokens": 6}
    HAND_TUNED = {"dcn_compress": "int4", "max_inflight": 8,
                  "fusion_threshold": 64 << 20, "cycle_time": 0.005,
                  "spec_tokens": 1}

    def step_ms(k) -> float:
        return (COMPUTE_MS + DCN_MS[k["dcn_compress"]]
                + GAP_MS[k["max_inflight"]]
                + SPEC_MS_PER_MISS * k["spec_tokens"]
                * (1.0 - ACCEPTANCE))

    def legs_of(k) -> dict:
        # What trace/analyze.window_legs would attribute (busy µs).
        return {"dispatch": COMPUTE_MS * 1e3,
                "dcn": DCN_MS[k["dcn_compress"]] * 1e3,
                "dispatch-gap": GAP_MS[k["max_inflight"]] * 1e3,
                "host": 1e3}

    def run_loop():
        knobs = dict(MIS_TUNED)
        eng = PolicyEngine(price=lambda knob, old, new, s:
                           retune_delta_bytes(knob, old, new, s.knobs))
        decisions, trail = [], []
        for w in range(windows):
            snap = WindowSnapshot(
                index=w, legs=legs_of(knobs), knobs=dict(knobs),
                spec_acceptance=ACCEPTANCE, headroom_frac=0.5,
                headroom_bytes=8 << 30)
            d = eng.step(snap)
            if d is not None:
                knobs[d.knob] = d.value  # the fleet applies the marker
            decisions.append(None if d is None else
                             (d.seq, d.window, d.knob, str(d.value)))
            trail.append(round(step_ms(knobs), 4))
        return knobs, [d for d in decisions if d], trail

    knobs, decisions, trail = run_loop()
    _, decisions2, _ = run_loop()

    mis_sps = 1000.0 / step_ms(MIS_TUNED)
    converged_sps = 1000.0 / trail[-1]
    hand_sps = 1000.0 / step_ms(HAND_TUNED)
    last_window = max((d[1] for d in decisions), default=0)
    return {
        "mis_tuned_steps_per_sec": round(mis_sps, 2),
        "converged_steps_per_sec": round(converged_sps, 2),
        "hand_tuned_steps_per_sec": round(hand_sps, 2),
        "speedup": round(converged_sps / mis_sps, 2),
        "vs_hand_tuned": round(converged_sps / hand_sps, 3),
        "n_decisions": len(decisions),
        "last_decision_window": last_window,
        "windows": windows,
        "deterministic_replay": decisions == decisions2,
        "converged_knobs": {k: str(v) for k, v in sorted(knobs.items())},
        "decisions": [f"w{w}: {knob}={val}"
                      for _seq, w, knob, val in decisions],
    }


def _routing_bench(smoke: bool = False) -> dict:
    """hvd-route fleet leg of ``--mode routing`` (pure Python, no jax,
    no TPU tunnel).  Three legs over simulated replicas that speak the
    client surface of routing/replica.py (health / generate / drain /
    resume / prefixes — duck-typed where the HTTP client would sit):

    1. **Trace replay** — a seeded million-request heavy-tailed trace
       (Zipf-shared prompt headers, Pareto completion lengths, a
       mid-trace arrival spike) against 6 single-server replica queues
       with LRU prefix caches keyed by the REAL chain hashes
       (routing/affinity.py).  Least-loaded + prefix-affinity dispatch
       vs round-robin: p99 TTFT and aggregate tokens/sec gates, plus a
       bit-identical placement digest on replay (the scorer is free of
       wall clock and PRNG).
    2. **Failover digest identity** — the REAL Router dispatches over
       replicas whose completions are a pure rolling-hash function of
       the tokens so far (the sim analogue of the serving bitwise
       contract: prompt+partial reproduces the uninterrupted tail).
       One replica drains mid-generation (503 with partial tokens),
       another dies outright (connection severed, no partials); every
       merged completion must be digest-identical to a single-replica
       reference run.
    3. **Autoscaling** — the REAL FleetAutoscaler over the REAL
       Router: a sustained spike boots a replica (priced by the
       hvd-mem planner against host headroom, prefix-seeded from the
       busiest donor), a second spike against exhausted headroom is
       VETOED (never an OOM), and the trough drains the booted replica
       back, donating its prefix index to a survivor.
    """
    import hashlib
    import random as _random
    from collections import OrderedDict

    from horovod_tpu.memory.planner import (kv_cache_bytes,
                                            prefix_pages_bytes)
    from horovod_tpu.routing import (AutoscaleConfig, FleetAutoscaler,
                                     Router, RouterConfig)
    from horovod_tpu.routing.affinity import (prompt_header_hashes,
                                              published_page_hashes)
    from horovod_tpu.routing.replica import ReplicaUnreachable

    PAGE, PPS = 16, 8
    FP = "routing-bench-fp"

    # ---- leg 1: million-request heavy-tailed trace replay ----------------
    n_requests = 20_000 if smoke else 1_000_000
    n_replicas = 6
    n_headers = 400
    header_tokens = 4 * PAGE      # 4-page shared prompt headers
    cache_cap = 64                # headers one replica keeps warm (LRU)
    prefill_us = 60.0             # cost per uncached prompt token
    decode_us = 50.0              # cost per generated token
    rng = _random.Random(20)

    headers = [[rng.randrange(256) for _ in range(header_tokens)]
               for _ in range(n_headers)]
    # One chain hash per header, computed ONCE through the real scheme
    # (routing/affinity.py) — the first-page digest stands for the
    # whole chain in the sim's per-replica index.
    header_key = [prompt_header_hashes(FP.encode(), h + [0], PAGE,
                                       PPS)[0] for h in headers]
    weights = [1.0 / (r + 1) ** 0.7 for r in range(n_headers)]
    cum, acc = [], 0.0
    for w in weights:
        acc += w
        cum.append(acc)
    hdr = rng.choices(range(n_headers), cum_weights=cum, k=n_requests)
    suffix = [rng.randrange(8, 25) for _ in range(n_requests)]
    mtok = [max(1, min(64, int(4 * rng.paretovariate(1.5))))
            for _ in range(n_requests)]
    # Arrivals: Poisson at a base rate with a 1.25x spike through the
    # middle third (the autoscaling leg re-uses the same shape).
    base_us = 1e6 / 2400.0
    arrive, t = [], 0.0
    lo, hi = n_requests // 3, 2 * n_requests // 3
    for i in range(n_requests):
        mean = base_us / 1.25 if lo <= i < hi else base_us
        t += rng.expovariate(1.0 / mean)
        arrive.append(t)

    def _replay(policy: str) -> dict:
        busy = [0.0] * n_replicas
        caches = [OrderedDict() for _ in range(n_replicas)]
        hits, total_tokens = 0, 0
        ttfts = []
        placements = hashlib.sha256()
        aff_bonus = header_tokens * prefill_us  # prefill saved by a hit
        for i in range(n_requests):
            now = arrive[i]
            key = header_key[hdr[i]]
            if policy == "rr":
                r = i % n_replicas
            else:
                best = None
                for j in range(n_replicas):
                    backlog = busy[j] - now
                    if backlog < 0.0:
                        backlog = 0.0
                    score = backlog
                    if key in caches[j]:
                        score -= aff_bonus
                    if best is None or score < best[0]:
                        best = (score, j)
                r = best[1]
            cache = caches[r]
            if key in cache:
                hits += 1
                cache.move_to_end(key)
                prefill = suffix[i] * prefill_us
            else:
                cache[key] = None
                if len(cache) > cache_cap:
                    cache.popitem(last=False)
                prefill = (header_tokens + suffix[i]) * prefill_us
            start = busy[r] if busy[r] > now else now
            ttfts.append(start + prefill - now)
            busy[r] = start + prefill + mtok[i] * decode_us
            total_tokens += mtok[i]
            placements.update(bytes([r]))
        ttfts.sort()
        makespan_s = max(busy) / 1e6
        return {
            "p50_ttft_ms": round(ttfts[len(ttfts) // 2] / 1e3, 3),
            "p99_ttft_ms": round(
                ttfts[int(0.99 * (len(ttfts) - 1))] / 1e3, 3),
            "tokens_per_sec": round(total_tokens / makespan_s, 1),
            "affinity_hit_rate": round(hits / n_requests, 4),
            "placement_digest": placements.hexdigest()[:16],
        }

    rr = _replay("rr")
    aff = _replay("affinity")
    aff_replay = _replay("affinity")

    # ---- shared sim replica for the real-Router legs ---------------------
    VOCAB = 251

    def _fold(state: int, tok: int) -> int:
        return (state * 1103515245 + tok + 12345) & 0x7FFFFFFF

    def _complete(prompt, m):
        # State is a pure fold over the tokens SO FAR, so
        # _complete(prompt + partial, m - k) == _complete(prompt, m)[k:]
        # — the sim analogue of the serving bitwise contract that makes
        # drain continuations digest-exact.
        s = 0
        for tok in prompt:
            s = _fold(s, int(tok))
        out = []
        for _ in range(m):
            tok = (s * 48271 + 11) % VOCAB
            out.append(tok)
            s = _fold(s, tok)
        return out

    class _SimReplica:
        def __init__(self, name: str) -> None:
            self.name = name
            self.ready = True
            self.dead = False
            self.queue_depth = 0  # external load knob (autoscale leg)
            self.pending = 0      # decaying backlog of recent serves
            self.served = 0
            self.drain_at = None   # served count: 503 mid-generation
            self.die_at = None     # served count: connection severed
            self.index = OrderedDict()  # published chain-hash digests
            self.chains = []            # published token chains
            self.resumes = []           # payloads received via resume()

        def _publish(self, toks) -> None:
            self.chains.append(list(toks))
            for h in published_page_hashes(FP.encode(), toks, PAGE,
                                           PPS):
                self.index[h] = None

        def health(self):
            if self.dead:
                raise ReplicaUnreachable(f"{self.name} is down")
            det = {"ready": self.ready,
                   "queue_depth": self.queue_depth + self.pending,
                   "kv_free_pages": 1 << 20,
                   "kv_total_pages": 1 << 20,
                   "page_size": PAGE, "pages_per_slot": PPS,
                   "fingerprint": FP,
                   "prefix_index": list(self.index)[-512:]}
            # Each poll "works off" part of the backlog, so the
            # reported depth tracks recent assignment — without it
            # every score ties at zero and the name tie-break funnels
            # the whole fleet's traffic onto one replica.
            self.pending = max(0, self.pending - 8)
            return (200 if self.ready else 503), {"serving": det}

        def generate(self, payload, timeout=None):
            if self.dead:
                raise ReplicaUnreachable(f"{self.name} is down")
            if not self.ready:
                return 503, {"error": "draining", "tokens": []}
            self.served += 1
            self.pending += 1
            prompt = [int(tok) for tok in payload["tokens"]]
            m = int(payload.get("max_tokens", 32))
            if self.served == self.die_at:
                self.dead = True
                raise ReplicaUnreachable(f"{self.name} died mid-call")
            if self.served == self.drain_at:
                emitted = _complete(prompt, max(1, m // 2))
                self.ready = False
                return 503, {"error": "drained", "tokens": emitted}
            toks = _complete(prompt, m)
            self._publish(prompt + toks)
            return 200, {"tokens": toks, "finish_reason": "length"}

        def drain(self):
            if self.dead:
                raise ReplicaUnreachable(f"{self.name} is down")
            self.ready = False
            return 200, {"requests": [],
                         "prefixes": [list(c) for c in self.chains]}

        def prefixes(self):
            if self.dead:
                raise ReplicaUnreachable(f"{self.name} is down")
            return 200, {"prefixes": [list(c) for c in self.chains]}

        def resume(self, payload):
            if self.dead:
                raise ReplicaUnreachable(f"{self.name} is down")
            self.resumes.append(payload)
            for chain in payload.get("prefixes") or []:
                self._publish([int(tok) for tok in chain])
            self.ready = True
            return 200, {"installed":
                         len(payload.get("requests") or []),
                         "ready": True}

    # ---- leg 2: drain/death failover, digest-identical completions -------
    def _failover_leg() -> dict:
        lrng = _random.Random(7)
        reqs = []
        for _ in range(240):
            prompt = (headers[lrng.randrange(40)]
                      + [lrng.randrange(256)
                         for _ in range(lrng.randrange(4, 12))])
            reqs.append((prompt, 8 + lrng.randrange(24)))

        def _digest(runs) -> str:
            d = hashlib.sha256()
            for prompt, toks in runs:
                d.update(f"{len(prompt)}:".encode())
                d.update(",".join(str(int(tok))
                                  for tok in toks).encode())
            return d.hexdigest()

        reference = _digest((p, _complete(p, m)) for p, m in reqs)

        router = Router(RouterConfig(probe_base=0.0),
                        sleep=lambda s: None)
        fleet = [_SimReplica(f"r{j}") for j in range(4)]
        fleet[1].drain_at = 25  # drains mid-generation (503+partials)
        fleet[2].die_at = 40    # severed mid-call, no partials
        for rep in fleet:
            router.add_replica(rep.name, rep)
        router.poll()
        runs, continuations, failovers, aff_requests = [], 0, 0, 0
        for k, (prompt, m) in enumerate(reqs):
            if k % 16 == 0:
                router.poll()
            status, resp = router.dispatch({"tokens": prompt,
                                            "max_tokens": m})
            if status != 200:
                return {"requests": len(reqs),
                        "digest_identical": False,
                        "error": f"dispatch {status}: {resp}"}
            runs.append((prompt, resp["tokens"]))
            stamp = resp.get("router") or {}
            continuations += int(stamp.get("resubmits", 0))
            failovers += int(stamp.get("failovers", 0))
            if int(stamp.get("affinity_pages", 0)) > 0:
                aff_requests += 1
        return {"requests": len(reqs),
                "digest_identical": _digest(runs) == reference,
                "continuations": continuations,
                "failovers": failovers,
                "affinity_requests": aff_requests}

    # ---- leg 3: autoscaling with planner pricing -------------------------
    def _autoscale_leg() -> dict:
        router = Router(RouterConfig(probe_base=0.0),
                        sleep=lambda s: None)
        pool = {}

        def launch(name: str):
            rep = _SimReplica(name)
            pool[name] = rep
            return rep

        def retire(name: str) -> None:
            pool.pop(name, None)

        base = [_SimReplica(f"base{j}") for j in range(2)]
        for rep in base:
            pool[rep.name] = rep
            router.add_replica(rep.name, rep)
        # Warm the donor so scale-up has live prefixes to seed from.
        base[0].resume({"requests": [],
                        "prefixes": [headers[j] + [1]
                                     for j in range(8)]})
        router.poll()

        # hvd-mem pricing: one replica's serving footprint (KV pool +
        # prefix reserve) against a shrinking host-headroom ledger.
        replica_bytes = (kv_cache_bytes(4, 8, 64, 8, PPS, PAGE)
                         + prefix_pages_bytes(4, 8, 64, 64, PAGE))
        host = {"free": replica_bytes + replica_bytes // 2}
        scaler = FleetAutoscaler(
            router, launch, retire,
            AutoscaleConfig(min_replicas=2, max_replicas=4,
                            up_load=4.0, down_load=1.0, sustain=2,
                            cooldown=1),
            price=lambda: replica_bytes,
            headroom=lambda: host["free"])

        events, seeded_pages, oom_free = [], 0, True

        def tick() -> None:
            nonlocal seeded_pages, oom_free
            router.poll()
            e = scaler.observe()
            if e is None:
                return
            events.append(e)
            if e.startswith("up:"):
                host["free"] -= replica_bytes
                if host["free"] < 0:  # a boot the planner should have
                    oom_free = False  # vetoed landed on an OOM
                newcomer = pool.get(e.split(":", 1)[1])
                if newcomer is not None:
                    seeded_pages = max(seeded_pages,
                                       len(newcomer.index))
            elif e.startswith("down:"):
                host["free"] += replica_bytes

        # Spike: deep queues everywhere -> scale up (priced, seeded).
        for rep in pool.values():
            rep.queue_depth = 9
        for _ in range(4):
            tick()
        # Still spiking, headroom now exhausted -> veto, never a boot.
        for rep in pool.values():
            rep.queue_depth = 9
        for _ in range(4):
            tick()
        # Trough: fleet idles -> drain the booted replica back.
        for rep in pool.values():
            rep.queue_depth = 0
        for _ in range(4):
            tick()

        donated = any(rep.resumes for rep in base)
        return {"events": events,
                "scaled_up": any(e.startswith("up:") for e in events),
                "seeded_pages": seeded_pages,
                "veto": "veto:up" in events,
                "scaled_down": any(e.startswith("down:")
                                   for e in events),
                "prefixes_donated": donated,
                "fleet_final": router.replica_names(),
                "oom_free": oom_free and host["free"] >= 0}

    failover = _failover_leg()
    autoscale = _autoscale_leg()
    return {
        "metric": "routing_tokens_per_sec",
        "value": aff["tokens_per_sec"],
        "unit": "tokens/sec",
        "vs_baseline": round(aff["tokens_per_sec"]
                             / rr["tokens_per_sec"], 2)
        if rr["tokens_per_sec"] else None,
        "n_requests": n_requests,
        "n_replicas": n_replicas,
        "round_robin": rr,
        "affinity": aff,
        "p99_ttft_speedup": round(rr["p99_ttft_ms"]
                                  / aff["p99_ttft_ms"], 2),
        "tokens_per_sec_speedup": round(aff["tokens_per_sec"]
                                        / rr["tokens_per_sec"], 2),
        "affinity_hit_rate": aff["affinity_hit_rate"],
        "deterministic_replay": aff == aff_replay,
        "failover": failover,
        "autoscale": autoscale,
    }


def _probe_inner() -> int:
    """Tunnel probe child: one tiny jitted matmul with a host fetch.

    Cheap (~seconds when healthy) but exercises the whole path a real
    attempt needs — backend init, compile, execute, device→host copy.
    """
    import jax
    import jax.numpy as jnp

    x = jnp.ones((128, 128), jnp.bfloat16)
    y = float(jax.jit(lambda a: (a @ a).sum())(x))
    dev = jax.devices()[0]
    print(json.dumps({"ok": y == 128.0 * 128 * 128,
                      "platform": dev.platform,
                      "device_kind": dev.device_kind}))
    return 0


def _smoke_inner() -> int:
    """Eager-path smoke child: dynamic collectives on the real chip.

    The test suite pins the eager/coordinator path to CPU
    (tests/conftest.py); this is the on-TPU evidence that the dynamic
    path is not CPU-only — ≙ the reference exercising its NCCL path in
    CI (reference horovod/common/operations.cc:773-938).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd

    platform = jax.devices()[0].platform
    hvd.init()
    x = jnp.arange(8.0)
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, average=False)),
        np.arange(8.0) * hvd.size())
    assert hvd.allgather(x).shape[0] == 8 * hvd.size()
    np.testing.assert_allclose(np.asarray(hvd.broadcast(x, 0)),
                               np.arange(8.0))
    h = hvd.allreduce_async(x, average=True)
    while not hvd.poll(h):
        time.sleep(0.001)
    np.testing.assert_allclose(np.asarray(hvd.synchronize(h)),
                               np.arange(8.0))

    import torch

    from horovod_tpu.frontends import torch as hvd_torch

    t = torch.arange(8, dtype=torch.float32)
    hvd_torch.allreduce_(t, average=False)
    np.testing.assert_allclose(t.numpy(), np.arange(8.0) * hvd.size())
    print(json.dumps({"ok": True, "platform": platform,
                      "size": hvd.size()}))
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CPU sanity checks")
    ap.add_argument("--mode",
                    choices=["resnet", "control", "dataplane", "input",
                             "serving", "overlap", "pipeline",
                             "memory", "fused", "tuning", "routing"],
                    default="resnet",
                    help="control = control-plane negotiations/sec only "
                         "(no XLA, no TPU tunnel); dataplane = "
                         "steady-state fused-cycle latency + "
                         "dispatches/cycle, eager vs megakernel, on the "
                         "8-virtual-CPU-device mesh (no TPU tunnel); "
                         "input = steps/sec with a synthetic slow host "
                         "loader, prefetch+async on vs off (no TPU "
                         "tunnel); serving = hvd-serve tokens/sec, "
                         "continuous vs static batching on a seeded "
                         "ragged-arrival trace, plus the hvd-spec "
                         "prefix-cache and speculative-decoding legs "
                         "(no TPU tunnel); overlap "
                         "= backward/communication overlap steps/sec, "
                         "streamed vs serialized bucket dispatch on a "
                         "transformer-LM chain, plus the bitwise "
                         "param-identity gates (no TPU tunnel); "
                         "pipeline = 1F1B MPMD pipeline schedule vs the "
                         "GPipe-ordered dispatch of the same per-stage "
                         "executables — steps/sec, exposed-bubble "
                         "seconds, bitwise + reference parity gates "
                         "(no TPU tunnel); memory = hvd-mem planner "
                         "accuracy vs the live ledger, plan "
                         "determinism, and the seeded-OOM forensics "
                         "path (no TPU tunnel); fused = hvd-fuse "
                         "computation-collective kernels — bitwise vs "
                         "the unfused reference, one-dispatch-per-"
                         "group, and exposed-communication strictly "
                         "below the unfused leg (no TPU tunnel); "
                         "tuning = hvd-tune closed-loop convergence — "
                         "the real policy engine + hvd-mem pricing "
                         "over a deterministic mis-tuned fleet model "
                         "(no XLA, no TPU tunnel); routing = hvd-route "
                         "fleet dispatch — least-loaded + prefix-"
                         "affinity vs round-robin on a seeded million-"
                         "request heavy-tailed trace, drain/death "
                         "failover digest identity through the real "
                         "Router, and planner-priced autoscaling "
                         "(no XLA, no TPU tunnel)")
    ap.add_argument("--check-speedup", type=float, default=None,
                    help="control mode: exit nonzero when the cache-on/"
                         "cache-off speedup is below this bound; "
                         "dataplane mode: exit nonzero when megakernel/"
                         "eager throughput is below this bound OR the "
                         "dispatches/cycle reduction is < 2x OR the "
                         "identity/hierarchical checks fail; input mode: "
                         "exit nonzero when prefetch-on/off steps/sec is "
                         "below this bound OR the trained params differ; "
                         "serving mode: exit nonzero when continuous/"
                         "static tokens/sec is below this bound OR the "
                         "two schedulers' completions differ OR the "
                         "engine rollout is not bitwise-equal to the "
                         "non-incremental forward (CI gates); overlap "
                         "mode: exit nonzero when overlapped/serialized "
                         "steps/sec is below this bound OR any bitwise "
                         "param-identity gate fails (full-precision vs "
                         "the monolithic step, int8 vs the serialized "
                         "schedule); pipeline mode: exit nonzero when "
                         "1f1b/gpipe steps/sec is below this bound OR "
                         "the 1f1b exposed-bubble seconds are not "
                         "strictly below the gpipe leg's OR the "
                         "bitwise/reference parity gates fail")
    ap.add_argument("--check-spec-speedup", type=float, default=None,
                    help="serving mode: exit nonzero when speculative/"
                         "non-speculative tokens/sec on the seeded "
                         "heavy-tailed trace is below this bound, when "
                         "speculative completions are not bitwise-equal "
                         "to non-speculative greedy (the bitwise-greedy "
                         "acceptance rule), when a steady-state "
                         "speculative iteration is not exactly one "
                         "draft propose + ONE target verify executable "
                         "dispatch with zero eager launches, when the "
                         "prefix-cache leg's completions differ from "
                         "cache-off, or when the repeated-prefix trace "
                         "saves no prefill tokens")
    ap.add_argument("--check-wire-ratio", type=float, default=None,
                    help="dataplane mode: exit nonzero when the int8 "
                         "bytes-on-wire compression ratio is below this "
                         "bound, when the int8/int4 fused kernels do "
                         "not match the eager-quantized reference, or "
                         "when the int8 leg falls under a 0.5x "
                         "throughput floor vs the adjacent uncompressed "
                         "leg (parity on a quiet box; the floor keeps "
                         "the CI gate load-proof)")
    ap.add_argument("--check-memory-plan", type=float, default=None,
                    help="memory mode: exit nonzero when the planner's "
                         "framework-bytes prediction misses the "
                         "measured ledger high-watermark by more than "
                         "this percentage on either leg, when repeated "
                         "plans are not byte-identical, or when the "
                         "seeded RESOURCE_EXHAUSTED fails to dump the "
                         "executable + top ledger categories")
    ap.add_argument("--check-tree-frames", type=float, default=None,
                    help="with --mode control: fail unless rank-0 rx "
                         "frames per simulated cycle stay under "
                         "C*fanout*log_fanout(world) at every "
                         "simulated world size (ops/tree.py gate)")
    ap.add_argument("--control-seconds", type=float, default=1.0,
                    help="control mode: seconds per measurement leg")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--attempts", type=int, default=3,
                    help="retries around backend init/compile flakes")
    ap.add_argument("--attempt-timeout", type=float, default=600.0,
                    help="max seconds per attempt before the child is "
                         "killed; clamped to the remaining total budget")
    ap.add_argument("--total-budget", type=float,
                    default=float(os.environ.get(
                        "HVD_TPU_BENCH_TOTAL_BUDGET", "1500")),
                    help="total wall-clock budget for probe + all "
                         "attempts + smoke; sized to fit inside the "
                         "driver's outer timeout so a structured JSON "
                         "line is always printed")
    ap.add_argument("--no-space-to-depth", dest="space_to_depth",
                    action="store_false", default=True,
                    help="disable the MLPerf space-to-depth stem")
    ap.add_argument("--_inner", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--_probe", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--_eager_smoke", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.mode == "control":
        result = _control_bench(seconds=args.control_seconds)
        result["tree"] = _tree_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            speedup = result.get("speedup") or 0.0
            if speedup < args.check_speedup:
                print(f"FAIL: response-cache speedup {speedup}x is below "
                      f"the required {args.check_speedup}x",
                      file=sys.stderr)
                return 1
        if args.check_tree_frames is not None:
            # The scale-out gate (CI job tree-bench): at simulated
            # world=256 rank 0's per-cycle frame count must sit under
            # c * fanout * log_fanout(world) — i.e. the tree actually
            # deleted the O(world) frame funnel, structurally.
            failures = []
            for w in result["tree"]["worlds"]:
                bound = args.check_tree_frames * w["fanout_log_bound"]
                if w["tree_frames_per_cycle"] > bound:
                    failures.append(
                        f"world={w['world']}: "
                        f"{w['tree_frames_per_cycle']} rank-0 frames "
                        f"per cycle > allowed {bound:.0f}")
                if w["world"] >= 64 and w["tree_frames_per_cycle"] * 4 \
                        > w["flat_frames_per_cycle"]:
                    failures.append(
                        f"world={w['world']}: tree frames "
                        f"{w['tree_frames_per_cycle']} not ≤ 1/4 of "
                        f"flat {w['flat_frames_per_cycle']}")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "dataplane":
        # CPU-only like --mode control: force the 8-virtual-device mesh
        # BEFORE the first jax import so the dynamic path runs anywhere,
        # tunnel or no tunnel (same bootstrap as tests/conftest.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        result = _dataplane_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            if (result.get("speedup") or 0.0) < args.check_speedup:
                failures.append(
                    f"megakernel speedup {result.get('speedup')}x < "
                    f"required {args.check_speedup}x")
            if (result.get("dispatch_reduction") or 0.0) < 2.0:
                failures.append(
                    f"dispatches/cycle reduction "
                    f"{result.get('dispatch_reduction')}x < required 2x")
            if not result.get("bitwise_identical"):
                failures.append("megakernel results not bitwise-identical "
                                "to the per-tensor path")
            if not result.get("hierarchical_equal"):
                failures.append("hierarchical ICI×DCN allreduce not "
                                "equivalent to flat psum")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        if args.check_wire_ratio is not None:
            failures = []
            comp = result.get("compression") or {}
            int8 = comp.get("int8") or {}
            ratio = int8.get("compression_ratio") or 0.0
            if ratio < args.check_wire_ratio:
                failures.append(
                    f"int8 bytes-on-wire ratio {ratio}x < required "
                    f"{args.check_wire_ratio}x")
            for name in ("int8", "int4"):
                if not (comp.get(name) or {}).get("reference_equal"):
                    failures.append(
                        f"{name} fused kernel does not match the "
                        f"eager-quantized reference")
            # Throughput: the quantized kernel is still ONE dispatch
            # per group and measures at parity (~1.0x) on a quiet box;
            # the CI assertion is a regression FLOOR, not the parity
            # claim — shared-runner wall clocks swing ±40% under load
            # (same policy as the tier-1 bench contract test), and the
            # measured ratio rides the JSON either way.
            spd = int8.get("speedup_vs_uncompressed") or 0.0
            if spd < 0.5:
                failures.append(
                    f"int8 leg at {spd}x of the uncompressed "
                    f"megakernel throughput (floor 0.5x)")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "memory":
        # CPU-only like --mode dataplane: pin the 8-virtual-device mesh
        # before the first jax import (same bootstrap as conftest.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        result = _memory_bench()
        print(json.dumps(result))
        if args.check_memory_plan is not None:
            failures = []
            for leg in ("dataplane", "pipeline"):
                err = (result.get(leg) or {}).get(
                    "prediction_error_pct")
                if err is None or err > args.check_memory_plan:
                    failures.append(
                        f"{leg} planner prediction off by {err}% "
                        f"(bound {args.check_memory_plan}%)")
            if not result.get("plan_deterministic"):
                failures.append(
                    "repeated plans are not byte-identical")
            if not (result.get("oom_dump") or {}).get("ok"):
                failures.append(
                    f"seeded RESOURCE_EXHAUSTED did not produce the "
                    f"forensic dump: {result.get('oom_dump')}")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "fused":
        # CPU-only like --mode dataplane: pin the 8-virtual-device mesh
        # before the first jax import (same bootstrap as conftest.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        result = _fused_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            for name, ok in (result.get("bitwise") or {}).items():
                if not ok:
                    failures.append(
                        f"fused {name} program not bitwise-identical "
                        f"to the unfused reference")
            for leg, disp in (result.get("dispatches_per_fused_group")
                              or {}).items():
                if disp != 1:
                    failures.append(
                        f"{leg} fused group dispatched {disp} XLA "
                        f"executables per cycle (contract: exactly 1)")
            if not (result.get("exposed_comm")
                    or {}).get("strictly_below"):
                ec = result.get("exposed_comm") or {}
                failures.append(
                    f"fused exposed communication "
                    f"{ec.get('fused_us')}us not strictly below the "
                    f"unfused leg's {ec.get('unfused_us')}us")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "tuning":
        # Pure Python (policy engine + pricing formulas): no XLA, no
        # mesh, no tunnel.
        result = _tuning_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            if (result.get("speedup") or 0.0) < args.check_speedup:
                failures.append(
                    f"tuned/mis-tuned speedup {result.get('speedup')}x "
                    f"< required {args.check_speedup}x")
            if (result.get("vs_hand_tuned") or 0.0) < 0.9:
                failures.append(
                    f"converged throughput is "
                    f"{result.get('vs_hand_tuned')} of the hand-tuned "
                    f"reference (required: within 10%)")
            if (result.get("last_decision_window") or 0) > 60:
                failures.append(
                    f"last decision at window "
                    f"{result.get('last_decision_window')} "
                    f"(required: converged within 60 windows)")
            if not result.get("deterministic_replay"):
                failures.append("decision sequence not identical on "
                                "replay")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "routing":
        # Pure Python (router + autoscaler + queueing sim): no XLA, no
        # mesh, no tunnel.
        result = _routing_bench(smoke=args.smoke)
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            if (result.get("p99_ttft_speedup")
                    or 0.0) < args.check_speedup:
                failures.append(
                    f"p99 TTFT speedup {result.get('p99_ttft_speedup')}"
                    f"x over round-robin < required "
                    f"{args.check_speedup}x")
            if (result.get("tokens_per_sec_speedup")
                    or 0.0) < args.check_speedup:
                failures.append(
                    f"tokens/sec speedup "
                    f"{result.get('tokens_per_sec_speedup')}x over "
                    f"round-robin < required {args.check_speedup}x")
            if (result.get("affinity_hit_rate") or 0.0) <= 0.0:
                failures.append("affinity hit rate is zero — the "
                                "prefix index never routed a warm "
                                "header")
            if not result.get("deterministic_replay"):
                failures.append("placement sequence not identical on "
                                "replay")
            fo = result.get("failover") or {}
            if not fo.get("digest_identical"):
                failures.append(
                    "failover completions are not digest-identical to "
                    f"the single-replica reference ({fo.get('error')})")
            if (fo.get("continuations") or 0) < 1:
                failures.append("no drain continuation was exercised")
            if (fo.get("failovers") or 0) < 2:
                failures.append("drain+death failovers not exercised")
            auto = result.get("autoscale") or {}
            for gate, msg in (
                    ("scaled_up", "the spike never booted a replica"),
                    ("seeded_pages", "the booted replica was not "
                                     "prefix-seeded from a donor"),
                    ("veto", "the exhausted-headroom boot was not "
                             "vetoed by the planner price check"),
                    ("scaled_down", "the trough never drained a "
                                    "replica back"),
                    ("prefixes_donated", "the drained replica's "
                                         "prefix index was not "
                                         "donated to a survivor"),
                    ("oom_free", "a scale-up landed on an OOM")):
                if not auto.get(gate):
                    failures.append(f"autoscale: {msg} "
                                    f"(events={auto.get('events')})")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "input":
        # CPU-only like --mode dataplane: pin the 8-virtual-device mesh
        # before the first jax import (same bootstrap as conftest.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        result = _input_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            if (result.get("speedup") or 0.0) < args.check_speedup:
                failures.append(
                    f"input-pipeline speedup {result.get('speedup')}x < "
                    f"required {args.check_speedup}x")
            if not result.get("params_identical"):
                failures.append("trained params differ between prefetch "
                                "on and off")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "overlap":
        # CPU-only like --mode dataplane: pin the 8-virtual-device mesh
        # before the first jax import (same bootstrap as conftest.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        result = _overlap_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            if (result.get("speedup") or 0.0) < args.check_speedup:
                failures.append(
                    f"overlap speedup {result.get('speedup')}x (streamed "
                    f"vs serialized dispatch) < required "
                    f"{args.check_speedup}x")
            if not result.get("bitwise_identical"):
                failures.append(
                    "overlapped params not bitwise-identical to the "
                    "monolithic step")
            if not result.get("serial_identical"):
                failures.append(
                    "overlapped params not bitwise-identical to the "
                    "serialized schedule")
            if not result.get("segmented_close"):
                failures.append(
                    "segmented overlapped params diverge from the "
                    "monolithic step beyond float tolerance")
            int8 = result.get("int8") or {}
            if not int8.get("bitwise_identical"):
                failures.append(
                    "int8 overlapped params not bitwise-identical to "
                    "the int8 serialized schedule (per-bucket EF "
                    "residuals broken)")
            if not int8.get("quantized_active"):
                failures.append(
                    "int8 leg produced the full-precision params — the "
                    "quantized wire path never engaged")
            if (result.get("mp") or {}).get("status") == "failed":
                # 'unavailable' (jax without np>1 CPU collectives) and
                # 'skipped' pass; a REAL np=2 failure is a regression.
                failures.append(
                    f"np=2 mp overlap leg failed: "
                    f"{(result.get('mp') or {}).get('detail', '')[:300]}")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "pipeline":
        # CPU-only like --mode dataplane: pin the 8-virtual-device mesh
        # before the first jax import (same bootstrap as conftest.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        result = _pipeline_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            if (result.get("speedup") or 0.0) < args.check_speedup:
                failures.append(
                    f"pipeline speedup {result.get('speedup')}x (1f1b "
                    f"vs gpipe-ordered dispatch) < required "
                    f"{args.check_speedup}x")
            if not result.get("bitwise_identical"):
                failures.append(
                    "1f1b params/loss not bitwise-identical to the "
                    "gpipe-ordered dispatch of the same executables")
            if not result.get("reference_close"):
                failures.append(
                    "pipeline step diverges from the monolithic "
                    "microbatch-mean gradient beyond float tolerance")
            if not result.get("bubble_hidden"):
                exp = result.get("exposed_bubble_seconds_per_step", {})
                failures.append(
                    f"1f1b exposed-bubble seconds {exp.get('1f1b')} not "
                    f"strictly below the gpipe leg's {exp.get('gpipe')} "
                    f"(reduction not hidden in the schedule)")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args.mode == "serving":
        # CPU-only like --mode dataplane: pin the 8-virtual-device mesh
        # before the first jax import (same bootstrap as conftest.py).
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        result = _serving_bench()
        print(json.dumps(result))
        if args.check_speedup is not None:
            failures = []
            if (result.get("speedup") or 0.0) < args.check_speedup:
                failures.append(
                    f"continuous-batching speedup "
                    f"{result.get('speedup')}x < required "
                    f"{args.check_speedup}x")
            if not result.get("results_identical"):
                failures.append(
                    "continuous and static schedulers produced "
                    "different completions (batch-composition "
                    "invariance broken)")
            if not result.get("bitwise_identical"):
                failures.append(
                    "engine prefill+decode rollout diverges from the "
                    "non-incremental serving_forward")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        if args.check_spec_speedup is not None:
            failures = []
            spec = result.get("speculative", {})
            prefix = result.get("prefix_cache", {})
            if (spec.get("speedup") or 0.0) < args.check_spec_speedup:
                failures.append(
                    f"speculative speedup {spec.get('speedup')}x < "
                    f"required {args.check_spec_speedup}x")
            if not spec.get("bitwise_greedy"):
                failures.append(
                    "speculative completions diverge from "
                    "non-speculative greedy (bitwise-greedy acceptance "
                    "broken)")
            if (spec.get("verify_dispatches_per_iteration") != 1
                    or spec.get("propose_dispatches_per_iteration") != 1
                    or spec.get("eager_dispatches_per_iteration") != 0):
                failures.append(
                    f"speculative steady state is not 1 propose + 1 "
                    f"verify dispatch with zero eager launches "
                    f"(got propose="
                    f"{spec.get('propose_dispatches_per_iteration')}, "
                    f"verify="
                    f"{spec.get('verify_dispatches_per_iteration')}, "
                    f"eager="
                    f"{spec.get('eager_dispatches_per_iteration')})")
            if not prefix.get("bitwise_identical"):
                failures.append(
                    "prefix-cache completions diverge from cache-off")
            if (prefix.get("prefill_tokens_saved") or 0) <= 0:
                failures.append(
                    "repeated-prefix trace saved no prefill tokens")
            if failures:
                for f in failures:
                    print(f"FAIL: {f}", file=sys.stderr)
                return 1
        return 0

    if args._probe:
        return _probe_inner()
    if args._eager_smoke:
        return _smoke_inner()
    if not args._inner:
        return _supervise(args)

    # Child: one attempt, structured output either way.  The parent owns
    # retries and the kill-on-hang watchdog (the tunnel can wedge inside
    # a C call where no Python exception ever surfaces).
    try:
        if args.smoke:
            from horovod_tpu.models.resnet import ResNet18Thin

            result = run(batch_size=8, image_size=32, warmup=1, iters=3,
                         model_ctor=ResNet18Thin, num_classes=16)
        else:
            import functools

            from horovod_tpu.models.resnet import ResNet50

            ctor = functools.partial(
                ResNet50, space_to_depth=args.space_to_depth)
            result = run(batch_size=args.batch_size,
                         image_size=args.image_size,
                         warmup=args.warmup, iters=args.iters,
                         model_ctor=ctor)
    except Exception as e:  # noqa: BLE001 — structured failure output
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    value = result.pop("value")
    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3),
    }
    out.update(result)
    print(json.dumps(out))
    return 0


def _run_child(extra_args, timeout):
    """Run one child attempt; return (rc, payload, timed_out).

    ``payload`` is the last parseable JSON line on stdout (a child that
    completed the measurement may still wedge at exit in the tunnel —
    salvage its printed result).  A timed-out child is SIGKILLed —
    SIGTERM does nothing to a process wedged inside the tunnel's C
    layer (observed: a probe child survived ``timeout 360`` by 20+
    minutes).  Children share one persistent XLA compilation cache so
    a retry after a flake does not pay the full compile again.
    """
    import subprocess

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache")
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
    cmd = [sys.executable, os.path.abspath(__file__)] + extra_args
    timed_out = False
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env)
    try:
        try:
            stdout, _ = proc.communicate(timeout=timeout)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            timed_out = True
            proc.kill()  # SIGKILL — see docstring
            try:
                stdout, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                stdout = b""
            rc = 0
    finally:
        # ANY other exit path (KeyboardInterrupt, a raise from
        # communicate, the salvage timing out) must also SIGKILL the
        # child: a wedged tunnel child outlives SIGTERM and its parent
        # by 20+ minutes, eating the next attempt's budget.
        if proc.poll() is None:
            proc.kill()
            try:
                proc.communicate(timeout=10)
            except Exception:  # noqa: BLE001 — reaping is best-effort
                pass
    payload = None
    for ln in reversed((stdout or b"").decode(errors="replace")
                       .splitlines()):
        if not ln.strip().startswith("{"):
            continue
        try:
            payload = json.loads(ln)
            break
        except json.JSONDecodeError:
            continue
    return rc, payload, timed_out


def _control_or_error() -> dict:
    """The control-plane microbench for the supervised run's JSON —
    tunnel-immune, so it must never take the whole bench down either."""
    try:
        return _control_bench(seconds=0.5)
    except Exception as e:  # noqa: BLE001 — structured either way
        return {"error": f"{type(e).__name__}: {e}"}


def _child_bench_or_error(mode: str, timeout: float = 180.0) -> dict:
    """One CPU-pinned microbench mode in a CHILD process, for the
    supervised run's JSON (the parent may be bound to the TPU tunnel;
    the child's --mode handler re-pins its own env before the first jax
    import).  Tunnel-immune like the control number — every round
    records these figures even when the TPU takes the headline down."""
    import subprocess

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--mode", mode]
    try:
        out = subprocess.run(cmd, capture_output=True, timeout=timeout,
                             env=env)
        for ln in reversed(out.stdout.decode(errors="replace")
                           .splitlines()):
            if ln.strip().startswith("{"):
                return json.loads(ln)
        return {"error": f"no JSON from {mode} child "
                         f"(rc={out.returncode})"}
    except Exception as e:  # noqa: BLE001 — structured either way
        return {"error": f"{type(e).__name__}: {e}"}


def _dataplane_or_error(timeout: float = 180.0) -> dict:
    return _child_bench_or_error("dataplane", timeout)


def _input_or_error(timeout: float = 180.0) -> dict:
    return _child_bench_or_error("input", timeout)


def _serving_or_error(timeout: float = 240.0) -> dict:
    return _child_bench_or_error("serving", timeout)


def _overlap_or_error(timeout: float = 240.0) -> dict:
    # The supervised child runs the quick shape (smaller chain, one
    # timed block): its numbers ride the round JSON for context; the
    # full-size identity + throughput gates live in CI (overlap-bench).
    os.environ["HVD_TPU_BENCH_OVERLAP_QUICK"] = "1"
    try:
        return _child_bench_or_error("overlap", timeout)
    finally:
        os.environ.pop("HVD_TPU_BENCH_OVERLAP_QUICK", None)


def _pipeline_or_error(timeout: float = 240.0) -> dict:
    # Quick shape for the supervised child (smaller chain, one timed
    # block); the full-size gates live in CI (pipeline-bench).
    os.environ["HVD_TPU_BENCH_PIPELINE_QUICK"] = "1"
    try:
        return _child_bench_or_error("pipeline", timeout)
    finally:
        os.environ.pop("HVD_TPU_BENCH_PIPELINE_QUICK", None)


def _memory_or_error(timeout: float = 240.0) -> dict:
    return _child_bench_or_error("memory", timeout)


def _fail_json(error: str, attempts: int, attempt_log=None,
               control=None, dataplane=None, inputpipe=None,
               serving=None, overlap=None, pipeline=None,
               memory=None) -> int:
    """Persistent failure: one parseable JSON line, not a traceback.
    The control-, data-plane, input-pipeline, serving, overlap,
    pipeline and memory numbers still ride along — none can be taken
    down by the tunnel, so every round records at least those."""
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": error,
        "attempts": attempts,
        "attempt_log": attempt_log or [],
        "control_plane": control if control is not None
        else _control_or_error(),
        "data_plane": dataplane if dataplane is not None
        else _dataplane_or_error(),
        "input_pipeline": inputpipe if inputpipe is not None
        else _input_or_error(),
        "serving": serving if serving is not None
        else _serving_or_error(),
        "overlap": overlap if overlap is not None
        else _overlap_or_error(),
        "pipeline": pipeline if pipeline is not None
        else _pipeline_or_error(),
        "memory": memory if memory is not None
        else _memory_or_error(),
    }))
    return 1


# Seconds reserved at the end of the budget for printing the final JSON,
# and the floor below which another measurement attempt is pointless.
_BUDGET_RESERVE = 15.0
_MIN_ATTEMPT = 120.0
_PROBE_TIMEOUT = 75.0
_SMOKE_TIMEOUT = 150.0
# The probe phase may spend up to this fraction of the total budget
# retrying a down tunnel (round-4 post-mortem: one 75 s probe surrendered
# the whole round's number to a single tunnel blip; the tunnel is known
# to recover on the scale of minutes).
_PROBE_BUDGET_FRACTION = 0.55


def _supervise(args) -> int:
    """Budget-aware supervision; always emits ONE JSON line.

    Round-3 post-mortem (BENCH_r03.json rc=124): 3 × 600 s attempts plus
    backoff overran the driver's ~1800 s outer timeout, so the failure
    JSON never printed.  Now probe + attempts + smoke all draw from one
    total budget that fits inside the driver's window.
    """
    deadline = time.monotonic() + args.total_budget
    t_start = time.monotonic()
    attempt_log = []
    # Control-, data-plane, input-pipeline, serving, overlap and
    # pipeline microbenches first: host/CPU-only, tunnel-immune —
    # whatever happens to the TPU below, this round records all six.
    control = _control_or_error()
    dataplane = _dataplane_or_error()
    inputpipe = _input_or_error()
    serving = _serving_or_error()
    overlap = _overlap_or_error()
    pipeline = _pipeline_or_error()
    memory = _memory_or_error()

    def remaining() -> float:
        return deadline - time.monotonic()

    def log_event(kind: str, detail: str) -> None:
        attempt_log.append({"t": round(time.monotonic() - t_start, 1),
                            "event": kind, "detail": detail})
        print(f"[bench +{attempt_log[-1]['t']:.0f}s] {kind}: {detail}",
              file=sys.stderr)

    # Phase 0 — tunnel probe LOOP.  A dead tunnel often recovers within
    # minutes, so spend up to _PROBE_BUDGET_FRACTION of the budget
    # re-probing with backoff instead of surrendering the round's number
    # to one blip; a tunnel that stays dead still gets its structured
    # failure JSON with the full probe log.
    probe_deadline = (t_start
                      + _PROBE_BUDGET_FRACTION * args.total_budget)
    probe, probe_n, quick_fails = None, 0, 0
    while True:
        probe_n += 1
        probe_t = min(_PROBE_TIMEOUT,
                      max(10.0, remaining() - _BUDGET_RESERVE))
        rc, probe, timed_out = _run_child(["--_probe"], probe_t)
        # A salvaged ok payload from a timed-out child counts as a pass:
        # the tunnel's known failure mode includes completing the work
        # and then wedging at interpreter exit (see _run_child) — the
        # measurement loop tolerates that, so the probe must too.
        if probe and probe.get("ok"):
            log_event("probe_ok",
                      f"{probe.get('device_kind')} (probe {probe_n}"
                      + (", child wedged at exit)" if timed_out or rc
                         else ")"))
            break
        why = (f"timed out after {probe_t:.0f}s" if timed_out
               else f"rc={rc}: {probe}")
        log_event("probe_fail", f"probe {probe_n}: {why}")
        probe = None
        # A probe that exits nonzero in seconds is a deterministic
        # failure (misconfigured backend, import error) — cap its
        # retries; only tunnel HANGS (timeouts) earn the long backoff
        # campaign, since those are the ones observed to recover.
        if not timed_out:
            quick_fails += 1
            if quick_fails >= 3:
                break
        backoff = min(20.0 * probe_n, 60.0)
        # Continue only if a worst-case probe (backoff + full probe
        # timeout) still fits before the probe deadline, so the probe
        # phase cannot overshoot its budget share and squeeze the
        # measurement below the total-budget guarantee.
        if (time.monotonic() + backoff + _PROBE_TIMEOUT > probe_deadline
                or remaining() < _MIN_ATTEMPT + _BUDGET_RESERVE):
            break
        time.sleep(backoff)
    if probe is None:
        return _fail_json(
            f"tunnel probe failed {probe_n}x over "
            f"{time.monotonic() - t_start:.0f}s (TPU tunnel down/hung?)",
            attempts=0, attempt_log=attempt_log, control=control,
            dataplane=dataplane, inputpipe=inputpipe, serving=serving,
            overlap=overlap, pipeline=pipeline, memory=memory)

    # Phase 1 — measurement attempts, each clamped to remaining budget.
    last_err = "unknown"
    inner = ["--_inner", "--batch-size", str(args.batch_size),
             "--image-size", str(args.image_size),
             "--iters", str(args.iters), "--warmup", str(args.warmup)]
    if args.smoke:
        inner.append("--smoke")
    if not args.space_to_depth:
        inner.append("--no-space-to-depth")
    payload = None
    attempts_made = 0
    for attempt in range(args.attempts):
        budget = remaining() - _BUDGET_RESERVE
        if attempt > 0 and budget < _MIN_ATTEMPT:
            last_err += (f"; gave up after {attempt} attempt(s): "
                         f"{budget:.0f}s of budget left")
            break
        attempts_made += 1
        attempt_t = min(args.attempt_timeout, max(30.0, budget))
        rc, got, timed_out = _run_child(inner, attempt_t)
        if rc == 0 and got and got.get("value") is not None:
            payload = got
            log_event("measure_ok",
                      f"attempt {attempt + 1}: "
                      f"{got.get('value')} img/s/chip")
            break
        if timed_out:
            last_err = (f"attempt timed out after {attempt_t:.0f}s "
                        "(TPU tunnel hang?)")
        else:
            last_err = (got or {}).get(
                "error", f"child exited rc={rc} without a result")
        log_event("measure_fail", f"attempt {attempt + 1}: {last_err}")
        if attempt + 1 < args.attempts:
            time.sleep(min(10.0 * (attempt + 1),
                           max(0.0, remaining() - _MIN_ATTEMPT)))
    if payload is None:
        return _fail_json(last_err, attempts=attempts_made,
                          attempt_log=attempt_log, control=control,
                          dataplane=dataplane, inputpipe=inputpipe,
                          serving=serving, overlap=overlap,
                          pipeline=pipeline, memory=memory)

    # Phase 2 — eager/dynamic-path smoke on the real chip (budget
    # permitting).  Failure is reported, not fatal: the headline number
    # above is already measured.
    smoke_t = min(_SMOKE_TIMEOUT, remaining() - _BUDGET_RESERVE)
    if smoke_t >= 30.0:
        rc, smoke, timed_out = _run_child(["--_eager_smoke"], smoke_t)
        if rc == 0 and smoke and smoke.get("ok"):
            payload["eager_tpu_smoke"] = "ok"
            payload["eager_tpu_platform"] = smoke.get("platform")
        elif timed_out:
            payload["eager_tpu_smoke"] = (
                f"timed out after {smoke_t:.0f}s")
        else:
            payload["eager_tpu_smoke"] = f"failed rc={rc}: {smoke}"
    else:
        payload["eager_tpu_smoke"] = "skipped: budget exhausted"
    payload["control_plane"] = control
    payload["data_plane"] = dataplane
    payload["input_pipeline"] = inputpipe
    payload["serving"] = serving
    payload["overlap"] = overlap
    payload["pipeline"] = pipeline
    payload["memory"] = memory
    payload["attempt_log"] = attempt_log
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
