"""hvd-telemetry: always-on metrics, cluster aggregation, flight recorder.

Three pieces (docs/metrics.md):

* :mod:`~horovod_tpu.telemetry.registry` — a lock-free-hot-path metrics
  registry every runtime layer publishes into; ``hvd.metrics()`` is the
  local snapshot.
* cluster aggregation — ``hvd.cluster_metrics()`` pulls every rank's
  snapshot over the control plane (FRAME_METRICS, ops/transport.py) and
  reports fleet min/max/mean/p50/p90/p99 per metric.  An optional
  Prometheus/JSON HTTP exporter (``HVD_TPU_METRICS_PORT``) serves
  ``/metrics`` and ``/healthz`` on rank 0.
* :mod:`~horovod_tpu.telemetry.flight` — a per-rank ring buffer of
  recent control-plane events dumped to ``HVD_TPU_FLIGHT_DIR`` on
  stalls, mismatches, dead peers and drain/receive-thread exceptions.
"""

from __future__ import annotations

from typing import Dict

from . import flight  # noqa: F401  (stdlib-only; safe to import first)
from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    aggregate,
    bucket_edges,
    metrics_enabled,
)

# Process-global default registry (module import order is unimportant:
# every layer that instruments itself asks this object for its metric
# handles at import time).
_default = MetricsRegistry()

# Metric-name prefixes worth carrying in a flight dump's compact tail:
# the control-plane, data-plane and host counters that contextualize a
# stall, PLUS (hvd-mem satellite) the gauge families — queue depths,
# occupancy, checkpoint backlog, memory watermarks — so every stall,
# dead-peer and OOM dump is self-contained forensics (docs/metrics.md
# "Dump format").  Push-fed gauges (serving.queue_depth,
# input.prefetch_queue_depth, checkpoint.pending, serving.kv_free_pages,
# memory.step_watermark_bytes) are current at dump time; collector-fed
# gauges carry their last-snapshot value (collectors still don't run
# here — a dump may fire from under runtime locks).
_FLIGHT_TAIL_PREFIXES = ("collective.", "transport.", "host.",
                        "events.", "input.", "trace.", "chaos.",
                        "serving.", "pipeline.", "overlap.",
                        "checkpoint.", "handles.", "memory.",
                        "analysis.", "tuning.")

# Extra tail providers (keyed, replace-on-reregister): subsystems whose
# dump-time truth lives OUTSIDE the registry (the hvd-mem ledger) merge
# a flat name->value dict into every tail.  Providers must be cheap and
# take only leaf locks — dumps fire from failure paths.
_extra_tails: Dict[str, object] = {}


def register_flight_tail(key: str, fn) -> None:
    _extra_tails[key] = fn


def unregister_flight_tail(key: str) -> None:
    _extra_tails.pop(key, None)


def _flight_metrics_tail() -> Dict[str, object]:
    """The compact snapshot appended to every flight dump (satellite of
    hvd-trace, extended by hvd-mem): counters AND gauges as bare
    values, histograms as count+sum.  Collectors are skipped — they
    read runtime structures and a dump may fire from under runtime
    locks; the striped leaves below are lock-free and the extra tail
    providers take only leaf locks."""
    out: Dict[str, object] = {}
    for name, m in _default.snapshot(run_collectors=False).items():
        if not name.startswith(_FLIGHT_TAIL_PREFIXES):
            continue
        if m.get("type") == "histogram":
            out[name] = {"count": m.get("count", 0),
                         "sum": m.get("sum", 0)}
        else:
            out[name] = m.get("value", 0)
    for fn in list(_extra_tails.values()):
        try:
            out.update(fn())
        except Exception:  # noqa: BLE001 — the dump must not mask
            pass           # the original failure
    return out


flight.set_metrics_provider(_flight_metrics_tail)


def _collect_analysis(reg: MetricsRegistry) -> None:
    """Pull the hvd-analyze runtime checkers' counts (docs/metrics.md
    "Analysis checkers").  Pull-side by design: the checkers run under
    arbitrary runtime locks — races._check fires INSIDE registry
    methods holding ``MetricsRegistry._lock`` — so they keep plain ints
    and this collector (which runs at snapshot time, outside the
    registry lock) publishes them as monotonic gauges."""
    from ..analysis import donation as _donation
    from ..analysis import races as _races
    from ..analysis import threads as _threads

    reg.gauge("analysis.race_checks",
              "lockset verifications by the data-race detector").set(
        _races.check_count())
    reg.gauge("analysis.thread_role_asserts",
              "dynamic thread-role contract verifications").set(
        _threads.assert_count())
    reg.gauge("analysis.donation_poisoned",
              "buffers registered as donated by guard_dispatch").set(
        _donation.poison_count())


_default.register_collector("analysis", _collect_analysis)


def registry() -> MetricsRegistry:
    return _default


def counter(name: str, help: str = "") -> Counter:
    return _default.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _default.gauge(name, help)


def histogram(name: str, kind: str = "seconds", help: str = "") -> Histogram:
    return _default.histogram(name, kind, help)


def enabled() -> bool:
    return _default.enabled


def set_enabled(v: bool) -> None:
    """Master switch for the whole telemetry subsystem (registry AND
    flight recorder) — the bench's overhead A/B.  Re-enabling restores
    the flight recorder's own env gate."""
    _default.set_enabled(v)
    flight.recorder.enabled = bool(v) and flight.flight_enabled_env()


def metrics() -> Dict[str, dict]:
    """This rank's local metrics snapshot (collectors included)."""
    return _default.snapshot()


def cluster_metrics(timeout: float = 10.0) -> Dict[str, dict]:
    """Fleet-level aggregation: rank 0 pulls every rank's snapshot over
    the control plane (FRAME_METRICS) and merges them — min/max/mean
    (+ per-rank values) for counters/gauges, merged buckets with
    p50/p90/p99 for histograms.  Rank-0-only in multi-process mode
    (workers answer the pull automatically from their receive thread —
    they should call :func:`metrics` for their own local view);
    single-process mode aggregates the one local snapshot."""
    from ..core import state as _state

    _state._check_initialized()
    st = _state.global_state()
    local = metrics()
    if not st.multiprocess:
        return aggregate({0: local})
    if st.process_index != 0:
        raise RuntimeError(
            "cluster_metrics() aggregates on the rank-0 controller; this "
            "rank answers the controller's FRAME_METRICS pull "
            "automatically — use hvd.metrics() for its local snapshot.")
    per_rank = st.transport.collect_metrics(local, timeout=timeout)
    return aggregate(per_rank)


# -- stall/dead-peer helpers shared by coordinator + collective ------------

_M_STALLS = counter(
    "events.stall_warnings",
    "stall-watch warnings (tensors pending past the threshold)")
_M_DEAD_PEERS = counter(
    "events.dead_peers", "peer processes that died without a handshake")
_M_DUMPS = counter("flight.dumps", "flight-recorder dumps written")


def stall_event(warnings) -> None:
    """One stall-watch firing: count it, append the full warning text
    (which names the tensor and the non-ready ranks) to the flight ring,
    and dump the ring — the 'what happened in the last 2000 events
    before the stall' forensic record."""
    ws = list(warnings)
    if not ws:
        return
    _M_STALLS.inc(len(ws))
    for w in ws:
        flight.record("stall", w)
    if flight.dump("stall", extra={"warnings": ws}) is not None:
        _M_DUMPS.inc()


def dead_peer_event(detail: str) -> None:
    _M_DEAD_PEERS.inc()
    flight.record("dead_peer", detail)
    if flight.dump("dead-peer", extra={"detail": detail}) is not None:
        _M_DUMPS.inc()


def error_event(message: str) -> None:
    flight.record("error", message)
    if flight.dump("error", extra={"message": message}) is not None:
        _M_DUMPS.inc()


def transport_fault_event(reason: str, detail: str) -> None:
    """A control-plane fault boundary fired (hvd-chaos hardening):
    a peer disconnect entering its grace window, a completed session
    resume, a frame deadline.  Recorded AND dumped — the ring's tail is
    the forensic record naming the fault (tests assert on it)."""
    flight.record("transport_fault", reason, detail)
    if flight.dump(reason, extra={"detail": detail}) is not None:
        _M_DUMPS.inc()


def exception_event(where: str, text: str) -> None:
    flight.record("exception", where, text)
    if flight.dump(f"exception-{where}",
                   extra={"where": where, "traceback": text}) is not None:
        _M_DUMPS.inc()


# -- hvd-pipeline events (PR 5): input prefetch + checkpoint writer --------

_M_PREFETCH_ERRORS = counter(
    "input.prefetch_errors", "loader exceptions captured by prefetchers")
_M_CKPT_ERRORS = counter(
    "checkpoint.errors", "background checkpoint writes that failed")


def prefetch_error_event(detail: str) -> None:
    """A prefetch loader raised on the stager thread: count it and dump
    the flight ring — the exception itself re-raises at the consuming
    step (parallel/input.py), this is the forensic side channel."""
    _M_PREFETCH_ERRORS.inc()
    flight.record("prefetch_error", detail)
    if flight.dump("prefetch-error", extra={"detail": detail}) is not None:
        _M_DUMPS.inc()


def checkpoint_error_event(path: str, detail: str) -> None:
    """A background checkpoint write failed: the handle carries the
    exception to ``wait()``; this records the failure even for callers
    that never wait (fire-and-forget saves must not fail silently)."""
    _M_CKPT_ERRORS.inc()
    flight.record("checkpoint_error", path, detail)
    if flight.dump("checkpoint-error",
                   extra={"path": path, "detail": detail}) is not None:
        _M_DUMPS.inc()


def overlap_fallback_event(reason: str, detail: str) -> None:
    """The backward/communication-overlap step fell back to the
    monolithic program (parallel/overlap.py): flight-record the NAMED
    reason (``adasum``/``sparse``/``sub-mesh``/...) with its
    human-readable detail.  The ``overlap.fallbacks`` counter is
    incremented by the caller in lockstep — one counter tick, one
    flight event, one warn line per fallback.  Recorded but NOT
    dumped: a fallback is a degraded mode, not a failure."""
    flight.record("overlap_fallback", reason, detail)


def install_runtime_collector() -> None:
    """Register the pull-side collector over the runtime's existing
    cheap stats structs (CacheStats, MegakernelStats, the handle pool).
    Idempotent: keyed registration replaces the previous instance on
    re-init.  Collectors run at snapshot time only — the steady-state
    hot path never touches these gauges."""

    def collect(reg: MetricsRegistry) -> None:
        from ..core import state as _state
        from ..ops import megakernel as _mk

        st = _state.global_state()
        cache = st.response_cache
        if cache is not None:
            s = cache.stats
            reg.gauge("cache.hits").set(s.hits)
            reg.gauge("cache.misses").set(s.misses)
            reg.gauge("cache.flushes").set(s.flushes)
            reg.gauge("cache.downgrades").set(s.downgrades)
            reg.gauge("cache.inserts").set(s.inserts)
            reg.gauge("cache.replayed_tensors").set(s.replayed_tensors)
            reg.gauge("cache.plan_hits").set(s.plan_hits)
            reg.gauge("cache.plan_misses").set(s.plan_misses)
            reg.gauge("cache.entries").set(cache.live_entries())
            reg.gauge("cache.epoch").set(cache.epoch)
        hm = st.handle_manager
        if hm is not None:
            reg.gauge("handles.live").set(hm.live_count())
        ms = _mk.stats
        reg.gauge("megakernel.builds").set(ms.builds)
        reg.gauge("megakernel.build_seconds").set(
            round(ms.build_seconds, 6))
        reg.gauge("megakernel.compile_seconds").set(
            round(ms.compile_seconds, 6))
        reg.gauge("megakernel.cache_hits").set(ms.cache_hits)
        reg.gauge("megakernel.flushes").set(ms.flushes)
        reg.gauge("megakernel.launches").set(ms.launches)
        reg.gauge("megakernel.hier_launches").set(ms.hier_launches)
        reg.gauge("megakernel.executables").set(_mk.cache_size())
        reg.gauge("megakernel.warm_starts").set(ms.warm_starts)
        # Quantized allreduce (docs/metrics.md "Quantized reduction"):
        # cumulative logical vs wire bytes and their ratio — with the
        # identity compressor the ratio sits at 1.0; int8 ≈ 3.97, int4
        # ≈ 7.9.  The per-launch distribution rides the
        # collective.wire_bytes histogram (fed at launch time by the
        # executor, not by this collector).
        reg.gauge("megakernel.quant_launches").set(ms.quant_launches)
        reg.gauge("megakernel.logical_bytes").set(ms.logical_bytes)
        reg.gauge("megakernel.wire_bytes").set(ms.wire_bytes)
        reg.gauge("megakernel.residual_tensors").set(_mk.residual_count())
        reg.gauge("compression.ratio").set(
            round(ms.logical_bytes / ms.wire_bytes, 4)
            if ms.wire_bytes else 1.0)

    _default.register_collector("runtime", collect)
