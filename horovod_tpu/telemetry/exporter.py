"""HTTP exporter: a route registry serving ``/metrics`` + ``/healthz``
plus any routes other subsystems register (hvd-serve's ``/generate``).

Off by default.  ``HVD_TPU_METRICS_PORT=<port>`` makes ``hvd.init()``
start one on the rank-0 controller (``HVD_TPU_METRICS_ALL_RANKS=1`` for
every rank); ``hvd.shutdown()`` stops it.  Tests and embedders can run
one directly via :func:`start_exporter` (port 0 picks an ephemeral
port, exposed as ``exporter.port``).

There is ONE process-global :class:`RouteRegistry` (:func:`routes`):
every exporter instance serves it, so a subsystem that needs an HTTP
surface — serving's ``/generate`` front door, a probe endpoint —
registers a route instead of binding a second listener that would fight
the exporter over ``HVD_TPU_METRICS_PORT``.  Routes registered before
or after the server starts are equally visible (dispatch reads the
registry per request).

Endpoints:
  GET /metrics         Prometheus text exposition (``hvd_`` prefix,
                       histograms as cumulative ``_bucket{le=...}``)
  GET /metrics?format=json   the raw ``hvd.metrics()`` snapshot
  GET /healthz         ``{"status": "ok"|"NOT_READY", ...}`` — 200 when
                       every registered health contributor reports
                       ready, 503 otherwise (the load-balancer
                       contract: hvd-serve contributes NOT_READY until
                       its ``warm_start`` completes, docs/inference.md)

The server thread only ever *reads* registry snapshots — it takes no
runtime lock beyond the registry's own leaves, so a wedged control
plane cannot wedge the health endpoint (that is the point of it).
"""

from __future__ import annotations

import json
import select
import socket as _socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from ..analysis import lockorder as _lockorder
from ..analysis import threads as _athreads
from . import flight as _flight
from ..analysis import races as _races
from .registry import MetricsRegistry

_PROM_HELP_TYPES = {"counter": "counter", "gauge": "gauge",
                    "histogram": "histogram"}

# A route handler: (query_string, request_body) -> (status, body, ctype).
# Routes registered with pass_client=True receive a third argument, a
# :class:`ClientProbe`, so a long-blocking handler (serving's
# /generate) can notice its client vanished and abort the work instead
# of generating tokens nobody will read (hvd-chaos hardening).
RouteHandler = Callable[..., Tuple[int, bytes, str]]
# A health contributor: () -> (ready, payload_dict) — payload is merged
# into the /healthz JSON under the contributor's name.
HealthContributor = Callable[[], Tuple[bool, dict]]


class ClientProbe:
    """Liveness probe for one HTTP client connection.

    ``disconnected()`` is a zero-timeout ``select`` + ``MSG_PEEK``: a
    readable socket returning EOF means the client closed mid-request
    (an HTTP/1.1 client sends nothing after its request, so readable
    data that is NOT EOF — a pipelined request — reads as still
    connected).  The hvd-chaos ``serving.disconnect`` site injects a
    positive answer here, which is exactly where a real disconnect is
    observed."""

    def __init__(self, sock: Optional[_socket.socket]) -> None:
        self._sock = sock

    def disconnected(self) -> bool:
        from .. import chaos as _chaos

        if _chaos.active() and _chaos.fire("serving.disconnect") \
                is not None:
            return True
        if self._sock is None:
            return False
        try:
            readable, _, _ = select.select([self._sock], [], [], 0)
            if not readable:
                return False
            return self._sock.recv(1, _socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True


def prometheus_name(name: str) -> str:
    return "hvd_" + "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name)


def prometheus_text(snapshot: dict) -> str:
    """Render one registry snapshot in the Prometheus text exposition
    format (v0.0.4): counters/gauges as single samples, histograms as
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``."""
    lines = []
    for name, m in snapshot.items():
        pname = prometheus_name(name)
        mtype = _PROM_HELP_TYPES.get(m.get("type"), "untyped")
        lines.append(f"# TYPE {pname} {mtype}")
        if m.get("type") == "histogram":
            cum = 0
            for edge, n in m.get("buckets", []):
                cum += n
                lines.append(f'{pname}_bucket{{le="{edge:g}"}} {cum}')
            cum += m.get("overflow", 0)
            lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{pname}_sum {m.get('sum', 0)}")
            lines.append(f"{pname}_count {m.get('count', 0)}")
        else:
            lines.append(f"{pname} {m.get('value', 0)}")
    return "\n".join(lines) + "\n"


@_races.race_checked
class RouteRegistry:
    """Path → handler table shared by every exporter instance.

    ``register``/``unregister`` may run from any thread at any time
    relative to the server; dispatch takes a locked snapshot per
    request.  The lock is a leaf on the hvd-analyze lock-order graph —
    handlers run OUTSIDE it, so a slow handler (serving's blocking
    ``/generate``) never wedges registration or other routes."""

    def __init__(self) -> None:
        self._lock = _lockorder.make_lock("exporter.RouteRegistry._lock")
        # (method, path) -> (handler, pass_client)
        self._routes: Dict[Tuple[str, str],
                           Tuple[RouteHandler, bool]] = {}
        # guarded_by: _lock
        self._health: Dict[str, HealthContributor] = {}  # guarded_by: _lock

    def register(self, path: str, handler: RouteHandler,
                 methods: Tuple[str, ...] = ("GET",),
                 pass_client: bool = False) -> None:
        """Bind ``handler`` to ``path`` for ``methods`` (replaces any
        previous binding — re-init idempotency).  ``pass_client=True``
        hands the handler a :class:`ClientProbe` third argument so it
        can watch for a mid-request client disconnect."""
        with self._lock:
            for m in methods:
                self._routes[(m.upper(), path)] = (handler, pass_client)

    def unregister(self, path: str) -> None:
        with self._lock:
            for key in [k for k in self._routes if k[1] == path]:
                del self._routes[key]

    def register_health(self, name: str,
                        contributor: HealthContributor) -> None:
        """Add a readiness contributor to ``/healthz`` (keyed — a
        re-registration replaces the previous instance)."""
        with self._lock:
            self._health[name] = contributor

    def unregister_health(self, name: str) -> None:
        with self._lock:
            self._health.pop(name, None)

    def lookup(self, method: str,
               path: str) -> Optional[Tuple[RouteHandler, bool]]:
        """(handler, pass_client) for a bound route, else None."""
        with self._lock:
            return self._routes.get((method.upper(), path))

    def paths(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted({p for _, p in self._routes}))

    def health_payload(self) -> Tuple[int, dict]:
        """(status_code, payload): 200/"ok" when every contributor is
        ready, 503/"NOT_READY" otherwise — the load-balancer contract."""
        rank = None
        initialized = False
        try:
            from ..core import state as _state

            st = _state.global_state()
            initialized = bool(st.initialized)
            if initialized:
                rank = st.process_index
        except Exception:  # noqa: BLE001 — health must answer regardless
            pass
        with self._lock:
            contributors = dict(self._health)
        payload = {"rank": rank, "initialized": initialized}
        ready = True
        for name, fn in contributors.items():
            try:
                ok, detail = fn()
            except Exception as e:  # noqa: BLE001 — a broken
                ok, detail = False, {"error": str(e)}  # contributor is
                # a NOT_READY, not a 500
            ready = ready and bool(ok)
            payload[name] = detail
        payload["status"] = "ok" if ready else "NOT_READY"
        return (200 if ready else 503), payload


_routes = RouteRegistry()


def routes() -> RouteRegistry:
    """The process-global route registry every exporter serves."""
    return _routes


class MetricsExporter:
    """A daemon-threaded HTTP server bound to one metrics registry and
    the process-global route registry."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "0.0.0.0",
                 routes: Optional[RouteRegistry] = None) -> None:
        self.registry = registry
        # Default is the process-global registry (subsystems register
        # into it without holding an exporter reference); a private
        # RouteRegistry lets a second tier — hvd-route's front door —
        # serve its own /generate in the same process without fighting
        # a colocated replica over the path.
        self.routes = _routes if routes is None else routes
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:
                pass  # no per-request stderr chatter

            def _reply(self, code: int, body: bytes,
                       ctype: str) -> None:
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError) as e:
                    # The client vanished between the handler finishing
                    # and the response write (hvd-chaos hardening):
                    # nothing to deliver to, and one gone client must
                    # never take the server thread down with a
                    # traceback.  The handler-side ClientProbe catches
                    # MID-request disconnects; this catches the
                    # at-reply race.
                    _flight.record("client_gone_at_reply", self.path,
                                   f"{type(e).__name__}")

            def _dispatch(self, method: str, body: bytes) -> None:
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    code, payload = exporter.routes.health_payload()
                    self._reply(code, json.dumps(payload).encode(),
                                "application/json")
                    return
                if method == "GET" and path in ("/metrics",
                                                "/metrics.json"):
                    snap = exporter.registry.snapshot()
                    if path.endswith(".json") or "format=json" in query:
                        self._reply(200, json.dumps(snap).encode(),
                                    "application/json")
                    else:
                        self._reply(
                            200, prometheus_text(snap).encode(),
                            "text/plain; version=0.0.4")
                    return
                bound = exporter.routes.lookup(method, path)
                if bound is None:
                    self._reply(404, b"not found\n", "text/plain")
                    return
                handler, pass_client = bound
                try:
                    if pass_client:
                        code, out, ctype = handler(
                            query, body, ClientProbe(self.connection))
                    else:
                        code, out, ctype = handler(query, body)
                except Exception as e:  # noqa: BLE001 — one bad request
                    # must not kill the server thread
                    self._reply(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")
                    return
                self._reply(code, out, ctype)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                self._dispatch("GET", b"")

            def do_POST(self) -> None:  # noqa: N802 — http.server API
                n = int(self.headers.get("Content-Length") or 0)
                self._dispatch("POST", self.rfile.read(n) if n else b"")

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        def _serve() -> None:  # thread: exporter
            _athreads.set_role("exporter")
            self._server.serve_forever()

        self._thread = threading.Thread(
            target=_serve,
            name="hvd-metrics-exporter", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)


def start_exporter(registry: MetricsRegistry, port: int,
                   host: str = "0.0.0.0",
                   routes: Optional[RouteRegistry] = None
                   ) -> MetricsExporter:
    return MetricsExporter(registry, port, host=host, routes=routes)
