"""Optional HTTP exporter: Prometheus text / JSON ``/metrics`` + ``/healthz``.

Off by default.  ``HVD_TPU_METRICS_PORT=<port>`` makes ``hvd.init()``
start one on the rank-0 controller (``HVD_TPU_METRICS_ALL_RANKS=1`` for
every rank); ``hvd.shutdown()`` stops it.  Tests and embedders can run
one directly via :func:`start_exporter` (port 0 picks an ephemeral
port, exposed as ``exporter.port``).

Endpoints:
  GET /metrics         Prometheus text exposition (``hvd_`` prefix,
                       histograms as cumulative ``_bucket{le=...}``)
  GET /metrics?format=json   the raw ``hvd.metrics()`` snapshot
  GET /healthz         ``{"status": "ok", "rank": r, "initialized": b}``

The server thread only ever *reads* registry snapshots — it takes no
runtime lock beyond the registry's own leaf, so a wedged control plane
cannot wedge the health endpoint (that is the point of it).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .registry import MetricsRegistry

_PROM_HELP_TYPES = {"counter": "counter", "gauge": "gauge",
                    "histogram": "histogram"}


def prometheus_name(name: str) -> str:
    return "hvd_" + "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name)


def prometheus_text(snapshot: dict) -> str:
    """Render one registry snapshot in the Prometheus text exposition
    format (v0.0.4): counters/gauges as single samples, histograms as
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``."""
    lines = []
    for name, m in snapshot.items():
        pname = prometheus_name(name)
        mtype = _PROM_HELP_TYPES.get(m.get("type"), "untyped")
        lines.append(f"# TYPE {pname} {mtype}")
        if m.get("type") == "histogram":
            cum = 0
            for edge, n in m.get("buckets", []):
                cum += n
                lines.append(f'{pname}_bucket{{le="{edge:g}"}} {cum}')
            cum += m.get("overflow", 0)
            lines.append(f'{pname}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{pname}_sum {m.get('sum', 0)}")
            lines.append(f"{pname}_count {m.get('count', 0)}")
        else:
            lines.append(f"{pname} {m.get('value', 0)}")
    return "\n".join(lines) + "\n"


def _health_payload() -> dict:
    rank = None
    initialized = False
    try:
        from ..core import state as _state

        st = _state.global_state()
        initialized = bool(st.initialized)
        if initialized:
            rank = st.process_index
    except Exception:  # noqa: BLE001 — health must answer regardless
        pass
    return {"status": "ok", "rank": rank, "initialized": initialized}


class MetricsExporter:
    """A daemon-threaded HTTP server bound to one registry."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "0.0.0.0") -> None:
        self.registry = registry
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:
                pass  # no per-request stderr chatter

            def _reply(self, code: int, body: bytes,
                       ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    self._reply(200, json.dumps(
                        _health_payload()).encode(), "application/json")
                elif path in ("/metrics", "/metrics.json"):
                    snap = exporter.registry.snapshot()
                    if path.endswith(".json") or "format=json" in query:
                        self._reply(200, json.dumps(snap).encode(),
                                    "application/json")
                    else:
                        self._reply(
                            200, prometheus_text(snap).encode(),
                            "text/plain; version=0.0.4")
                else:
                    self._reply(404, b"not found\n", "text/plain")

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="hvd-metrics-exporter", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)


def start_exporter(registry: MetricsRegistry, port: int,
                   host: str = "0.0.0.0") -> MetricsExporter:
    return MetricsExporter(registry, port, host=host)
