"""Always-on metrics registry: counters, gauges, bounded histograms.

The hvd-telemetry tentpole (docs/metrics.md).  The reference Horovod's
only runtime introspection is the post-hoc Chrome-trace timeline
(docs/timeline.md); this registry answers "is the fleet healthy right
now": every runtime layer (coordinator, transport, cache, megakernel,
handles) publishes cheap in-memory metrics that ``hvd.metrics()``
snapshots locally and ``hvd.cluster_metrics()`` aggregates fleet-wide
over the control plane (FRAME_METRICS, ops/transport.py).

Design constraints (the control plane negotiates at 1e5+ requests/sec;
arXiv:1810.11112 shows per-phase instrumentation must not perturb the
phases it measures):

* **Lock-free hot path.**  Counters and histograms accumulate into
  *striped* per-thread cells — each writer thread owns a private cell
  no other thread ever writes, so increments are exact without any
  lock or atomic.  The only lock is a leaf taken once per
  (thread, metric) at first touch and briefly by snapshot readers to
  copy the cell list; it participates in the PR-1 lock-order graph and
  must stay a leaf (no other runtime lock is ever acquired under it).
* **No wall-clock in hot paths.**  The registry itself never reads a
  clock; latency histograms are fed by call sites that spend exactly
  one ``perf_counter`` pair per event (ops/collective.py).
* **Bounded histograms.**  Fixed log2 bucket edges per kind (seconds /
  bytes / count), indexed with one ``math.frexp`` call — no per-observe
  search, no unbounded label space.
* **Cheap when off.**  ``HVD_TPU_METRICS=0`` (or
  ``set_enabled(False)``) turns every ``inc``/``observe``/``set`` into
  a single flag check; the A/B is measured by ``bench.py --mode
  control`` and recorded in the bench JSON (≤ 5 % gate).

Pull metrics (values that already exist as cheap stats structs —
``CacheStats``, ``MegakernelStats``, the handle pool depth) are read by
registered *collectors* at snapshot time instead of being pushed on the
hot path: zero steady-state cost.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis import lockorder as _lockorder
from ..analysis import races as _races


def metrics_enabled() -> bool:
    """Default enablement (the registry is always-on unless opted out)."""
    return os.environ.get("HVD_TPU_METRICS", "1") != "0"


# Fixed log2 bucket-edge families: [2**lo, 2**hi) plus one overflow
# bucket.  Chosen once, shared by every histogram of the kind, so
# cluster aggregation can merge buckets without re-binning.
_KIND_EXPONENTS: Dict[str, Tuple[int, int]] = {
    # 2^-20 s ≈ 1 µs .. 2^5 = 32 s: spans a cache-hit negotiation to a
    # stall threshold.
    "seconds": (-20, 6),
    # 16 B .. 16 GiB: a scalar metric to a full fusion buffer.
    "bytes": (4, 35),
    # 1 .. 4096: fusion-group widths, frame batch sizes.
    "count": (0, 13),
}


def bucket_edges(kind: str) -> List[float]:
    lo, hi = _KIND_EXPONENTS[kind]
    return [float(2.0 ** e) for e in range(lo, hi)]


def _bucket_index(v: float, lo: int, nbuckets: int) -> int:
    """Index of the smallest power-of-two edge >= v (overflow =
    ``nbuckets``).  One C-level frexp, no search: v = m * 2**e with
    0.5 <= m < 1, so the covering edge is 2**e (or 2**(e-1) when v is
    itself a power of two)."""
    if v <= 0.0:
        return 0
    m, e = math.frexp(v)
    idx = (e if m > 0.5 else e - 1) - lo
    if idx < 0:
        return 0
    if idx > nbuckets:
        return nbuckets
    return idx


class _Striped:
    """Per-thread accumulation cells shared by Counter and Histogram.

    ``_cells`` is append-only under ``_cells_lock`` (a leaf: nothing
    else is ever acquired while holding it); each cell is written by
    exactly one thread, so the hot path is lock-free AND exact."""

    __slots__ = ("_tl", "_cells", "_cells_lock")

    def __init__(self) -> None:
        self._tl = threading.local()
        # One shared lock NAME for every metric: name-keyed lock-order
        # graph, one leaf node (analysis/lockorder.py).
        self._cells_lock = _lockorder.make_lock("telemetry._cells_lock")
        self._cells: List[list] = []  # guarded_by: _cells_lock

    def _cell(self, template: list) -> list:
        cell = getattr(self._tl, "cell", None)
        if cell is None:
            cell = list(template)
            with self._cells_lock:
                self._cells.append(cell)
            self._tl.cell = cell
        return cell

    def _cells_snapshot(self) -> List[list]:
        with self._cells_lock:
            return list(self._cells)


class Counter(_Striped):
    """Monotonic counter.  ``inc`` is exact under concurrent writers
    (striped cells) and lock-free after the first touch per thread."""

    __slots__ = ("name", "help", "_enabled_ref")

    def __init__(self, name: str, help: str, enabled_ref: list) -> None:
        super().__init__()
        self.name = name
        self.help = help
        self._enabled_ref = enabled_ref

    def inc(self, n: int = 1) -> None:
        if not self._enabled_ref[0]:
            return
        cell = getattr(self._tl, "cell", None)
        if cell is None:
            cell = self._cell([0])
        cell[0] += n

    @property
    def value(self):
        return sum(c[0] for c in self._cells_snapshot())

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Point-in-time value; ``set`` is a single atomic attribute store
    (collectors are the usual writer, at snapshot time)."""

    __slots__ = ("name", "help", "_enabled_ref", "_value")

    def __init__(self, name: str, help: str, enabled_ref: list) -> None:
        self.name = name
        self.help = help
        self._enabled_ref = enabled_ref
        self._value = 0

    def set(self, v) -> None:
        if self._enabled_ref[0]:
            self._value = v

    @property
    def value(self):
        return self._value

    def snapshot(self) -> dict:
        v = self._value
        return {"type": "gauge",
                "value": float(v) if isinstance(v, float) else v}


class Histogram(_Striped):
    """Bounded histogram over fixed log2 edges (see ``_KIND_EXPONENTS``).

    Per-thread cell layout: ``[sum, count, b_0 .. b_n, overflow]`` —
    one frexp + three in-cell adds per observe, exact under concurrent
    writers, no lock on the hot path."""

    __slots__ = ("name", "help", "kind", "_lo", "_n", "edges",
                 "_enabled_ref", "_template")

    def __init__(self, name: str, help: str, kind: str,
                 enabled_ref: list) -> None:
        super().__init__()
        if kind not in _KIND_EXPONENTS:
            raise ValueError(
                f"unknown histogram kind {kind!r}; expected one of "
                f"{sorted(_KIND_EXPONENTS)}")
        self.name = name
        self.help = help
        self.kind = kind
        lo, hi = _KIND_EXPONENTS[kind]
        self._lo = lo
        self._n = hi - lo
        self.edges = bucket_edges(kind)
        self._enabled_ref = enabled_ref
        self._template = [0.0, 0] + [0] * (self._n + 1)

    def observe(self, v) -> None:
        if not self._enabled_ref[0]:
            return
        cell = getattr(self._tl, "cell", None)
        if cell is None:
            cell = self._cell(self._template)
        v = float(v)
        cell[0] += v
        cell[1] += 1
        cell[2 + _bucket_index(v, self._lo, self._n)] += 1

    def snapshot(self) -> dict:
        total = list(self._template)
        for c in self._cells_snapshot():
            for i, v in enumerate(c):
                total[i] += v
        return {
            "type": "histogram",
            "kind": self.kind,
            "sum": total[0],
            "count": total[1],
            "buckets": [[edge, total[2 + i]]
                        for i, edge in enumerate(self.edges)],
            "overflow": total[2 + self._n],
        }


@_races.race_checked
class MetricsRegistry:
    """Name-keyed metric table + snapshot-time collectors.

    ``_lock`` guards only metric creation and the collector table; it
    is a leaf in the lock-order graph and is never held while user code
    (collectors) runs."""

    def __init__(self, enabled: Optional[bool] = None) -> None:
        # Shared mutable flag cell: every metric holds a reference, so
        # set_enabled flips the whole registry with one store and the
        # hot path pays a single list-index check.
        self._enabled_ref = [metrics_enabled() if enabled is None
                             else bool(enabled)]
        self._lock = _lockorder.make_lock("MetricsRegistry._lock")
        self._metrics: Dict[str, object] = {}  # guarded_by: _lock
        self._collectors: Dict[str, Callable] = {}  # guarded_by: _lock

    @property
    def enabled(self) -> bool:
        return self._enabled_ref[0]

    def set_enabled(self, v: bool) -> None:
        self._enabled_ref[0] = bool(v)

    def _get_or_create(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args, self._enabled_ref)
                self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help)

    def histogram(self, name: str, kind: str = "seconds",
                  help: str = "") -> Histogram:
        m = self._get_or_create(name, Histogram, help, kind)
        if m.kind != kind:
            raise TypeError(
                f"metric {name!r} already registered as a "
                f"{m.kind!r} histogram, not {kind!r}")
        return m

    def register_collector(self, key: str, fn: Callable) -> None:
        """Register (or replace) a pull-side collector: ``fn(registry)``
        runs at snapshot time and typically sets gauges from existing
        cheap stats structs.  Keyed so a re-init replaces rather than
        stacks the runtime collector."""
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    def snapshot(self, run_collectors: bool = True) -> Dict[str, dict]:
        """Consistent-enough point-in-time view: collectors run first
        (outside any lock), then every metric renders its current value.
        A failing collector is skipped — observability must never take
        the runtime down."""
        if run_collectors and self.enabled:
            with self._lock:
                collectors = list(self._collectors.values())
            for fn in collectors:
                try:
                    fn(self)
                except Exception:  # noqa: BLE001 — never break snapshot
                    pass
        with self._lock:
            items = sorted(self._metrics.items())
        return {name: m.snapshot() for name, m in items}


# ---------------------------------------------------------------------------
# Cluster aggregation (consumed by hvd.cluster_metrics)
# ---------------------------------------------------------------------------

def quantile_from_buckets(buckets: List[List[float]], overflow: int,
                          count: int, q: float) -> Optional[float]:
    """Upper-edge quantile estimate from log2 buckets (the standard
    Prometheus-histogram convention: report the edge of the bucket the
    q-th observation falls in)."""
    if count <= 0:
        return None
    target = q * count
    cum = 0
    for edge, n in buckets:
        cum += n
        if cum >= target:
            return edge
    return float("inf") if overflow else (buckets[-1][0] if buckets
                                          else None)


def aggregate(snapshots: Dict[int, Dict[str, dict]]) -> Dict[str, dict]:
    """Fleet-level view over per-rank snapshots: min/max/mean/sum for
    scalars, merged buckets + p50/p90/p99 for histograms.  A metric
    missing on some ranks aggregates over the ranks that have it
    (``ranks`` records how many)."""
    names: Dict[str, List[Tuple[int, dict]]] = {}
    for rank in sorted(snapshots):
        for name, m in snapshots[rank].items():
            names.setdefault(name, []).append((rank, m))
    out: Dict[str, dict] = {}
    for name, entries in sorted(names.items()):
        kind = entries[0][1].get("type")
        if kind == "histogram":
            merged: Dict[float, int] = {}
            total_sum = 0.0
            total_count = 0
            overflow = 0
            for _rank, m in entries:
                total_sum += m.get("sum", 0.0)
                total_count += m.get("count", 0)
                overflow += m.get("overflow", 0)
                for edge, n in m.get("buckets", []):
                    merged[edge] = merged.get(edge, 0) + n
            buckets = sorted(merged.items())
            agg = {
                "type": "histogram",
                "ranks": len(entries),
                "count": total_count,
                "sum": total_sum,
                "mean": (total_sum / total_count) if total_count else None,
                "overflow": overflow,
            }
            for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                agg[key] = quantile_from_buckets(
                    [list(b) for b in buckets], overflow, total_count, q)
            out[name] = agg
        else:
            values = [float(m.get("value", 0)) for _rank, m in entries]
            per_rank = {rank: m.get("value", 0) for rank, m in entries}
            out[name] = {
                "type": kind,
                "ranks": len(values),
                "min": min(values),
                "max": max(values),
                "mean": sum(values) / len(values),
                "sum": sum(values),
                "per_rank": per_rank,
            }
    return out
