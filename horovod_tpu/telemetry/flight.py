"""Crash/stall flight recorder: a per-rank ring of control-plane events.

Today's failure story for a distributed stall is a one-line warning on
rank 0 ("Tensor X has been pending for 60s...").  The flight recorder
turns that into a replayable forensic record: every rank keeps a
fixed-size in-memory ring of recent control-plane events (negotiation
submits, broadcast responses, coalesced frames, cache epoch
transitions, lock-order edges, withdrawals) and, when something goes
wrong — a stall warning, a cross-rank mismatch diagnostic, a dead-peer
poison, an unhandled exception on the drain/receive threads — dumps the
ring to ``HVD_TPU_FLIGHT_DIR`` as structured JSON whose tail names the
exact divergence point (docs/metrics.md documents the format).

Hot-path budget: ``record`` is one ``time.monotonic`` read plus one
``deque.append`` (atomic in CPython — no lock).  Recording is on by
default (``HVD_TPU_FLIGHT=0`` opts out; ``telemetry.set_enabled(False)``
silences it together with the metrics registry); *dumping* additionally
requires ``HVD_TPU_FLIGHT_DIR`` to be set.

This module is intentionally stdlib-only (no imports from the rest of
the package) so low-level modules — including the lock-order detector,
which everything else imports — can feed it without cycles.

Env contract:
  HVD_TPU_FLIGHT=0          disable recording (default on)
  HVD_TPU_FLIGHT_DIR        directory for dump files (unset = no dumps)
  HVD_TPU_FLIGHT_EVENTS     ring capacity (default 2000)
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 2000

# Dumps are rate-limited per reason and capped per process: a stall
# that warns every tick must not fill the disk with identical rings.
MIN_DUMP_INTERVAL_SECONDS = 5.0
MAX_DUMPS_PER_PROCESS = 50

_SAN_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def flight_enabled_env() -> bool:
    return os.environ.get("HVD_TPU_FLIGHT", "1") != "0"


def flight_dir() -> Optional[str]:
    return os.environ.get("HVD_TPU_FLIGHT_DIR") or None


# Compact metrics tail appended to every dump: a stall/dead-peer dump
# then carries the collective/transport/host counters at dump time, so
# the forensic record is self-contained — no separate hvd.metrics()
# call to correlate by hand.  Injected (set_metrics_provider, from
# telemetry/__init__.py) so this module stays stdlib-only.
_metrics_provider = None


def set_metrics_provider(fn) -> None:
    """Install the callable whose dict becomes each dump's ``metrics``
    tail (None clears it).  The provider must be cheap and lock-free —
    dumps fire from failure paths that may hold runtime locks."""
    global _metrics_provider
    _metrics_provider = fn


def _metrics_tail() -> Optional[dict]:
    if _metrics_provider is None:
        return None
    try:
        return _metrics_provider()
    except Exception:  # noqa: BLE001 — the dump must not mask failures
        return None


def _rank_of() -> int:
    """Best-effort rank for dump filenames; resolved lazily so this
    module never imports runtime state at load time."""
    try:
        from ..core import state as _state

        st = _state.global_state()
        if st.initialized:
            return st.process_index
    except Exception:  # noqa: BLE001 — dumping must never raise
        pass
    for var in ("HVD_TPU_RANK", "JAX_PROCESS_INDEX", "RANK"):
        v = os.environ.get(var)
        if v and v.isdigit():
            return int(v)
    return 0


class FlightRecorder:
    """Fixed-size ring of (monotonic, kind, args) event tuples."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        self.capacity = capacity if capacity is not None else int(
            os.environ.get("HVD_TPU_FLIGHT_EVENTS", str(DEFAULT_CAPACITY)))
        self.enabled = (flight_enabled_env() if enabled is None
                        else bool(enabled))
        # deque.append/popleft are atomic under the GIL: the hot path
        # takes no lock.  The plain (unchecked) lock below guards ONLY
        # the cold dump bookkeeping; it nests inside no other lock and
        # no runtime lock is acquired while holding it.
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._dump_lock = threading.Lock()
        self._last_dump: Dict[str, float] = {}
        self._dump_count = 0

    # -- hot path ----------------------------------------------------------
    def record(self, kind: str, *args) -> None:
        """Append one event.  ``args`` should be small scalars/strings
        already formatted — the recorder stores them as-is and only
        stringifies at dump time."""
        if self.enabled:
            self._events.append((time.monotonic(), kind, args))

    # -- cold paths --------------------------------------------------------
    def snapshot(self) -> List[tuple]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def dump(self, reason: str, extra: Optional[dict] = None,
             directory: Optional[str] = None) -> Optional[str]:
        """Write the ring to ``<dir>/hvd_flight_rank<r>_<seq>_<reason>.json``.

        Returns the path, or None when dumping is disabled, the
        per-reason rate limit applies, or the per-process cap is
        reached.  Never raises: the recorder is a diagnostic of last
        resort and must not mask the original failure."""
        d = directory or flight_dir()
        if d is None or not self.enabled:
            return None
        now = time.monotonic()
        with self._dump_lock:
            last = self._last_dump.get(reason)
            if last is not None and now - last < MIN_DUMP_INTERVAL_SECONDS:
                return None
            if self._dump_count >= MAX_DUMPS_PER_PROCESS:
                return None
            self._last_dump[reason] = now
            self._dump_count += 1
            seq = self._dump_count
        try:
            rank = _rank_of()
            events = [
                {"t": round(t, 6), "kind": kind,
                 "args": [a if isinstance(a, (int, float)) else str(a)
                          for a in args]}
                for t, kind, args in self.snapshot()
            ]
            payload = {
                "format": "hvd-flight-v1",
                "reason": reason,
                "rank": rank,
                "pid": os.getpid(),
                "wall_time": time.time(),
                "monotonic": now,
                "capacity": self.capacity,
                "extra": extra or {},
                "events": events,
            }
            tail = _metrics_tail()
            if tail is not None:
                payload["metrics"] = tail
            os.makedirs(d, exist_ok=True)
            slug = _SAN_RE.sub("-", reason)[:48] or "event"
            path = os.path.join(
                d, f"hvd_flight_rank{rank}_{seq:03d}_{slug}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1)
            os.replace(tmp, path)  # readers never see a partial file
            return path
        except Exception:  # noqa: BLE001 — see docstring
            return None


# Process-global recorder every runtime layer feeds.
recorder = FlightRecorder()


def record(kind: str, *args) -> None:
    recorder.record(kind, *args)


def dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    return recorder.dump(reason, extra=extra)


def snapshot() -> List[tuple]:
    return recorder.snapshot()
