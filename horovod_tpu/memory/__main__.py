"""``python -m horovod_tpu.memory`` — the no-hardware memory dryrun
(docs/memory.md; the ``hvd.schedule_plan`` convention).

``--plan`` prints one deterministic JSON plan for a named model and its
what-if knobs; identical arguments produce byte-identical output (the
CI ``memory`` job gates this).  No devices are touched and nothing is
compiled — answering "will this config fit" must not itself need the
hardware it is sizing.

Examples::

  python -m horovod_tpu.memory --plan --model transformer_lm \\
      --batch-size 64 --world 8 --capacity-bytes $((16 << 30))
  python -m horovod_tpu.memory --plan --model pipeline \\
      --stages 4 --microbatches 8 --schedule gpipe   # the what-if
  python -m horovod_tpu.memory --plan --model serving --kv-slots 32
"""

from __future__ import annotations

import argparse
import sys

from . import planner


def _build(args: argparse.Namespace) -> "planner.MemoryPlan":
    cap = args.capacity_bytes
    if args.model == "dataplane":
        return planner.plan_dataplane(
            tensors=args.tensors, elems=args.elems, world=args.world,
            dtype=args.dtype, fusion_threshold=args.fusion_threshold,
            capacity=cap)
    if args.model == "pipeline":
        return planner.plan_pipeline(
            n_stages=args.stages, num_microbatches=args.microbatches,
            microbatch_rows=args.microbatch_rows, width=args.width,
            world=args.world, schedule=args.schedule,
            interleave=args.interleave, dtype=args.dtype, capacity=cap)
    if args.model == "serving":
        return planner.plan_serving(
            n_layers=args.layers, n_heads=args.heads,
            head_dim=args.head_dim, max_slots=args.kv_slots,
            pages_per_slot=args.kv_pages, page_size=args.page_size,
            world=args.world, dtype=args.dtype,
            prefix_pages=args.prefix_pages,
            draft_layers=args.draft_layers,
            vocab_size=args.vocab_size, capacity=cap)
    return planner.plan_transformer_lm(
        vocab_size=args.vocab_size, d_model=args.d_model,
        n_heads=args.heads, n_layers=args.layers, d_ff=args.d_ff,
        max_seq_len=args.seq_len, batch_size=args.batch_size,
        world=args.world, optimizer=args.optimizer,
        prefetch_depth=args.prefetch_depth, dtype=args.dtype,
        capacity=cap)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.memory",
        description="static HBM planner: predict peak per-rank bytes "
                    "and answer what-if questions without hardware")
    ap.add_argument("--plan", action="store_true",
                    help="print the resolved plan JSON (deterministic: "
                         "same config => byte-identical output)")
    ap.add_argument("--model", default="transformer_lm",
                    choices=list(planner.model_names()))
    ap.add_argument("--world", type=int, default=1,
                    help="replica count (per-rank figures divide the "
                         "batch by it)")
    ap.add_argument("--capacity-bytes", type=int, default=None,
                    help="advertised per-rank HBM; adds fits/headroom "
                         "to the plan")
    ap.add_argument("--dtype", default="float32")
    # transformer_lm / serving model shape
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--optimizer", default="adam")
    ap.add_argument("--prefetch-depth", type=int, default=2)
    ap.add_argument("--head-dim", type=int, default=16)
    # serving KV what-ifs
    ap.add_argument("--kv-slots", type=int, default=8)
    ap.add_argument("--kv-pages", type=int, default=8,
                    help="pages per slot")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--prefix-pages", type=int, default=0,
                    help="dedicated shared-prefix page reserve "
                         "(hvd-spec; the serving.prefix_pages ledger "
                         "partition)")
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="speculative-decoding draft model depth "
                         "(prices serving.draft_kv + "
                         "serving.draft_params; 0 = no draft)")
    # pipeline what-ifs
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--microbatch-rows", type=int, default=32)
    ap.add_argument("--width", type=int, default=96)
    ap.add_argument("--schedule", default=None,
                    choices=["1f1b", "gpipe"],
                    help="pipeline schedule what-if (default: the "
                         "HVD_TPU_PIPELINE_SCHEDULE env / 1f1b)")
    ap.add_argument("--interleave", type=int, default=None)
    # dataplane what-ifs
    ap.add_argument("--tensors", type=int, default=32)
    ap.add_argument("--elems", type=int, default=256)
    ap.add_argument("--fusion-threshold", type=int, default=None)
    args = ap.parse_args(argv)

    if not args.plan:
        ap.print_help()
        return 2
    try:
        plan = _build(args)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(plan.to_json())
    if plan.capacity_bytes and not plan.to_dict()["fits"]:
        return 3  # scriptable "does not fit" verdict
    return 0


if __name__ == "__main__":
    sys.exit(main())
