"""OOM forensics: RESOURCE_EXHAUSTED capture at the dispatch sites
(hvd-mem piece 3, docs/memory.md).

An XLA out-of-memory today is a bare ``RESOURCE_EXHAUSTED: Out of
memory while trying to allocate ...`` traceback with no record of WHAT
was holding HBM.  This module turns it into a forensic flight dump:

* every framework dispatch site — megakernel launches
  (ops/megakernel.py), serving prefill/decode (serving/engine.py),
  pipeline stage programs (parallel/pipeline.py) — runs inside
  :func:`guard`, which catches RESOURCE_EXHAUSTED and emits a
  flight-recorder dump whose tail names the **failing executable**, the
  **top ledger categories** (who was holding what), the **predicted vs
  observed** bytes for the executable, the backend's own
  ``memory_stats`` and a ``jax.live_arrays()`` attribution sweep — then
  re-raises unchanged (forensics must not change failure semantics);
* ``HVD_TPU_MEM_CAPACITY=<bytes>`` simulates a small device: a dispatch
  whose predicted footprint would push the ledger past the advertised
  capacity raises a deterministic :class:`ResourceExhaustedError`
  through the SAME path — how the acceptance test (and an operator
  dry-running a risky config) seeds an OOM without hardware;
* :func:`preflight_warn` is the launch-time half: ``hvd.init()`` and
  the train-step builders compare a static plan against the advertised
  capacity and WARN before the first step, pointing at
  ``python -m horovod_tpu.memory --plan``.
"""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Dict, Optional

from .. import telemetry as _telemetry
from ..telemetry import flight as _flight
from . import ledger as _ledger

CAPACITY_ENV = "HVD_TPU_MEM_CAPACITY"

_M_OOMS = _telemetry.counter(
    "memory.oom_events",
    "RESOURCE_EXHAUSTED dispatches captured (real or simulated)")
_M_PREFLIGHT = _telemetry.counter(
    "memory.preflight_warnings",
    "static plans that exceeded the advertised HBM capacity at init/"
    "build time")


class ResourceExhaustedError(RuntimeError):
    """Simulated-capacity OOM (``HVD_TPU_MEM_CAPACITY``).  The message
    leads with RESOURCE_EXHAUSTED so every detector — including
    operators grepping logs — treats it exactly like XLA's own."""


def validate_env() -> None:
    """Fail ``hvd.init()`` on a malformed capacity knob (the standard
    named-knob contract)."""
    v = os.environ.get(CAPACITY_ENV)
    if v:
        try:
            ok = int(v) > 0
        except ValueError:
            ok = False
        if not ok:
            raise ValueError(
                f"{CAPACITY_ENV}={v!r}: expected a positive integer "
                f"byte count (the simulated/advertised per-rank HBM "
                f"capacity)")


def advertised_capacity() -> Optional[int]:
    """Per-DEVICE HBM capacity in bytes: the ``HVD_TPU_MEM_CAPACITY``
    override (simulation / operator pin) wins, else the backend's
    ``memory_stats()['bytes_limit']`` where provided (itself a
    per-device figure), else None (an unknown capacity disables the
    pre-flight and simulation paths — never guessed).  Every
    comparison site feeds per-device estimates (docs/memory.md)."""
    v = os.environ.get(CAPACITY_ENV)
    if v:
        try:
            return int(v)
        except ValueError:
            return None
    stats = _ledger.device_memory_stats()
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    return None


def is_resource_exhausted(exc: BaseException) -> bool:
    """True for XLA's RESOURCE_EXHAUSTED family (XlaRuntimeError text
    contract — stable across jaxlib versions) and this module's
    simulated variant."""
    if isinstance(exc, ResourceExhaustedError):
        return True
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "Out of memory" in text


def oom_event(executable: str, exc: BaseException,
              predicted_bytes: Optional[int] = None) -> Optional[str]:
    """Count + flight-record + dump one OOM.  The dump's ``extra``
    carries everything the post-mortem needs: the failing executable,
    the top-3 ledger categories at failure time, predicted vs observed
    bytes, the backend's memory_stats and the live-array sweep.
    Returns the dump path (None when dumping is off)."""
    _M_OOMS.inc()
    top = _ledger.ledger.top(3)
    _flight.record("oom", executable,
                   f"{type(exc).__name__}", _ledger.ledger.total())
    extra: Dict[str, object] = {
        "executable": executable,
        "error": f"{type(exc).__name__}: {exc}"[:2000],
        "ledger_total_bytes": _ledger.ledger.total(),
        "ledger_watermark_bytes": _ledger.ledger.watermark(),
        "top_categories": [{"category": c, "bytes": b}
                           for c, b in top],
        "predicted_bytes": predicted_bytes,
        "advertised_capacity_bytes": advertised_capacity(),
        "device_memory_stats": _ledger.device_memory_stats(),
        "live_arrays": _ledger.live_array_report(),
    }
    path = _flight.dump("oom", extra=extra)
    print(f"ERROR: hvd-mem: RESOURCE_EXHAUSTED dispatching "
          f"{executable!r}"
          + (f" (predicted {predicted_bytes} bytes)"
             if predicted_bytes else "")
          + f"; top ledger categories: "
          + (", ".join(f"{c}={b}" for c, b in top) or "none")
          + (f"; flight dump: {path}" if path else "")
          + " — see docs/memory.md 'Out of device memory'",
          file=sys.stderr)
    return path


def check_simulated(executable, predicted_bytes: Optional[int] = None
                    ) -> None:
    """The simulated-capacity pre-check, shared by :func:`guard` and
    the megakernel launch path (which avoids the contextmanager frame
    on its hot path): raise a deterministic RESOURCE_EXHAUSTED when
    the ledger total plus the predicted footprint exceeds
    ``HVD_TPU_MEM_CAPACITY``.  Callers pass PER-DEVICE predictions;
    the ledger-total baseline is the process-level accounting (equal
    on the single-device simulation meshes this knob targets, a
    conservative over-estimate on multi-device processes).
    ``executable`` may be a callable so the steady state never builds
    the name string."""
    cap = simulated_capacity()
    if cap is None:
        return
    total = _ledger.ledger.total()
    projected = total + (predicted_bytes or 0)
    if projected <= cap:
        return
    name = executable() if callable(executable) else executable
    exc = ResourceExhaustedError(
        f"RESOURCE_EXHAUSTED: simulated HBM capacity {cap} bytes "
        f"exceeded dispatching {name!r} (ledger {total} + predicted "
        f"{predicted_bytes or 0} = {projected} bytes; {CAPACITY_ENV})")
    oom_event(name, exc, predicted_bytes)
    raise exc


@contextlib.contextmanager
def guard(executable: str, predicted_bytes: Optional[int] = None):
    """Wrap one dispatch: simulated-capacity pre-check, then
    RESOURCE_EXHAUSTED capture.  Anything else passes through
    untouched, and the OOM re-raises after the dump — the guard
    observes failures, it never swallows them."""
    check_simulated(executable, predicted_bytes)
    try:
        yield
    except BaseException as e:  # noqa: BLE001 — re-raised below
        if is_resource_exhausted(e):
            oom_event(executable, e, predicted_bytes)
        raise


def simulated_capacity() -> Optional[int]:
    """The env-pinned capacity only (real backends enforce their own
    limit — double-enforcing it at dispatch would fail healthy
    launches whose transient footprint the allocator handles)."""
    v = os.environ.get(CAPACITY_ENV)
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def preflight_warn(plan_bytes: int, where: str,
                   detail: str = "") -> bool:
    """Compare a static prediction against the advertised capacity and
    warn — at init/build time, BEFORE any device allocation — when it
    does not fit.  Returns True when a warning fired (tests gate on
    it).  A warning, not an error: the plan is an upper bound and the
    operator may know better; the message names the dryrun tool."""
    cap = advertised_capacity()
    if cap is None or plan_bytes <= cap:
        return False
    _M_PREFLIGHT.inc()
    _flight.record("mem_preflight", where, plan_bytes, cap)
    print(f"WARNING: hvd-mem pre-flight ({where}): predicted "
          f"{plan_bytes} bytes exceeds the advertised per-rank HBM "
          f"capacity {cap} bytes"
          + (f" ({detail})" if detail else "")
          + "; run python -m horovod_tpu.memory --plan for the "
          f"what-if breakdown (docs/memory.md)", file=sys.stderr)
    return True
