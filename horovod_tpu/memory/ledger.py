"""Live HBM ledger: byte-accounting for every framework-owned device
allocation (hvd-mem piece 1, docs/memory.md).

The stack observes *time* exhaustively (hvd-trace) but device **memory**
is what actually kills jobs at scale — KV pages, in-flight pipeline
activations, donated fusion buffers, error-feedback residuals, prefetch
slots and checkpoint snapshots are all framework-owned HBM with no
accounting anywhere before this module.  The ledger is a per-process
table ``category -> current bytes`` fed by lightweight ``alloc``/
``free``/``set`` calls at the allocation sites themselves:

==========================  =============================================
category                    fed by
==========================  =============================================
``megakernel.fusion``       ops/megakernel.py ``launch`` (pack + unpack
                            payload bytes live for the dispatch)
``megakernel.residuals``    ops/megakernel.py error-feedback store
``serving.kv_pages``        serving/kv_cache.py page arrays
``input.prefetch``          parallel/input.py staged device batches
``pipeline.activations``    parallel/pipeline.py stage-boundary carries
``checkpoint.snapshots``    utils/checkpoint.py host snapshots queued on
                            the background writer
==========================  =============================================

Surfaces:

* telemetry gauges (``memory.bytes.<category>``, ``memory.ledger_bytes``,
  ``memory.high_watermark_bytes``, ``memory.step_watermark_bytes`` and
  the ``memory.device_*`` family from ``device.memory_stats()`` where
  the backend provides it) — set by a snapshot-time collector, so they
  ride the existing FRAME_METRICS / FRAME_METRICS_TREE fleet pull and
  ``hvd.cluster_metrics()`` reports per-rank HBM min/max/mean for free;
* a flight-recorder tail provider (telemetry.register_flight_tail), so
  every stall/dead-peer/OOM dump carries the ledger at dump time;
* :class:`MemoryWatch` — a StragglerWatch-style callback that warns on
  monotonic ledger growth over N steps, NAMING the leaking category.

Accounting is exact bookkeeping of what the framework *asked for*
(array ``nbytes``), not an allocator shadow: XLA may round, alias or
donate underneath.  Sharded stores charge their process-RESIDENT bytes
(:func:`resident_nbytes` — the KV page arrays); transient launch
buffers charge the global logical bytes of the shared planner model,
so plan-vs-ledger comparisons stay apples-to-apples.  The
``memory.device_*`` gauges and the dump-time :func:`live_array_report`
sweep bound the unattributed remainder.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..analysis import races as _races
from ..telemetry import flight as _flight

_M_LEAKS = _telemetry.counter(
    "memory.leak_warnings",
    "MemoryWatch firings (one category grew monotonically for N "
    "consecutive steps)")

# The categories the subsystem documents (docs/memory.md); the ledger
# accepts any name — a new allocation site does not need a registry
# change — but the planner predicts exactly these.
CATEGORIES = (
    "megakernel.fusion",
    "megakernel.residuals",
    "serving.kv_pages",
    "serving.prefix_pages",
    "serving.draft_kv",
    "serving.draft_params",
    "input.prefetch",
    "pipeline.activations",
    "checkpoint.snapshots",
)


@_races.race_checked
class MemoryLedger:
    """Byte ledger with per-category current/peak and per-step total
    watermarks.  The lock is a leaf on the hvd-analyze lock-order graph
    (allocation sites may call in while holding runtime locks; nothing
    is ever acquired under it)."""

    def __init__(self) -> None:
        self._lock = _lockorder.make_lock("memory.MemoryLedger._lock")
        self._bytes: Dict[str, int] = {}        # guarded_by: _lock
        self._keyed: Dict[Tuple[str, object], int] = {}
        # guarded_by: _lock
        self._peak: Dict[str, int] = {}         # guarded_by: _lock
        self._total_peak = 0                    # guarded_by: _lock
        self._step_peak = 0                     # guarded_by: _lock
        self._last_step_peak = 0                # guarded_by: _lock
        self._steps = 0                         # guarded_by: _lock

    # -- bookkeeping (all O(#categories), category count is ~6) ------------
    def _note_locked(self) -> None:
        total = sum(self._bytes.values())
        if total > self._total_peak:
            self._total_peak = total
        if total > self._step_peak:
            self._step_peak = total

    def alloc(self, category: str, nbytes: int, key=None) -> None:
        """Account ``nbytes`` against ``category``.  With ``key`` the
        entry is idempotent per (category, key): a re-alloc REPLACES the
        previous size (stores whose objects resize in place) and the
        matching ``free(key=...)`` releases exactly what is held."""
        n = int(nbytes)
        if n < 0:
            return
        with self._lock:
            if key is not None:
                prev = self._keyed.pop((category, key), 0)
                self._keyed[(category, key)] = n
                self._bytes[category] = max(
                    0, self._bytes.get(category, 0) - prev) + n
            else:
                self._bytes[category] = self._bytes.get(category, 0) + n
            if self._bytes[category] > self._peak.get(category, 0):
                self._peak[category] = self._bytes[category]
            self._note_locked()

    def free(self, category: str, nbytes: Optional[int] = None,
             key=None) -> None:
        """Release bytes.  Clamped at zero — a free racing an enablement
        toggle (or a double free on a shutdown path) must never drive a
        category negative and poison every later reading."""
        with self._lock:
            if key is not None:
                n = self._keyed.pop((category, key), 0)
            else:
                n = int(nbytes or 0)
            self._bytes[category] = max(
                0, self._bytes.get(category, 0) - n)

    def set(self, category: str, nbytes: int) -> None:
        """Absolute update — stores that already know their total
        (the residual table) set it instead of tracking deltas."""
        with self._lock:
            self._bytes[category] = max(0, int(nbytes))
            if self._bytes[category] > self._peak.get(category, 0):
                self._peak[category] = self._bytes[category]
            self._note_locked()

    # -- readers -----------------------------------------------------------
    def bytes_by_category(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._bytes)

    def total(self) -> int:
        with self._lock:
            return sum(self._bytes.values())

    def peak_by_category(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._peak)

    def watermark(self) -> int:
        """All-time peak of the total (the figure the planner's
        framework-owned prediction is gated against)."""
        with self._lock:
            return self._total_peak

    def step_watermark(self) -> int:
        """Peak total over the most recently completed step window."""
        with self._lock:
            return self._last_step_peak

    def steps(self) -> int:
        with self._lock:
            return self._steps

    def top(self, n: int = 3) -> List[Tuple[str, int]]:
        """The ``n`` largest categories by current bytes — the OOM
        dump's "who was holding what" tail (memory/oom.py)."""
        with self._lock:
            items = sorted(self._bytes.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return [(c, b) for c, b in items[:n] if b > 0]

    def note_step(self) -> int:
        """Close one step window: record its peak total as the per-step
        high-watermark and start the next window at the CURRENT total
        (long-lived stores carry over; transients reset).  Called once
        per training step (parallel/training.py, parallel/pipeline.py);
        returns the closed window's watermark."""
        with self._lock:
            self._steps += 1
            self._last_step_peak = self._step_peak
            self._step_peak = sum(self._bytes.values())
            return self._last_step_peak

    def reset(self) -> None:
        """Forget everything (tests and bench A/B legs)."""
        with self._lock:
            self._bytes.clear()
            self._keyed.clear()
            self._peak.clear()
            self._total_peak = 0
            self._step_peak = 0
            self._last_step_peak = 0
            self._steps = 0

    def snapshot(self) -> Dict[str, int]:
        """Flat ``metric name -> value`` view (the flight-dump tail and
        the gauge collector share it)."""
        with self._lock:
            out = {f"memory.bytes.{c}": b
                   for c, b in sorted(self._bytes.items())}
            out["memory.ledger_bytes"] = sum(self._bytes.values())
            out["memory.high_watermark_bytes"] = self._total_peak
            out["memory.step_watermark_bytes"] = self._last_step_peak
        return out


# Process-global ledger every allocation site feeds.
ledger = MemoryLedger()


def enabled() -> bool:
    """Accounting gate: the allocation sites check this (one flag read)
    so the bench's telemetry-on/off A/B — the ≤5 % ledger-overhead
    contract — measures the accounting too."""
    return _telemetry.enabled()


# -- backend-provided truth -------------------------------------------------

def device_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """``device.memory_stats()`` where the backend provides it (TPU/GPU
    do; CPU returns None).  Never raises — this feeds gauges and dumps."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return None
        return {k: int(v) for k, v in stats.items()
                if isinstance(v, (int, float))}
    except Exception:  # noqa: BLE001 — observability must never raise
        return None


def live_array_report(top_n: int = 10) -> Dict[str, object]:
    """Dump-time attribution sweep over ``jax.live_arrays()``: total
    live bytes per platform plus the ``top_n`` (shape, dtype) groups by
    bytes.  ``live_bytes - ledger total`` bounds what the framework does
    NOT own (user params, optimizer state, batches) — the OOM dump
    carries both so "framework leak" vs "model simply too big" is
    decidable from the dump alone."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 — sweep is best-effort
        return {"live_bytes": None, "arrays": None, "top": []}
    total = 0
    groups: Dict[Tuple[str, str], List[int]] = {}
    for a in arrays:
        try:
            nb = int(a.nbytes)
        except Exception:  # noqa: BLE001 — deleted/exotic arrays
            continue
        total += nb
        key = (str(tuple(a.shape)), str(a.dtype))
        g = groups.setdefault(key, [0, 0])
        g[0] += nb
        g[1] += 1
    top = sorted(groups.items(), key=lambda kv: -kv[1][0])[:top_n]
    return {
        "live_bytes": total,
        "arrays": len(arrays),
        "top": [{"shape": shape, "dtype": dtype, "bytes": nb,
                 "count": cnt}
                for (shape, dtype), (nb, cnt) in top],
    }


# -- telemetry wiring -------------------------------------------------------

def install_collector() -> None:
    """Register the snapshot-time gauge collector (idempotent, keyed
    like the runtime collector): ledger categories/watermarks plus the
    backend's own ``memory_stats`` where available.  Because these are
    plain registry gauges they ride FRAME_METRICS / FRAME_METRICS_TREE
    and ``hvd.cluster_metrics()`` aggregates per-rank HBM for free."""

    def collect(reg) -> None:
        for name, value in ledger.snapshot().items():
            reg.gauge(name).set(value)
        stats = device_memory_stats()
        if stats:
            for key, gauge_name in (
                    ("bytes_in_use", "memory.device_bytes_in_use"),
                    ("peak_bytes_in_use", "memory.device_peak_bytes"),
                    ("bytes_limit", "memory.device_bytes_limit")):
                if key in stats:
                    reg.gauge(gauge_name).set(stats[key])

    _telemetry.registry().register_collector("memory", collect)


def _flight_tail() -> Dict[str, int]:
    return ledger.snapshot()


# The flight tail reads the ledger directly (not the registry) so every
# stall/dead-peer/OOM dump carries CURRENT bytes even though dumps skip
# collectors; the ledger lock is a leaf, safe from under runtime locks.
_telemetry.register_flight_tail("memory", _flight_tail)
install_collector()


# -- the leak watch ---------------------------------------------------------

class MemoryWatch:
    """Training callback (StragglerWatch-style): warn live when one
    ledger category grows MONOTONICALLY for ``patience`` consecutive
    checks by at least ``min_growth`` bytes total, naming the category.

    Drop it into any training loop's callback list (duck-typed
    ``on_batch_end``/``on_epoch_end``) or drive :meth:`check` directly.
    A paged KV store that never releases, a prefetcher whose consumer
    died, a residual table growing under a name churn — each is named
    within ``patience`` steps instead of discovered as an OOM
    post-mortem (memory/oom.py then owns the post-mortem too)."""

    def __init__(self, patience: int = 8, min_growth: int = 1 << 20,
                 ledger_: Optional[MemoryLedger] = None) -> None:
        if patience < 2 or min_growth < 0:
            raise ValueError(
                f"MemoryWatch needs patience >= 2 and min_growth >= 0 "
                f"(got {patience}, {min_growth})")
        self.patience = int(patience)
        self.min_growth = int(min_growth)
        self._ledger = ledger_ if ledger_ is not None else ledger
        self._last: Dict[str, int] = {}
        self._streaks: Dict[str, int] = {}
        self._base: Dict[str, int] = {}
        self.warnings: List[dict] = []

    def set_trainer(self, trainer) -> None:  # Callback surface
        pass

    def check(self, sizes: Optional[Dict[str, int]] = None
              ) -> Optional[List[dict]]:
        """One step's evaluation; returns the warning dicts when any
        category fired (every leaking category is named — two leaks
        produce two warnings), else None.  Tests drive this directly
        with synthetic sizes."""
        if sizes is None:
            sizes = self._ledger.bytes_by_category()
        fired: List[dict] = []
        for cat in sorted(sizes):
            cur = sizes[cat]
            prev = self._last.get(cat)
            if prev is not None and cur > prev:
                if cat not in self._streaks:
                    self._base[cat] = prev
                self._streaks[cat] = self._streaks.get(cat, 0) + 1
            else:
                self._streaks.pop(cat, None)
                self._base.pop(cat, None)
            self._last[cat] = cur
            streak = self._streaks.get(cat, 0)
            growth = cur - self._base.get(cat, cur)
            if streak >= self.patience and growth >= self.min_growth:
                fired.append({"category": cat, "bytes": cur,
                              "growth": growth, "steps": streak})
                self._streaks[cat] = 0
                self._base[cat] = cur
        for cat in list(self._last):
            if cat not in sizes:
                del self._last[cat]
                self._streaks.pop(cat, None)
                self._base.pop(cat, None)
        for w in fired:
            self.warnings.append(w)
            _M_LEAKS.inc()
            _flight.record("memory_leak", w["category"], w["bytes"],
                           w["growth"])
            print(f"WARNING: hvd-mem MemoryWatch: ledger category "
                  f"{w['category']!r} grew monotonically for "
                  f"{self.patience} consecutive steps "
                  f"(+{w['growth']} bytes to {w['bytes']}) — likely "
                  f"leak; run python -m horovod_tpu.memory --plan to "
                  f"compare against the expected footprint "
                  f"(docs/memory.md)", file=sys.stderr)
        return fired or None

    # -- Callback surface --------------------------------------------------
    def on_batch_end(self, batch: int, logs=None) -> None:
        self.check()

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        self.check()


def tree_nbytes(tree) -> int:
    """Total ``nbytes`` over a pytree's array leaves (scalars and
    non-array leaves count zero) — the shared sizing helper for the
    prefetch/checkpoint/pipeline accounting sites.  NOTE: for a
    sharded ``jax.Array`` this is the GLOBAL logical size; use
    :func:`resident_nbytes` where the per-process resident figure is
    the right one (the KV page store)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            try:
                total += int(nb)
            except (TypeError, ValueError):
                pass
    return total


def device_nbytes(x) -> int:
    """Bytes ONE device holds of ``x`` (its first addressable shard):
    the figure capacity checks compare against per-device HBM — a
    replicated array costs its full size per device, a tp-sharded one
    1/tp.  Falls back to the global ``nbytes`` for non-jax leaves."""
    shards = getattr(x, "addressable_shards", None)
    if shards:
        try:
            return int(shards[0].data.nbytes)
        except Exception:  # noqa: BLE001 — sizing is observability
            pass
    nb = getattr(x, "nbytes", None)
    try:
        return int(nb) if nb is not None else 0
    except (TypeError, ValueError):
        return 0


def resident_nbytes(x) -> int:
    """Bytes of ``x`` actually resident on THIS process's devices: the
    sum of its addressable shards (a model-sharded KV store on tp=4
    holds 1/4 of the global bytes per rank).  Falls back to the global
    ``nbytes`` for non-jax leaves; identical to it in single-process
    mode, where every shard is addressable."""
    shards = getattr(x, "addressable_shards", None)
    if shards is not None:
        try:
            return sum(int(s.data.nbytes) for s in shards)
        except Exception:  # noqa: BLE001 — sizing is observability
            pass
    nb = getattr(x, "nbytes", None)
    try:
        return int(nb) if nb is not None else 0
    except (TypeError, ValueError):
        return 0
