"""Static memory planner: predict peak HBM per rank before a launch
(hvd-mem piece 2, docs/memory.md).

Two inputs, one plan:

* **Analytic models** of every framework-owned allocation the ledger
  (memory/ledger.py) accounts at runtime — fusion buffers, EF
  residuals, KV pages, prefetch slots, pipeline carries, checkpoint
  snapshots — PLUS the workload-owned big four (params, optimizer
  state, gradients, activations).  The byte formulas are shared with
  the runtime accounting sites (``fusion_group_bytes`` is the SAME
  function ``ops/megakernel.launch`` charges the ledger with), so the
  plan-vs-measured comparison is a real consistency check, not two
  guesses shaking hands.
* **Harvested ``compiled.memory_analysis()``** from every AOT-compile
  point the repo owns — the megakernel manifest warm-start path, the
  per-stage pipeline executables, serving prefill/decode buckets —
  recorded per executable by :func:`record_compiled` where the backend
  implements the query (TPU does; CPU returns nothing and the plan
  says so instead of inventing numbers).

``python -m horovod_tpu.memory --plan`` is the no-hardware dryrun
surface (the ``hvd.schedule_plan`` convention): answer "will this
config fit" — and what-if variants (batch size, microbatch count, KV
pages, interleave) — without compiling anything twice.  Plan JSON is
byte-identical for identical configs (sorted keys, no clocks), which
the CI ``memory`` job gates.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..analysis import lockorder as _lockorder

PLAN_FORMAT = "hvd-mem-plan-v1"

_DTYPE_BYTES = {
    "float32": 4, "f32": 4, "float64": 8, "bfloat16": 2, "float16": 2,
    "int32": 4, "int8": 1, "uint8": 1, "int64": 8,
}


def dtype_bytes(dtype) -> int:
    """Item size without importing jax (the CLI must answer on a box
    with nothing initialized); jax/numpy dtypes resolve via their
    itemsize, strings via the table."""
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize:
        return int(itemsize)
    name = str(getattr(dtype, "name", dtype)).lower()
    if name in _DTYPE_BYTES:
        return _DTYPE_BYTES[name]
    raise ValueError(f"unknown dtype {dtype!r}; expected one of "
                     f"{sorted(_DTYPE_BYTES)}")


# ---------------------------------------------------------------------------
# Shared byte models (the ledger's accounting sites use these too)
# ---------------------------------------------------------------------------

def fusion_group_bytes(shapes: Tuple[Tuple[int, ...], ...], dtype,
                       world: int, variant: str = "sp_pr") -> int:
    """Bytes one megakernel launch holds live: the group's input
    contributions plus its outputs (the packed intermediate aliases
    into them under XLA's donation).  Per-replica variants carry a
    ``world``-leading axis on both sides; replicated/mp payloads are
    single-copy.  This is the function ``ops/megakernel.launch``
    charges the ledger with — prediction and measurement share one
    model by construction."""
    item = dtype_bytes(dtype)
    payload = sum(int(math.prod(s)) if s else 1 for s in shapes) * item
    lead = world if variant in ("sp_pr", "mp") else 1
    return 2 * lead * payload


def fusion_group_device_bytes(shapes: Tuple[Tuple[int, ...], ...],
                              dtype) -> int:
    """PER-DEVICE footprint of one launch — what capacity checks
    compare against a per-device HBM figure.  Uniform across variants:
    a per-replica array holds one row per device, a replicated or mp
    payload one full copy, so each device carries one payload of
    inputs plus one of outputs.  (:func:`fusion_group_bytes` is the
    GLOBAL model the ledger/planner consistency contract shares — a
    2·world multiple of this on the per-replica variants.)"""
    item = dtype_bytes(dtype)
    return 2 * sum(int(math.prod(s)) if s else 1 for s in shapes) * item


def kv_cache_bytes(n_layers: int, n_heads: int, head_dim: int,
                   max_slots: int, pages_per_slot: int, page_size: int,
                   dtype="float32") -> int:
    """K + V page arrays of serving/kv_cache.PagedKVCache (the +1 is
    the reserved trash page; a dedicated prefix reserve is priced
    separately by :func:`prefix_pages_bytes` — the same partition the
    runtime ledger charges)."""
    n_pages = 1 + max_slots * pages_per_slot
    return (2 * n_layers * n_pages * page_size * n_heads * head_dim
            * dtype_bytes(dtype))


def prefix_pages_bytes(n_layers: int, n_heads: int, head_dim: int,
                       n_prefix_pages: int, page_size: int,
                       dtype="float32") -> int:
    """K + V bytes of a dedicated shared-prefix page reserve
    (``PagedKVCache(prefix_pages=N)``) — the ``--prefix-pages``
    what-if, and byte-for-byte the ``serving.prefix_pages`` ledger
    partition the runtime charges at cache construction."""
    return (2 * n_layers * n_prefix_pages * page_size * n_heads
            * head_dim * dtype_bytes(dtype))


def pipeline_activation_bytes(n_stages: int, num_microbatches: int,
                              microbatch_rows: int, width: int,
                              dtype="float32",
                              schedule: Optional[str] = None,
                              interleave: Optional[int] = None) -> int:
    """Peak stage-boundary carry bytes under the resolved schedule:
    ``schedule_plan(...).peak_activations`` (the event-simulated dryrun,
    parallel/pipeline.py) times one carry's GLOBAL bytes.  1F1B bounds
    this at the stage depth; GPipe grows it with the microbatch count —
    the what-if the CLI answers."""
    from ..parallel.pipeline import schedule_plan

    plan = schedule_plan(n_stages, num_microbatches, schedule=schedule,
                         interleave=interleave)
    carry = microbatch_rows * width * dtype_bytes(dtype)
    return plan.peak_activations * carry


def prefetch_bytes(depth: int, batch_bytes: int) -> int:
    """Staged device batches a prefetcher may hold at once
    (parallel/input.py: ``depth`` queued plus the one in flight on the
    stager thread)."""
    return (depth + 1) * batch_bytes


def retune_delta_bytes(knob: str, old, new, knobs) -> int:
    """hvd-tune candidate pricing (tuning/policy.py veto hook): the
    predicted change in per-device live bytes if ``knob`` moves
    ``old`` -> ``new``, from the same byte formulas the planner's
    what-ifs use.  Positive = the candidate costs memory; the tuner
    vetoes candidates whose cost exceeds the window's HBM headroom, so
    a retune can never land on an OOM.

    ``knobs`` is the current knob mapping (tuning.actuation
    ``current_knobs``); it supplies the fusion threshold that bounds
    both the fusion-buffer and the per-in-flight-step cost, and an
    optional ``spec_token_bytes`` advertised by the serving engine."""
    try:
        threshold = int(knobs.get("fusion_threshold", 64 * 1024 * 1024))
    except (TypeError, ValueError):
        threshold = 64 * 1024 * 1024
    try:
        old_i, new_i = int(old or 0), int(new)
    except (TypeError, ValueError):
        return 0
    if knob == "fusion_threshold":
        # In + out fusion buffers, each bounded by the threshold
        # (the same 2x model fusion_group_bytes charges).
        return 2 * (new_i - old_i)
    if knob == "max_inflight":
        # Each extra in-flight step pins up to one dispatched fusion
        # buffer of outputs (parallel/training._ThrottledStep holds the
        # step's tree until it leaves the window).
        return (new_i - old_i) * threshold
    if knob == "spec_tokens":
        # Per extra speculated token: the verify block's logits + draft
        # KV append — advertised by the live engine when one is
        # registered (serving/engine.py), else unpriceable (0).
        try:
            per_token = int(knobs.get("spec_token_bytes", 0) or 0)
        except (TypeError, ValueError):
            per_token = 0
        return (new_i - old_i) * per_token
    if knob == "prefix_pages":
        # Growing the shared-prefix reserve pins extra KV pages; the
        # per-page byte cost comes from the live cache
        # (``page_global_bytes``, advertised as ``prefix_page_bytes``
        # by tuning.actuation.current_knobs), the SAME byte model
        # prefix_pages_bytes prices at plan time — unpriceable (0)
        # without a live serving engine.
        try:
            per_page = int(knobs.get("prefix_page_bytes", 0) or 0)
        except (TypeError, ValueError):
            per_page = 0
        return (new_i - old_i) * per_page
    # Compression escalation narrows wire bytes and cycle_time is
    # host-side only — neither ever costs device memory.
    return 0


def fused_group_bytes(out_shape: Tuple[int, ...], chunks: int,
                      dtype="float32", chunk_axis: int = 0) -> int:
    """Bytes one fused computation-collective launch holds live beyond
    its inputs: the full output plus ONE chunk's partial product — the
    interleave buffer a chunk's collective leg reads while the next
    chunk computes (ops/fused.py).  This is the function
    :class:`~..ops.fused.FusedProgram` charges the ledger's
    ``fused.launch`` category with — prediction and measurement share
    one model by construction."""
    item = dtype_bytes(dtype)
    total = int(math.prod(out_shape)) if out_shape else 1
    rows = out_shape[chunk_axis] if out_shape else 1
    c = max(1, min(int(chunks), max(1, rows)))
    chunk_rows = -(-rows // c)  # ceil: the largest chunk in the plan
    chunk = total // max(1, rows) * chunk_rows
    return (total + chunk) * item


# ---------------------------------------------------------------------------
# Harvest: compiled.memory_analysis() per AOT executable
# ---------------------------------------------------------------------------

_harvest_lock = _lockorder.make_lock("memory.planner._harvest_lock")
_harvest: Dict[str, Dict[str, int]] = {}  # guarded_by: _harvest_lock

# The numeric fields jax's MemoryAnalysis exposes (names vary a little
# across jaxlib versions; we scan for the stable *_in_bytes suffix).
_ANALYSIS_SUFFIX = "_in_bytes"


def record_compiled(name: str, compiled) -> Optional[Dict[str, int]]:
    """Harvest ``compiled.memory_analysis()`` into the process-global
    table, keyed by executable name.  Returns the harvested dict, or
    None when the backend does not implement the query (XLA:CPU) — the
    plan's ``compiled`` section then reports coverage honestly instead
    of zeros.  Never raises: harvesting is observability."""
    try:
        analysis = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — Unimplemented on CPU, AttributeError
        return None    # on old jax: the planner works without it
    if analysis is None:
        return None
    out: Dict[str, int] = {}
    for attr in dir(analysis):
        if attr.endswith(_ANALYSIS_SUFFIX) and not attr.startswith("_"):
            try:
                out[attr] = int(getattr(analysis, attr))
            except (TypeError, ValueError):
                continue
    if not out:
        return None
    with _harvest_lock:
        _harvest[name] = out
    return out


def harvested() -> Dict[str, Dict[str, int]]:
    with _harvest_lock:
        return {k: dict(v) for k, v in _harvest.items()}


def clear_harvest() -> None:
    with _harvest_lock:
        _harvest.clear()


def harvest_section() -> Dict[str, Any]:
    """The plan's ``compiled`` section: per-executable
    ``memory_analysis`` numbers plus the peak over executables of
    (argument + output + temp) — the XLA-reported live-set bound for
    the single executable whose dispatch peaks."""
    table = harvested()
    peak = 0
    peak_name = None
    for name, fields in table.items():
        live = sum(fields.get(k, 0) for k in (
            "argument_size_in_bytes", "output_size_in_bytes",
            "temp_size_in_bytes"))
        if live > peak:
            peak, peak_name = live, name
    return {
        "executables": {k: dict(sorted(v.items()))
                        for k, v in sorted(table.items())},
        "peak_executable": peak_name,
        "peak_executable_bytes": peak,
        "coverage": len(table),
    }


def manifest_section(directory: Optional[str] = None) -> Dict[str, Any]:
    """Static fusion-buffer predictions for every megakernel the
    persistent-cache manifest recorded (the warm-start path's
    executables) — how a FRESH process plans a mesh it has not compiled
    on yet.  Serving entries contribute their KV/config identity, group
    entries their :func:`fusion_group_bytes`."""
    from ..ops import megakernel as _mk

    d = directory or _mk.compile_cache_dir()
    if d is None:
        return {"entries": 0, "peak_group_bytes": 0,
                "peak_group_device_bytes": 0}
    peak = 0
    peak_dev = 0
    entries = 0
    for entry in _mk.load_manifest(d):
        if entry.get("variant") not in ("sp_pr", "sp_rep"):
            continue
        entries += 1
        shapes = tuple(tuple(s) for s in entry.get("shapes", ()))
        world = int((entry.get("mesh") or {}).get("count", 1))
        dtype = entry.get("dtype", "float32")
        peak = max(peak, fusion_group_bytes(
            shapes, dtype, world, entry.get("variant", "sp_pr")))
        peak_dev = max(peak_dev,
                       fusion_group_device_bytes(shapes, dtype))
    return {"entries": entries, "peak_group_bytes": peak,
            "peak_group_device_bytes": peak_dev}


# ---------------------------------------------------------------------------
# The plan
# ---------------------------------------------------------------------------

@dataclass
class MemoryPlan:
    """One resolved memory plan.  ``sections`` maps workload components
    to byte figures; ``framework`` is the ledger-covered subset — the
    half the runtime measures, so ``framework_bytes`` vs the ledger's
    high watermark is the accuracy contract (±15 %, CI-gated).
    ``to_json()`` is deterministic: identical config ⇒ byte-identical
    output (sorted keys, no clocks, no environment echoes beyond the
    config itself)."""

    model: str
    config: Dict[str, Any]
    world: int
    sections: Dict[str, int] = field(default_factory=dict)
    framework: Dict[str, int] = field(default_factory=dict)
    facts: Dict[str, Any] = field(default_factory=dict)
    capacity_bytes: Optional[int] = None

    @property
    def framework_bytes(self) -> int:
        return sum(self.framework.values())

    @property
    def per_rank_bytes(self) -> int:
        return self.framework_bytes + sum(self.sections.values())

    def to_dict(self) -> Dict[str, Any]:
        fits = None
        headroom = None
        if self.capacity_bytes:
            headroom = self.capacity_bytes - self.per_rank_bytes
            fits = headroom >= 0
        return {
            "format": PLAN_FORMAT,
            "model": self.model,
            "config": dict(sorted(self.config.items())),
            "world": self.world,
            "sections": dict(sorted(self.sections.items())),
            "facts": dict(sorted(self.facts.items())),
            "framework": dict(sorted(self.framework.items())),
            "framework_bytes": self.framework_bytes,
            "per_rank_bytes": self.per_rank_bytes,
            "capacity_bytes": self.capacity_bytes,
            "headroom_bytes": headroom,
            "fits": fits,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, indent=1)


def _transformer_param_bytes(vocab_size: int, d_model: int,
                             n_heads: int, n_layers: int, d_ff: int,
                             max_seq_len: int, dtype="float32") -> int:
    """Parameter bytes of models/transformer.init_transformer, computed
    from the layer shapes (embedding + positional, per layer QKV/out
    projections, two FFN matrices, two layernorm pairs, final norm +
    untied head) — pure arithmetic, no tracing, so the CLI stays
    hardware-free and deterministic."""
    item = dtype_bytes(dtype)
    per_layer = (4 * d_model * d_model                      # wq/wk/wv/wo
                 + 2 * d_model * d_ff + d_ff + d_model      # ffn + biases
                 + 4 * d_model)                             # 2 x ln
    total = (vocab_size * d_model                           # embedding
             + max_seq_len * d_model                        # positions
             + n_layers * per_layer
             + 2 * d_model                                  # final ln
             + d_model * vocab_size)                        # untied head
    return total * item


_OPTIMIZER_SLOTS = {"adam": 2, "adamw": 2, "sgd": 0, "momentum": 1,
                    "none": 0}


def plan_dataplane(tensors: int, elems: int, world: int,
                   dtype: str = "float32",
                   fusion_threshold: Optional[int] = None,
                   capacity: Optional[int] = None) -> MemoryPlan:
    """Plan for the dataplane steady state (``bench.py --mode
    dataplane``'s workload): a ``tensors``-wide allreduce program.  The
    framework peak is the largest fusion group's launch footprint under
    the threshold partition (groups are filled greedily in submission
    order — the coordinator's plan_fusion policy)."""
    item = dtype_bytes(dtype)
    thr = fusion_threshold if fusion_threshold is not None \
        else int(os.environ.get("HOROVOD_FUSION_THRESHOLD",
                                str(64 << 20)))
    per_tensor = elems * item
    groups: List[int] = []
    cur = 0
    for _ in range(tensors):
        if cur and cur + per_tensor > thr:
            groups.append(cur)
            cur = 0
        cur += per_tensor
    if cur:
        groups.append(cur)
    peak_group = max(groups) if groups else 0
    fusion = fusion_group_bytes(
        ((peak_group // item,),), dtype, world, "sp_pr")
    return MemoryPlan(
        model="dataplane",
        config={"tensors": tensors, "elems": elems, "dtype": dtype,
                "fusion_threshold": thr},
        world=world,
        sections={"tensors": tensors * world * per_tensor},
        facts={"fusion_groups": len(groups),
               "peak_group_payload_bytes": peak_group},
        framework={"megakernel.fusion": fusion},
        capacity_bytes=capacity)


def plan_pipeline(n_stages: int, num_microbatches: int,
                  microbatch_rows: int, width: int, world: int,
                  schedule: Optional[str] = None,
                  interleave: Optional[int] = None,
                  dtype: str = "float32",
                  stage_param_bytes: Optional[int] = None,
                  capacity: Optional[int] = None) -> MemoryPlan:
    """Plan for the MPMD pipeline step: carries from the event-simulated
    schedule plan (the 1F1B-vs-GPipe what-if), stage parameters /
    gradient accumulators, and the per-stage bucket reduction's fusion
    transient."""
    from ..parallel.pipeline import schedule_plan

    plan = schedule_plan(n_stages, num_microbatches, schedule=schedule,
                         interleave=interleave)
    item = dtype_bytes(dtype)
    sp = stage_param_bytes if stage_param_bytes is not None \
        else (width * width + width) * item
    carry = microbatch_rows * width * item
    activations = plan.peak_activations * carry
    fusion = 2 * world * sp  # largest stage bucket's launch footprint
    return MemoryPlan(
        model="pipeline",
        config={"n_stages": n_stages,
                "num_microbatches": num_microbatches,
                "microbatch_rows": microbatch_rows, "width": width,
                "schedule": plan.schedule,
                "interleave": plan.interleave, "dtype": dtype},
        world=world,
        sections={"params": n_stages * sp,
                  "gradient_accumulators": n_stages * world * sp},
        facts={"peak_activation_carries": plan.peak_activations,
               "bubble_fraction": round(plan.bubble_fraction, 4)},
        framework={"pipeline.activations": activations,
                   "megakernel.fusion": fusion},
        capacity_bytes=capacity)


def plan_serving(n_layers: int, n_heads: int, head_dim: int,
                 max_slots: int, pages_per_slot: int, page_size: int,
                 world: int = 1, dtype: str = "float32",
                 param_bytes: int = 0,
                 prefix_pages: int = 0,
                 draft_layers: int = 0,
                 draft_d_ff: Optional[int] = None,
                 vocab_size: int = 256,
                 capacity: Optional[int] = None) -> MemoryPlan:
    """Plan for the serving engine: the paged KV store (the dominant
    framework buffer) plus replicated params.  The KV what-ifs —
    slots, pages per slot, page size — are the router tier's capacity
    question (ROADMAP item 2).  hvd-spec what-ifs: ``--prefix-pages``
    prices a dedicated shared-prefix reserve
    (:func:`prefix_pages_bytes`, the runtime's ledger partition) and
    ``--draft-layers`` a speculative-decoding draft model over the
    same slots — its own KV store (:func:`kv_cache_bytes`, the same
    formula the draft ``PagedKVCache`` charges ``serving.draft_kv``
    with) plus its replicated parameters
    (:func:`_transformer_param_bytes`, exact for ``init_transformer``
    trees; draft ``d_model = n_heads * head_dim``, ``d_ff`` defaults
    to ``4 * d_model``, positions sized to the KV capacity)."""
    kv = kv_cache_bytes(n_layers, n_heads, head_dim, max_slots,
                        pages_per_slot, page_size, dtype)
    framework = {"serving.kv_pages": kv}
    facts = {"kv_capacity_tokens": max_slots * pages_per_slot
             * page_size}
    if prefix_pages:
        framework["serving.prefix_pages"] = prefix_pages_bytes(
            n_layers, n_heads, head_dim, prefix_pages, page_size,
            dtype)
        facts["prefix_pages"] = prefix_pages
    if draft_layers:
        d_model = n_heads * head_dim
        framework["serving.draft_kv"] = kv_cache_bytes(
            draft_layers, n_heads, head_dim, max_slots,
            pages_per_slot, page_size, dtype)
        framework["serving.draft_params"] = _transformer_param_bytes(
            vocab_size, d_model, n_heads, draft_layers,
            draft_d_ff if draft_d_ff is not None else 4 * d_model,
            pages_per_slot * page_size, dtype)
        facts["draft_layers"] = draft_layers
    return MemoryPlan(
        model="serving",
        config={"n_layers": n_layers, "n_heads": n_heads,
                "head_dim": head_dim, "max_slots": max_slots,
                "pages_per_slot": pages_per_slot,
                "page_size": page_size, "dtype": dtype,
                "prefix_pages": prefix_pages,
                "draft_layers": draft_layers},
        world=world,
        sections={"params": param_bytes},
        facts=facts,
        framework=framework,
        capacity_bytes=capacity)


def plan_transformer_lm(vocab_size: int = 256, d_model: int = 128,
                        n_heads: int = 8, n_layers: int = 2,
                        d_ff: int = 256, max_seq_len: int = 64,
                        batch_size: int = 32, world: int = 1,
                        optimizer: str = "adam",
                        prefetch_depth: int = 2,
                        dtype: str = "float32",
                        capacity: Optional[int] = None) -> MemoryPlan:
    """End-to-end training plan for the transformer LM example: params
    + optimizer slots + gradients + a coarse activation model
    (per-token residual-stream floats across the layer stack; remat
    halves it in practice — the figure is an upper bound, documented in
    docs/memory.md) + the framework buffers (fusion launch of the
    largest gradient group, prefetch staging, one checkpoint
    snapshot)."""
    if optimizer not in _OPTIMIZER_SLOTS:
        raise ValueError(f"unknown optimizer {optimizer!r}; expected "
                         f"one of {sorted(_OPTIMIZER_SLOTS)}")
    item = dtype_bytes(dtype)
    params = _transformer_param_bytes(vocab_size, d_model, n_heads,
                                      n_layers, d_ff, max_seq_len,
                                      dtype)
    opt = _OPTIMIZER_SLOTS[optimizer] * params
    grads = params
    per_rank_batch = max(1, batch_size // max(1, world))
    activations = (per_rank_batch * max_seq_len
                   * (2 * d_model + d_ff) * n_layers * item)
    batch_bytes = per_rank_batch * max_seq_len * 4 * 2  # tokens+targets
    fusion = fusion_group_bytes(((params // item,),), dtype, world,
                                "sp_pr")
    return MemoryPlan(
        model="transformer_lm",
        config={"vocab_size": vocab_size, "d_model": d_model,
                "n_heads": n_heads, "n_layers": n_layers,
                "d_ff": d_ff, "max_seq_len": max_seq_len,
                "batch_size": batch_size, "optimizer": optimizer,
                "prefetch_depth": prefetch_depth, "dtype": dtype},
        world=world,
        sections={"params": params, "optimizer_state": opt,
                  "gradients": grads, "activations": activations},
        framework={"megakernel.fusion": fusion,
                   "input.prefetch": prefetch_bytes(prefetch_depth,
                                                    batch_bytes),
                   "checkpoint.snapshots": params},
        capacity_bytes=capacity)


_MODELS = {
    "dataplane": plan_dataplane,
    "pipeline": plan_pipeline,
    "serving": plan_serving,
    "transformer_lm": plan_transformer_lm,
}


def model_names() -> Tuple[str, ...]:
    return tuple(sorted(_MODELS))


def build_plan(model: str, **kwargs) -> MemoryPlan:
    """Resolve one plan by model name (the CLI surface; a typo names
    every valid model, the ``hvd.init`` knob-validation convention)."""
    fn = _MODELS.get(model)
    if fn is None:
        raise ValueError(f"unknown plan model {model!r}; expected one "
                         f"of {', '.join(model_names())}")
    return fn(**kwargs)
