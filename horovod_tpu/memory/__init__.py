"""hvd-mem: fleet-wide HBM observability (docs/memory.md).

Three coupled halves, the same vertical slice hvd-trace cut on the
orthogonal axis — device **memory** instead of time:

* :mod:`~horovod_tpu.memory.ledger` — the live byte ledger fed by every
  framework-owned allocation site (fusion buffers, EF residuals, KV
  pages, prefetch slots, pipeline carries, checkpoint snapshots),
  surfaced as ``memory.*`` telemetry gauges that ride the
  FRAME_METRICS / FRAME_METRICS_TREE fleet pull — so
  ``hvd.cluster_metrics()`` reports per-rank HBM min/max/mean for free
  — plus :class:`~horovod_tpu.memory.ledger.MemoryWatch`, the live
  leak detector.
* :mod:`~horovod_tpu.memory.planner` — the static memory planner:
  analytic byte models shared with the runtime accounting sites,
  harvested ``compiled.memory_analysis()`` per AOT executable, and
  ``python -m horovod_tpu.memory --plan`` as the no-hardware dryrun
  answering "will this config fit" and its what-ifs.
* :mod:`~horovod_tpu.memory.oom` — RESOURCE_EXHAUSTED capture at the
  dispatch sites: a forensic flight dump naming the failing executable
  and the top ledger categories, a simulated-capacity knob
  (``HVD_TPU_MEM_CAPACITY``) and the init/build-time pre-flight
  warnings.
"""

from __future__ import annotations

from . import ledger  # noqa: F401  (import installs collector + tail)
from . import oom  # noqa: F401
from . import planner  # noqa: F401
from .ledger import (  # noqa: F401
    MemoryLedger,
    MemoryWatch,
    device_memory_stats,
    live_array_report,
    tree_nbytes,
)

# The process-global ledger instance lives at memory.ledger.ledger (the
# flight/recorder convention); re-exporting it here as ``ledger`` would
# shadow the submodule for every ``from ..memory import ledger`` site.
from .oom import (  # noqa: F401
    ResourceExhaustedError,
    advertised_capacity,
    guard,
    is_resource_exhausted,
    oom_event,
    preflight_warn,
)
from .planner import (  # noqa: F401
    MemoryPlan,
    build_plan,
    fusion_group_bytes,
    harvested,
    kv_cache_bytes,
    model_names,
    pipeline_activation_bytes,
    record_compiled,
)
