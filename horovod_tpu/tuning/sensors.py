"""hvd-tune sensors: the windowed, file-free diagnosis feed.

One :class:`WindowAggregator` lives on the rank-0 controller
(tuning/controller.py) and is sampled once per decision window from the
drain tick.  Each sample folds the observability the two previous PRs
built — the in-memory hvd-trace span buffer (``trace.export_events``,
decomposed by ``trace.analyze.window_legs``), the fleet skew tracker
(``trace.watch.tracker``), the live speculative engines' acceptance
rate, and the hvd-mem ledger/backend HBM headroom — into one
deterministic :class:`~horovod_tpu.tuning.policy.WindowSnapshot`.

Leg attribution is windowed by differencing: the span buffer is a
bounded deque, so each sample decomposes the whole buffer and subtracts
the previous sample's totals; when any leg's total went DOWN (old spans
rolled off the deque faster than new ones arrived) the absolute totals
are used for that window — monotone-safe, never negative.
"""

from __future__ import annotations

from typing import Dict, Optional

from .policy import WindowSnapshot


class WindowAggregator:
    def __init__(self, st, straggler_skew_s: float = 0.001):
        self._st = st
        self._straggler_skew_s = float(straggler_skew_s)
        self._prev_legs: Optional[Dict[str, float]] = None
        self._prev_prefix: Optional[Dict[str, float]] = None
        self._index = 0

    def _window_legs(self) -> Dict[str, float]:
        from .. import trace as _trace
        from ..trace import analyze as _analyze

        totals = _analyze.window_legs(_trace.export_events())
        prev = self._prev_legs
        self._prev_legs = dict(totals)
        if prev is None or any(totals.get(k, 0.0) < prev.get(k, 0.0)
                               for k in totals):
            return totals
        return {k: totals[k] - prev.get(k, 0.0) for k in totals}

    def _straggler(self) -> int:
        from ..trace import watch as _watch

        skews = _watch.tracker.skew_by_rank()
        if not skews:
            return -1
        worst = max(skews.values())
        if worst < self._straggler_skew_s:
            return -1
        return min(r for r, s in skews.items() if s == worst)

    def _spec_acceptance(self) -> float:
        from . import actuation as _actuation

        for engine in _actuation.spec_engines():
            try:
                rate = engine.spec_acceptance_rate  # property on the
                if callable(rate):                  # serving engine
                    rate = rate()
            except Exception:  # noqa: BLE001 — a draining engine must
                continue       # not break the sensor pass
            if rate is not None:
                return float(rate)
        return -1.0

    def _headroom(self):
        """(fraction_free, bytes_free) from the backend's memory_stats
        when present, else the advertised capacity against the ledger's
        accounted total; (-1.0, -1) when neither is known."""
        from ..memory import ledger as _ledger
        from ..memory import oom as _oom

        stats = _ledger.device_memory_stats()
        if stats:
            limit = stats.get("bytes_limit") or 0
            used = stats.get("bytes_in_use") or 0
            if limit > 0:
                free = max(0, int(limit) - int(used))
                return free / float(limit), free
        capacity = _oom.advertised_capacity()
        if capacity:
            used = _ledger.ledger.total()
            free = max(0, int(capacity) - int(used))
            return free / float(capacity), free
        return -1.0, -1

    def _prefix(self):
        """(hit_rate, kv_free_frac) for the prefix-reserve rule, from
        the live metrics registry: windowed differencing of the
        serving.prefix_hits (+ draft) and serving.prefills counters
        (monotone-safe like the legs), plus the current
        kv_free_pages/kv_total_pages gauges; (-1.0, -1.0) when no
        serving engine publishes them."""
        from .. import telemetry as _telemetry

        snap = _telemetry.registry().snapshot()

        def _val(name: str):
            m = snap.get(name)
            return None if m is None else float(m.get("value", 0))

        prefills = _val("serving.prefills")
        if prefills is None:
            self._prev_prefix = None
            return -1.0, -1.0
        hits = ((_val("serving.prefix_hits") or 0.0)
                + (_val("serving.prefix_hits_draft") or 0.0))
        totals = {"hits": hits, "prefills": prefills}
        prev = self._prev_prefix
        self._prev_prefix = dict(totals)
        if prev is not None and all(totals[k] >= prev.get(k, 0.0)
                                    for k in totals):
            hits = totals["hits"] - prev.get("hits", 0.0)
            prefills = totals["prefills"] - prev.get("prefills", 0.0)
        rate = hits / prefills if prefills > 0 else -1.0
        total_pages = _val("serving.kv_total_pages") or 0.0
        free_pages = _val("serving.kv_free_pages")
        kv_free = (free_pages / total_pages
                   if free_pages is not None and total_pages > 0
                   else -1.0)
        return rate, kv_free

    def sample(self) -> WindowSnapshot:
        from . import actuation as _actuation

        frac, free = self._headroom()
        hit_rate, kv_free = self._prefix()
        snap = WindowSnapshot(
            index=self._index,
            legs=self._window_legs(),
            knobs=_actuation.current_knobs(self._st),
            straggler_rank=self._straggler(),
            spec_acceptance=self._spec_acceptance(),
            headroom_frac=frac,
            headroom_bytes=free,
            prefix_hit_rate=hit_rate,
            kv_free_frac=kv_free,
        )
        self._index += 1
        return snap
