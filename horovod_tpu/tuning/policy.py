"""hvd-tune policy engine: diagnosis -> knob delta, pure in its inputs.

The rule table maps one :class:`WindowSnapshot` (the sensors' per-window
diagnosis, sensors.py) to at most ONE :class:`Decision` per window.  The
engine is deliberately free of wall clock and PRNG: feeding it the same
snapshot sequence always yields the same decision sequence — the
determinism gate ``bench.py --mode tuning`` replays.

Stability machinery (docs/tuning.md "Why the tuner won't thrash"):

* **Hysteresis** — a rule's condition must hold for ``sustain``
  consecutive windows before it fires; a boundary-flapping input
  (condition alternating true/false) never accumulates the streak.
* **Cooldown** — after a rule touches a knob (or is vetoed on it), that
  knob is untouchable for ``cooldown`` further windows, so the effect of
  one retune is measured before the next.
* **Engagement floor** — leg-dominance rules need the dominant leg to
  carry at least ``engage_share`` of the window's busy time; an
  undiagnosable (flat) profile produces no decision at all.
* **Planner veto** — every candidate is priced by the hvd-mem planner's
  shared byte formulas (memory/planner.py) through the ``price`` hook
  BEFORE it becomes a decision; a candidate whose predicted device-byte
  delta exceeds the window's headroom is counted (``vetoes``) and the
  knob left untouched — a retune can never land on an OOM.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Tuple

# The compression escalation ladder the dcn rule climbs (one rung per
# decision): each rung narrows the DCN wire format further
# (ops/compression.py; int4 is the EQuARX-style block-quantized floor).
COMPRESSION_LADDER = ("none", "bf16", "int8", "int4")

# Knob names (the wire vocabulary carried by RETUNE markers —
# tuning/actuation.py owns the apply side of each).
KNOB_DCN_COMPRESS = "dcn_compress"
KNOB_MAX_INFLIGHT = "max_inflight"
KNOB_FUSION_THRESHOLD = "fusion_threshold"
KNOB_CYCLE_TIME = "cycle_time"
KNOB_SPEC_TOKENS = "spec_tokens"
KNOB_PREFIX_PAGES = "prefix_pages"

KNOB_NAMES = (KNOB_DCN_COMPRESS, KNOB_MAX_INFLIGHT, KNOB_FUSION_THRESHOLD,
              KNOB_CYCLE_TIME, KNOB_SPEC_TOKENS, KNOB_PREFIX_PAGES)


@dataclass(frozen=True)
class WindowSnapshot:
    """One decision window's diagnosis — everything the policy may read.

    ``legs`` is busy µs per critical-path leg (trace/analyze.py LEGS
    vocabulary); ``straggler_rank`` is the window's late rank (-1 none);
    ``spec_acceptance`` is the serving engine's acceptance rate (-1 when
    no speculative engine is live); ``headroom_frac`` is free/capacity
    HBM (-1 unknown); ``headroom_bytes`` the absolute free bytes (-1
    unknown) the planner veto prices against; ``knobs`` the CURRENT
    knob values the deltas start from.  ``prefix_hit_rate`` is the
    window's shared-prefix hit fraction (target + draft hits over
    prefills, -1 when no serving engine is live) and ``kv_free_frac``
    the KV admission-headroom fraction (free/total pages, -1 unknown)
    — the prefix-reserve retune rule's inputs."""

    index: int
    legs: Mapping[str, float]
    knobs: Mapping[str, object]
    straggler_rank: int = -1
    spec_acceptance: float = -1.0
    headroom_frac: float = -1.0
    headroom_bytes: int = -1
    prefix_hit_rate: float = -1.0
    kv_free_frac: float = -1.0


@dataclass(frozen=True)
class Decision:
    seq: int
    window: int
    knob: str
    value: object
    reason: str

    def wire(self) -> str:
        """The ``knob=value`` token a RETUNE marker carries."""
        return f"{self.knob}={self.value}"


@dataclass(frozen=True)
class PolicyConfig:
    sustain: int = 2            # consecutive windows before a rule fires
    cooldown: int = 2           # knob-untouchable windows after a fire
    engage_share: float = 0.10  # leg rules: minimum dominant-leg share
    dcn_share: float = 0.35     # dcn-dominated threshold
    gap_share: float = 0.35     # dispatch-gap-dominated threshold
    low_acceptance: float = 0.5  # spec_tokens shrink threshold
    headroom_floor: float = 0.10  # free/capacity triggering byte-saving
    straggler_skew_us: float = 1000.0  # sensors' persistence threshold
    max_inflight_cap: int = 8
    fusion_floor_bytes: int = 1 << 20
    # Prefix-reserve retuning (hvd-route tail): a hot index starving
    # for KV headroom earns a bigger dedicated reserve; a cold index
    # gives its reserve back.
    prefix_hit_high: float = 0.5   # hit rate worth growing for
    prefix_hit_low: float = 0.05   # hit rate the reserve shrinks under
    prefix_kv_floor: float = 0.25  # kv_free_frac that signals pressure
    prefix_pages_cap: int = 256
    pinned: frozenset = field(default_factory=frozenset)


def _share(legs: Mapping[str, float], leg: str) -> float:
    total = sum(max(0.0, float(v)) for v in legs.values())
    if total <= 0.0:
        return 0.0
    return max(0.0, float(legs.get(leg, 0.0))) / total


class PolicyEngine:
    """The deterministic rule table.  ``price`` is the planner-veto hook:
    ``price(knob, old, new, snapshot) -> predicted device-byte DELTA``
    (positive = the candidate costs memory); a delta above the
    snapshot's known headroom vetoes the candidate."""

    def __init__(self, cfg: Optional[PolicyConfig] = None,
                 price: Optional[Callable[..., int]] = None):
        self.cfg = cfg or PolicyConfig()
        self._price = price
        self._seq = 0
        self._sustain: Dict[str, int] = {}
        self._cooldown: Dict[str, int] = {}
        self._straggler: Tuple[int, int] = (-1, 0)  # (rank, streak)
        self.decisions: List[Decision] = []
        self.vetoes = 0
        self.veto_log: List[Tuple[int, str, object, str]] = []

    # -- rule proposals ----------------------------------------------------
    def _propose_dcn(self, snap: WindowSnapshot):
        cur = str(snap.knobs.get(KNOB_DCN_COMPRESS, "none"))
        try:
            idx = COMPRESSION_LADDER.index(cur)
        except ValueError:
            idx = 0  # fp16 etc.: restart the ladder conservatively
        if idx + 1 >= len(COMPRESSION_LADDER):
            return None
        nxt = COMPRESSION_LADDER[idx + 1]
        return (KNOB_DCN_COMPRESS, nxt,
                f"dcn leg at {_share(snap.legs, 'dcn'):.0%} of the "
                f"critical path: escalate DCN compression {cur} -> {nxt}")

    def _propose_gap(self, snap: WindowSnapshot):
        cur = int(snap.knobs.get(KNOB_MAX_INFLIGHT, 2))
        if cur >= self.cfg.max_inflight_cap:
            return None
        nxt = min(self.cfg.max_inflight_cap, cur * 2)
        return (KNOB_MAX_INFLIGHT, nxt,
                f"dispatch-gap leg at {_share(snap.legs, 'dispatch-gap'):.0%}"
                f": widen in-flight window {cur} -> {nxt}")

    def _propose_rebucket(self, snap: WindowSnapshot):
        cur = int(snap.knobs.get(KNOB_FUSION_THRESHOLD, 64 << 20))
        if cur <= self.cfg.fusion_floor_bytes:
            return None
        nxt = max(self.cfg.fusion_floor_bytes, cur // 2)
        return (KNOB_FUSION_THRESHOLD, nxt,
                f"persistent straggler rank {snap.straggler_rank}: "
                f"re-bucket via fusion threshold {cur} -> {nxt}")

    def _propose_spec(self, snap: WindowSnapshot):
        cur = int(snap.knobs.get(KNOB_SPEC_TOKENS, 3))
        if cur <= 1:
            return None
        return (KNOB_SPEC_TOKENS, cur - 1,
                f"spec acceptance {snap.spec_acceptance:.0%} below "
                f"{self.cfg.low_acceptance:.0%}: shrink spec_tokens "
                f"{cur} -> {cur - 1}")

    def _propose_headroom(self, snap: WindowSnapshot):
        # Trade speed for bytes: smaller fusion buffers first, then
        # narrower wire formats (both shrink the live device footprint).
        cur = int(snap.knobs.get(KNOB_FUSION_THRESHOLD, 64 << 20))
        if cur > self.cfg.fusion_floor_bytes:
            nxt = max(self.cfg.fusion_floor_bytes, cur // 2)
            return (KNOB_FUSION_THRESHOLD, nxt,
                    f"HBM headroom {snap.headroom_frac:.0%} below "
                    f"{self.cfg.headroom_floor:.0%}: shrink fusion "
                    f"buffers {cur} -> {nxt}")
        return self._propose_dcn(snap)

    def _propose_prefix_grow(self, snap: WindowSnapshot):
        cur = int(snap.knobs.get(KNOB_PREFIX_PAGES, 0) or 0)
        if cur >= self.cfg.prefix_pages_cap:
            return None
        nxt = min(self.cfg.prefix_pages_cap, max(cur * 2, 8))
        return (KNOB_PREFIX_PAGES, nxt,
                f"prefix hit rate {snap.prefix_hit_rate:.0%} with KV "
                f"headroom at {snap.kv_free_frac:.0%}: grow the prefix "
                f"reserve {cur} -> {nxt} pages")

    def _propose_prefix_shrink(self, snap: WindowSnapshot):
        cur = int(snap.knobs.get(KNOB_PREFIX_PAGES, 0) or 0)
        if cur <= 0:
            return None
        return (KNOB_PREFIX_PAGES, cur // 2,
                f"prefix hit rate {snap.prefix_hit_rate:.0%} below "
                f"{self.cfg.prefix_hit_low:.0%}: shrink the prefix "
                f"reserve {cur} -> {cur // 2} pages")

    # -- the window step ---------------------------------------------------
    def _conditions(self, snap: WindowSnapshot) -> List[Tuple[str, float]]:
        """(rule, urgency) for every rule whose condition holds this
        window, most urgent first — a deterministic total order (urgency
        desc, then rule name asc)."""
        cfg = self.cfg
        held: List[Tuple[str, float]] = []
        if 0.0 <= snap.headroom_frac < cfg.headroom_floor:
            held.append(("headroom", 2.0))  # safety outranks speed
        dcn = _share(snap.legs, "dcn")
        if dcn >= max(cfg.dcn_share, cfg.engage_share):
            held.append(("dcn", dcn))
        gap = _share(snap.legs, "dispatch-gap")
        if gap >= max(cfg.gap_share, cfg.engage_share):
            held.append(("gap", gap))
        if self._straggler[0] >= 0 \
                and self._straggler[1] >= cfg.sustain:
            held.append(("straggler", 0.5))
        if 0.0 <= snap.spec_acceptance < cfg.low_acceptance:
            held.append(("spec", 0.4))
        # Prefix-reserve balance: a HOT index under KV-headroom
        # pressure earns dedicated pages (the shared pool is thrashing
        # cached prefixes against live slots); a COLD index with a
        # reserve gives it back.  Mutually exclusive by construction
        # (hit rate cannot be both >= high and < low).
        if (0.0 <= snap.kv_free_frac < cfg.prefix_kv_floor
                and snap.prefix_hit_rate >= cfg.prefix_hit_high):
            held.append(("prefix_grow", 0.3))
        if (0.0 <= snap.prefix_hit_rate < cfg.prefix_hit_low
                and int(snap.knobs.get(KNOB_PREFIX_PAGES, 0) or 0) > 0):
            held.append(("prefix_shrink", 0.2))
        held.sort(key=lambda e: (-e[1], e[0]))
        return held

    _PROPOSERS = {
        "dcn": _propose_dcn,
        "gap": _propose_gap,
        "straggler": _propose_rebucket,
        "spec": _propose_spec,
        "headroom": _propose_headroom,
        "prefix_grow": _propose_prefix_grow,
        "prefix_shrink": _propose_prefix_shrink,
    }

    def step(self, snap: WindowSnapshot) -> Optional[Decision]:
        """Consume one window; return at most one decision."""
        cfg = self.cfg
        # Knob cooldowns age by one window.
        for knob in list(self._cooldown):
            self._cooldown[knob] -= 1
            if self._cooldown[knob] <= 0:
                del self._cooldown[knob]
        # Straggler persistence: consecutive windows blaming one rank.
        rank, streak = self._straggler
        if snap.straggler_rank >= 0 and snap.straggler_rank == rank:
            self._straggler = (rank, streak + 1)
        elif snap.straggler_rank >= 0:
            self._straggler = (snap.straggler_rank, 1)
        else:
            self._straggler = (-1, 0)
        # Hysteresis: streaks reset the window a condition lapses.
        held = self._conditions(snap)
        held_names = {name for name, _ in held}
        for name in list(self._sustain):
            if name not in held_names:
                del self._sustain[name]
        for name in held_names:
            self._sustain[name] = self._sustain.get(name, 0) + 1
        # Fire the most urgent sustained rule whose knob is free.  The
        # straggler rule's persistence is its same-rank streak (already
        # >= sustain to be held at all) — the generic streak would
        # double the hysteresis.
        for name, _urgency in held:
            need = 1 if name == "straggler" else cfg.sustain
            if self._sustain.get(name, 0) < need:
                continue
            proposal = self._PROPOSERS[name](self, snap)
            if proposal is None:
                continue
            knob, value, reason = proposal
            if knob in cfg.pinned or knob in self._cooldown:
                continue
            if self._price is not None:
                delta = int(self._price(knob, snap.knobs.get(knob),
                                        value, snap))
                if snap.headroom_bytes >= 0 and delta > snap.headroom_bytes:
                    # Veto: counted, knob untouched, and cooled down so
                    # the same doomed candidate is not re-priced every
                    # window while the pressure lasts.
                    self.vetoes += 1
                    self.veto_log.append((snap.index, knob, value, reason))
                    self._cooldown[knob] = cfg.cooldown
                    self._sustain[name] = 0
                    return None
            decision = Decision(self._seq, snap.index, knob, value, reason)
            self._seq += 1
            self._sustain[name] = 0
            self._cooldown[knob] = cfg.cooldown
            self.decisions.append(decision)
            return decision
        return None
