"""hvd-tune: closed-loop online self-tuning (docs/tuning.md).

The fleet retunes its own performance knobs from live trace + memory
telemetry: **sensors** (sensors.py) fold the hvd-trace span buffer, the
fleet skew tracker, the serving acceptance rate and the HBM ledger into
a per-window diagnosis; the pure **policy** rule table (policy.py) maps
diagnosis -> at most one knob delta per window, with hysteresis,
per-knob cooldown and the hvd-mem planner's byte pricing as an OOM
veto; **actuation** (actuation.py) rides every decision down the
broadcast response stream as a RETUNE marker so all ranks apply at the
same cycle boundary — fleet-coherent by construction, verified by the
env-fingerprint digest every rank publishes over telemetry.

Env contract:
  HVD_TPU_TUNE=1             enable the closed loop (controller side)
  HVD_TPU_TUNE_WINDOW=<n>    decision window in drain ticks (default 64)
  HVD_TPU_TUNE_PIN=a,b       knobs the policy may never touch
  HOROVOD_AUTOTUNE=1         DEPRECATED alias: the round-4 explore-then-
                             commit sweep over (fusion_threshold,
                             cycle_time), folded in as one rule on the
                             same actuation path (its
                             HOROVOD_AUTOTUNE_LOG/_WARMUP_SAMPLES/
                             _SAMPLE_SECONDS contract is unchanged)
"""

from __future__ import annotations

import os
import sys

from .controller import Tuner
from .policy import (COMPRESSION_LADDER, KNOB_NAMES, Decision, PolicyConfig,
                     PolicyEngine, WindowSnapshot)

__all__ = ["Tuner", "Decision", "PolicyConfig", "PolicyEngine",
           "WindowSnapshot", "COMPRESSION_LADDER", "KNOB_NAMES",
           "validate_env", "install"]


def validate_env() -> None:
    """Fail init — not the first decision window — on a malformed
    hvd-tune knob, naming the valid vocabulary."""
    tune = os.environ.get("HVD_TPU_TUNE", "")
    if tune not in ("", "0", "1"):
        raise ValueError(f"HVD_TPU_TUNE={tune!r}: expected 0 or 1")
    window = os.environ.get("HVD_TPU_TUNE_WINDOW")
    if window:
        try:
            if int(window) < 1:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"HVD_TPU_TUNE_WINDOW={window!r}: expected a positive "
                f"integer (decision window in drain ticks)") from None
    raw = os.environ.get("HVD_TPU_TUNE_PIN", "")
    for pin in raw.replace(";", ",").split(","):
        pin = pin.strip()
        if pin and pin not in KNOB_NAMES:
            raise ValueError(
                f"HVD_TPU_TUNE_PIN names unknown knob {pin!r}: expected "
                f"a comma-separated subset of {', '.join(KNOB_NAMES)}")


def install(st) -> None:
    """Wire hvd-tune into a freshly initialized runtime (core/state.init).

    Every rank registers the telemetry collector (env-digest + per-knob
    gauges ride FRAME_METRICS pulls); the process that owns negotiation
    — rank 0 in multi-process mode, the only process otherwise —
    additionally gets the controller when enabled.  The controller is
    published BOTH as ``st.tuner`` (the coordinator tick's marker
    source) and as ``st.autotuner`` (the drain loop's
    record_bytes/maybe_step feed — the round-4 name, kept so the fold-in
    changes no call site)."""
    from . import actuation as _actuation

    _actuation.install_collector()
    st.tuner = None
    st.autotuner = None
    closed_loop = os.environ.get("HVD_TPU_TUNE") == "1"
    sweep = os.environ.get("HOROVOD_AUTOTUNE") == "1"
    if st.coordinator is None or not (closed_loop or sweep):
        return
    if sweep and not closed_loop:
        print("[hvd-tune] HOROVOD_AUTOTUNE=1 is a deprecated alias: the "
              "explore-then-commit sweep now runs inside the hvd-tune "
              "controller (set HVD_TPU_TUNE=1 for the full closed loop)",
              file=sys.stderr)
    st.tuner = st.autotuner = Tuner(st, sweep=sweep,
                                    closed_loop=closed_loop)
