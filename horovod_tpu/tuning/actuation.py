"""hvd-tune actuation: fleet-coherent knob application.

Decisions become :class:`~horovod_tpu.ops.wire.Response` markers of type
``RETUNE`` that ride the broadcast response stream (the CACHE_FLUSH
machinery generalized): rank 0's coordinator tick appends pending
markers (ops/collective._coordinator_tick), every rank's executor
applies them HERE at the same response-stream position
(ops/collective._execute_response_inner), so env knobs, compiled-kernel
caches and cache replicas flip at one cycle boundary — fleet-coherent by
construction.  The marker's ``tensor_names`` carry ``knob=value`` tokens
and ``tensor_sizes`` the decision sequence number.

Verification rides telemetry: every rank publishes a stable integer
digest of the SPMD env fingerprint (``tuning.env_digest`` gauge, fed by
a collector so FRAME_METRICS pulls carry it); after an applied retune
the rank-0 controller compares per-rank digests and rolls the knob back
fleet-wide on divergence (tuning/controller.py).  A worker that missed a
marker across a transport fault is also caught by the EXISTING literal
env-fingerprint check the session-resume RECONNECT handshake re-runs
(ops/transport.py).
"""

from __future__ import annotations

import hashlib
import os
import sys
import weakref
from typing import Dict, List, Optional, Tuple

from .. import telemetry as _telemetry
from ..ops.wire import Response, ResponseType
from . import policy as _policy

_M_APPLIED = _telemetry.counter(
    "tuning.applied", "retune markers applied on this rank")

# Live objects retuned in place (weak so actuation never extends their
# lifetime): in-flight windows expose ``resize``; speculative serving
# engines expose ``set_spec_tokens`` (serving/engine.py registers armed
# engines at construction).
_inflight_windows: "weakref.WeakSet" = weakref.WeakSet()
_spec_engines: "weakref.WeakSet" = weakref.WeakSet()
# EVERY serving engine (speculative or not) registers here so the
# prefix_pages knob can live-trim its cache's index cap and advertise
# the per-page byte price the planner veto needs.
_serving_engines: "weakref.WeakSet" = weakref.WeakSet()


def register_inflight_window(window) -> None:
    _inflight_windows.add(window)


def register_spec_engine(engine) -> None:
    _spec_engines.add(engine)


def spec_engines() -> List[object]:
    return list(_spec_engines)


def register_serving_engine(engine) -> None:
    _serving_engines.add(engine)


def serving_engines() -> List[object]:
    return list(_serving_engines)


# ---------------------------------------------------------------------------
# Knob table: parse/format + current value + per-rank apply
# ---------------------------------------------------------------------------

def _parse_value(knob: str, raw: str):
    if knob == _policy.KNOB_DCN_COMPRESS:
        from ..ops import compression as _compression

        _compression.resolve(raw)  # typo'd name -> ValueError, not a
        return raw                 # half-applied fleet
    if knob == _policy.KNOB_CYCLE_TIME:
        v = float(raw)
        if v <= 0:
            raise ValueError(f"cycle_time must be > 0, got {v}")
        return v
    if knob == _policy.KNOB_PREFIX_PAGES:
        v = int(float(raw))
        if v < 0:  # 0 is legal: the shrink rule may retire the reserve
            raise ValueError(f"prefix_pages must be >= 0, got {v}")
        return v
    v = int(float(raw))  # autotune sweeps may format ints as floats
    if v < 1:
        raise ValueError(f"{knob} must be >= 1, got {v}")
    return v


def current_knobs(st) -> Dict[str, object]:
    """The CURRENT knob values on this rank — the policy's deltas start
    here, and the per-knob gauges publish them (docs/metrics.md)."""
    from ..ops import compression as _compression

    dcn = (os.environ.get("HVD_TPU_DCN_COMPRESS")
           or os.environ.get(_compression.DEFAULT_ENV) or "none")
    try:
        inflight = max(1, int(os.environ.get("HVD_TPU_MAX_INFLIGHT", "2")))
    except ValueError:
        inflight = 2
    try:
        spec = int(os.environ.get("HVD_TPU_SPEC_TOKENS", "3"))
    except ValueError:
        spec = 3
    try:
        prefix = max(0, int(os.environ.get("HVD_TPU_PREFIX_PAGES",
                                           "0")))
    except ValueError:
        prefix = 0
    knobs: Dict[str, object] = {
        _policy.KNOB_DCN_COMPRESS: dcn,
        _policy.KNOB_MAX_INFLIGHT: inflight,
        _policy.KNOB_FUSION_THRESHOLD: int(st.fusion_threshold_bytes),
        _policy.KNOB_CYCLE_TIME: float(st.tick_seconds),
        _policy.KNOB_SPEC_TOKENS: spec,
        _policy.KNOB_PREFIX_PAGES: prefix,
    }
    # A live serving engine advertises its per-page KV byte cost so
    # the planner can price prefix_pages moves (memory/planner.py
    # retune_delta_bytes).
    for engine in serving_engines():
        cache = getattr(engine, "cache", None)
        per_page = getattr(cache, "page_global_bytes", None)
        if per_page is not None:
            try:
                knobs["prefix_page_bytes"] = int(per_page)
            except (TypeError, ValueError):
                pass
            break
    # A live speculative engine advertises its per-token verify cost so
    # the planner can price spec_tokens moves (memory/planner.py).
    for engine in spec_engines():
        per_tok = getattr(engine, "spec_token_bytes", None)
        if callable(per_tok):
            try:
                knobs["spec_token_bytes"] = int(per_tok())
            except Exception:  # noqa: BLE001 — pricing is best-effort
                pass
            break
    return knobs


def _apply_dcn_compress(st, value: str) -> None:
    os.environ["HVD_TPU_DCN_COMPRESS"] = value
    # The compiled megakernels are keyed by WireFormat — a new wire
    # codebook means new programs, dropped fleet-wide at this same
    # stream position so no rank mixes codebooks within a cycle.
    from ..ops import megakernel as _megakernel

    _megakernel.flush(f"hvd-tune: dcn compression -> {value}")


def _apply_max_inflight(st, value: int) -> None:
    os.environ["HVD_TPU_MAX_INFLIGHT"] = str(value)
    for window in list(_inflight_windows):
        try:
            window.resize(value)
        except Exception:  # noqa: BLE001 — a dying step wrapper must
            pass           # not wedge the drain tick


def _apply_fusion_threshold(st, value: int) -> None:
    st.fusion_threshold_bytes = int(value)
    if st.coordinator is not None:
        # Rank 0 / single-process: the facade invalidates memoized
        # packing plans and flushes the megakernels itself.
        st.coordinator.set_fusion_threshold(int(value))
        from ..core import state as _state

        for ps in _state.process_sets_snapshot():
            if ps.coordinator is not None:
                ps.coordinator.set_fusion_threshold(int(value))
    else:
        # Workers hold no coordinator but DO hold a cache replica with
        # memoized packing plans and compiled megakernels.
        if st.response_cache is not None:
            st.response_cache.invalidate_plans(
                f"hvd-tune: fusion threshold -> {value}")
        from ..ops import megakernel as _megakernel

        _megakernel.flush(f"hvd-tune: fusion threshold -> {value}")


def _apply_cycle_time(st, value: float) -> None:
    st.tick_seconds = float(value)


def _apply_spec_tokens(st, value: int) -> None:
    os.environ["HVD_TPU_SPEC_TOKENS"] = str(value)
    for engine in list(_spec_engines):
        try:
            engine.set_spec_tokens(int(value))
        except Exception:  # noqa: BLE001 — a draining engine must not
            pass           # wedge the drain tick


def _apply_prefix_pages(st, value: int) -> None:
    # The env feeds the NEXT engine build (the device-side reserve is
    # fixed at construction); live engines get their index cap
    # retuned immediately — shrink trims the reclaimable LRU, grow
    # lifts the cap so subsequent prompts publish into it.
    os.environ["HVD_TPU_PREFIX_PAGES"] = str(value)
    for engine in list(_serving_engines):
        try:
            engine.cache.set_prefix_target(int(value))
        except Exception:  # noqa: BLE001 — a draining engine must not
            pass           # wedge the drain tick


_APPLIERS = {
    _policy.KNOB_DCN_COMPRESS: _apply_dcn_compress,
    _policy.KNOB_MAX_INFLIGHT: _apply_max_inflight,
    _policy.KNOB_FUSION_THRESHOLD: _apply_fusion_threshold,
    _policy.KNOB_CYCLE_TIME: _apply_cycle_time,
    _policy.KNOB_SPEC_TOKENS: _apply_spec_tokens,
    _policy.KNOB_PREFIX_PAGES: _apply_prefix_pages,
}


# ---------------------------------------------------------------------------
# Marker construction + apply (the response-stream surface)
# ---------------------------------------------------------------------------

def make_marker(tokens: List[str], seq: int) -> Response:
    """A RETUNE stream marker: ``knob=value`` tokens + the decision
    sequence number every rank logs on apply."""
    return Response(ResponseType.RETUNE, tensor_names=list(tokens),
                    tensor_sizes=[int(seq)])


def apply_marker(resp: Response, st) -> None:
    """Apply one RETUNE marker on THIS rank — called from the response
    executor at the marker's stream position on every rank.  Malformed
    tokens are skipped with a diagnostic (the drain tick must survive
    anything the wire carries), applied tokens update the per-knob
    gauges and the apply log line the np=2 coherence leg parses."""
    seq = int(resp.tensor_sizes[0]) if resp.tensor_sizes else -1
    applied: List[Tuple[str, object]] = []
    for token in resp.tensor_names:
        knob, _, raw = token.partition("=")
        applier = _APPLIERS.get(knob)
        if applier is None:
            print(f"[hvd-tune] rank {st.process_index} skipping unknown "
                  f"retune knob {token!r} (seq={seq})", file=sys.stderr)
            continue
        try:
            value = _parse_value(knob, raw)
            applier(st, value)
        except (TypeError, ValueError) as e:
            print(f"[hvd-tune] rank {st.process_index} skipping malformed "
                  f"retune {token!r} (seq={seq}): {e}", file=sys.stderr)
            continue
        applied.append((knob, value))
    if applied:
        _M_APPLIED.inc(len(applied))
        pairs = " ".join(f"{k}={v}" for k, v in applied)
        print(f"[hvd-tune] rank {st.process_index} applied seq={seq} "
              f"{pairs}", file=sys.stderr)
    tuner = st.tuner
    if tuner is not None:
        tuner.note_applied(seq, applied)


# ---------------------------------------------------------------------------
# Fleet-coherence telemetry: the env-fingerprint digest gauge
# ---------------------------------------------------------------------------

def env_digest() -> int:
    """Stable 53-bit integer digest of the SPMD env fingerprint
    (ops/compression.env_fingerprint) — integers survive the JSON
    metrics wire exactly, full float53 precision."""
    from ..ops import compression as _compression

    h = hashlib.sha256(_compression.env_fingerprint().encode()).digest()
    return int.from_bytes(h[:7], "big") >> 3


def _collect_tuning(reg) -> None:
    """Every rank publishes its fingerprint digest + current knob values
    (docs/metrics.md "hvd-tune"); the digest rides FRAME_METRICS pulls so
    the rank-0 controller can verify a retune landed fleet-wide."""
    reg.gauge("tuning.env_digest",
              "53-bit digest of the SPMD env fingerprint").set(env_digest())
    from ..core import state as _state

    st = _state.global_state()
    if not st.initialized:
        return
    knobs = current_knobs(st)
    reg.gauge("tuning.knob.dcn_compress",
              "DCN compression ladder rung (none/bf16/int8/int4)").set(
        _policy.COMPRESSION_LADDER.index(knobs[_policy.KNOB_DCN_COMPRESS])
        if knobs[_policy.KNOB_DCN_COMPRESS] in _policy.COMPRESSION_LADDER
        else -1)
    reg.gauge("tuning.knob.max_inflight",
              "in-flight dispatch window depth").set(
        knobs[_policy.KNOB_MAX_INFLIGHT])
    reg.gauge("tuning.knob.fusion_threshold",
              "tensor-fusion threshold bytes").set(
        knobs[_policy.KNOB_FUSION_THRESHOLD])
    reg.gauge("tuning.knob.cycle_time",
              "background tick period seconds").set(
        knobs[_policy.KNOB_CYCLE_TIME])
    reg.gauge("tuning.knob.spec_tokens",
              "speculative decode depth").set(
        knobs[_policy.KNOB_SPEC_TOKENS])
    reg.gauge("tuning.knob.prefix_pages",
              "dedicated shared-prefix page reserve").set(
        knobs[_policy.KNOB_PREFIX_PAGES])


def install_collector() -> None:
    """Idempotent (keyed) registration — every rank, every init."""
    _telemetry.registry().register_collector("tuning", _collect_tuning)
