"""hvd-tune controller: the rank-0 closed loop (ROADMAP open item 3).

One :class:`Tuner` lives on the process that owns negotiation — rank 0
in multi-process mode, the only process otherwise — and is driven from
the drain tick exactly like the round-4 autotuner it absorbs:
``record_bytes`` per executed response, ``maybe_step`` per tick.  Every
``HVD_TPU_TUNE_WINDOW`` ticks it samples the sensors
(tuning/sensors.py), runs the pure policy engine (tuning/policy.py),
and turns at most one decision into a RETUNE stream marker the next
coordinator tick broadcasts (tuning/actuation.py) — so the controller
itself never mutates a knob; it only ever *asks the stream to*, and its
own rank applies at the same stream position as everyone else.

Round-4 autotune fold-in: when ``HOROVOD_AUTOTUNE=1`` (kept as a
deprecated alias of the subsystem) the explore-then-commit sweep
(utils/autotune.py) runs as one rule inside this controller, its apply
hook redirected onto the same marker path — and the sweep's two knobs
(fusion_threshold, cycle_time) are pinned out of the rule table's
reach, so two tuners can never fight over one knob.  ``done`` flips
only once the commit marker has been APPLIED locally (not merely
enqueued): callers that loop on ``autotuner.done`` observe the
committed values the moment the loop exits.

Fleet verification: after an applied retune in multi-process mode the
next window pulls ``cluster_metrics()`` and compares every rank's
``tuning.env_digest`` gauge; divergence (a rank that somehow missed the
marker) increments ``tuning.rollbacks`` and enqueues a rollback marker
restoring the previous values fleet-wide — a retune either completes on
every rank or is rolled back on every rank, never a split-knob fleet.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Tuple

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..ops.wire import Response
from . import actuation as _actuation
from . import policy as _policy

_M_DECISIONS = _telemetry.counter(
    "tuning.decisions", "policy decisions enqueued as RETUNE markers")
_M_VETOES = _telemetry.counter(
    "tuning.vetoes", "candidates vetoed by the planner's byte pricing")
_M_ROLLBACKS = _telemetry.counter(
    "tuning.rollbacks", "retunes rolled back after a fleet-coherence "
                        "divergence")

DEFAULT_WINDOW_TICKS = 64

_SWEEP_KNOBS = (_policy.KNOB_FUSION_THRESHOLD, _policy.KNOB_CYCLE_TIME)


def _pinned_from_env() -> frozenset:
    raw = os.environ.get("HVD_TPU_TUNE_PIN", "")
    return frozenset(p.strip() for p in raw.replace(";", ",").split(",")
                     if p.strip())


class Tuner:
    def __init__(self, st, sweep: bool = False, closed_loop: bool = False,
                 window_ticks: Optional[int] = None,
                 policy_config: Optional[_policy.PolicyConfig] = None,
                 verify_timeout: float = 2.0):
        self._st = st
        self._lock = _lockorder.make_lock("tuning.Tuner._lock")
        self._pending: List[Response] = []  # guarded_by: _lock
        self._next_seq = 0                  # guarded_by: _lock
        self._applied_seq = -1
        self._commit_seq: Optional[int] = None
        self._verify_timeout = float(verify_timeout)
        self._verify_due = False
        # seq -> [(knob, previous value)] for rollback on divergence.
        self._undo: Dict[int, List[Tuple[str, object]]] = {}
        self._ticks = 0
        self._window_ticks = int(
            window_ticks if window_ticks is not None
            else os.environ.get("HVD_TPU_TUNE_WINDOW",
                                DEFAULT_WINDOW_TICKS))
        self._sweep = None
        self.policy: Optional[_policy.PolicyEngine] = None
        self._sensors = None
        self._vetoes_seen = 0
        if closed_loop:
            from ..memory.planner import retune_delta_bytes
            from .sensors import WindowAggregator

            cfg = policy_config
            if cfg is None:
                pinned = _pinned_from_env()
                if sweep:
                    # The fold-in's no-fighting rule: while the sweep
                    # owns its two knobs the rule table cannot touch
                    # them.
                    pinned = pinned | frozenset(_SWEEP_KNOBS)
                cfg = _policy.PolicyConfig(pinned=pinned)
            self.policy = _policy.PolicyEngine(
                cfg, price=lambda knob, old, new, snap:
                retune_delta_bytes(knob, old, new, snap.knobs))
            self._sensors = WindowAggregator(
                st, straggler_skew_s=cfg.straggler_skew_us / 1e6)
        if sweep:
            from ..utils.autotune import Autotuner

            self._sweep = Autotuner(self._enqueue_sweep)

    # -- the autotune drain-loop contract ---------------------------------
    def record_bytes(self, n: int) -> None:
        if self._sweep is not None:
            self._sweep.record_bytes(n)

    @property
    def committed(self):
        return self._sweep.committed if self._sweep is not None else None

    @property
    def done(self) -> bool:
        """The sweep is finished AND its commit has been applied locally
        — loops waiting on ``done`` must observe the committed values."""
        if self._sweep is None or self._sweep.committed is None:
            return False
        return self._commit_seq is not None \
            and self._applied_seq >= self._commit_seq

    def close(self) -> None:
        if self._sweep is not None:
            self._sweep.close()

    # -- marker plumbing ---------------------------------------------------
    def _enqueue(self, tokens: List[str],
                 undo: Optional[List[Tuple[str, object]]] = None) -> int:
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._pending.append(_actuation.make_marker(tokens, seq))
            if undo:
                self._undo[seq] = list(undo)
        return seq

    def _enqueue_sweep(self, threshold: int, cycle: float) -> None:
        """The Autotuner's apply hook, redirected onto the marker path."""
        seq = self._enqueue([
            f"{_policy.KNOB_FUSION_THRESHOLD}={int(threshold)}",
            f"{_policy.KNOB_CYCLE_TIME}={float(cycle)}"])
        if self._sweep is not None and self._sweep.committed is not None \
                and self._commit_seq is None:
            self._commit_seq = seq

    def take_markers(self) -> List[Response]:
        """Drain pending markers — called by the coordinator tick, which
        appends them to the broadcast response stream."""
        with self._lock:
            pending, self._pending = self._pending, []
        return pending

    def note_applied(self, seq: int, applied) -> None:
        """Actuation's callback once THIS rank applied a marker."""
        if seq > self._applied_seq:
            self._applied_seq = seq
        if applied and self._st.multiprocess:
            self._verify_due = True

    # -- the closed loop ---------------------------------------------------
    def maybe_step(self) -> None:
        if self._sweep is not None:
            self._sweep.maybe_step()
        if self.policy is None:
            return
        self._ticks += 1
        if self._ticks % self._window_ticks:
            return
        if self._verify_due:
            self._verify_due = False
            self._verify_fleet()
        snap = self._sensors.sample()
        decision = self.policy.step(snap)
        if self.policy.vetoes > self._vetoes_seen:
            _M_VETOES.inc(self.policy.vetoes - self._vetoes_seen)
            self._vetoes_seen = self.policy.vetoes
        if decision is None:
            return
        old = snap.knobs.get(decision.knob)
        seq = self._enqueue([decision.wire()],
                            undo=[(decision.knob, old)])
        _M_DECISIONS.inc()
        print(f"[hvd-tune] decision seq={seq} window={snap.index} "
              f"{decision.wire()}: {decision.reason}", file=sys.stderr)

    def _verify_fleet(self) -> None:
        """Post-retune coherence check: every rank's env-digest gauge
        must agree.  Divergence -> rollback marker, fleet-wide."""
        if not self._st.multiprocess or self._st.transport is None:
            return
        try:
            agg = _telemetry.cluster_metrics(timeout=self._verify_timeout)
        except Exception:  # noqa: BLE001 — a mid-shutdown pull must not
            return         # kill the drain tick; re-verified next window
        per_rank = (agg.get("tuning.env_digest") or {}).get("per_rank")
        if not per_rank or len(set(per_rank.values())) <= 1:
            return
        _M_ROLLBACKS.inc()
        undo: List[Tuple[str, object]] = []
        with self._lock:
            for seq in sorted(self._undo, reverse=True):
                undo.extend(self._undo.pop(seq))
        ranks = sorted(per_rank)
        print(f"[hvd-tune] env-digest divergence across ranks {ranks} "
              f"after retune: rolling back {len(undo)} knob(s) "
              f"fleet-wide", file=sys.stderr)
        if undo:
            self._enqueue([f"{k}={v}" for k, v in undo])
