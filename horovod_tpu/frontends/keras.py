"""Keras frontend: the reference's ``horovod.keras`` API over the TPU
runtime, targeting Keras 3 on the JAX backend (set ``KERAS_BACKEND=jax``
before importing keras — the TPU-native combination).

Re-creation of the reference surface (horovod/keras/__init__.py:29-160,
horovod/keras/callbacks.py) with the TF-session plumbing replaced by the
eager collective path of :mod:`..ops.collective` and, inside compiled
training, the same dual-path reduction the optax
:class:`~horovod_tpu.parallel.data.DistributedOptimizer` uses:

* **eager** (custom training loops calling ``optimizer.apply`` /
  ``apply_gradients`` with concrete arrays): gradients go through the
  dynamic-path allreduce queue exactly like the reference's
  ``get_gradients`` override (horovod/keras/__init__.py:43-65).
* **compiled under shard_map** over the replica axis: fused ``lax.psum``
  reduction.
* **compiled under Keras's own jit** (``model.fit`` on the JAX backend,
  with or without ``keras.distribution.DataParallel``): gradients of the
  global batch are already synchronized by XLA's SPMD partitioner — the
  TPU-native analogue of the allreduce — so they pass through unchanged.

Usage parity::

    import horovod_tpu.frontends.keras as hvd
    hvd.init()
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.01))
    model.compile(optimizer=opt, loss="mse")
    model.fit(x, y, callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
    ])
"""

from __future__ import annotations

import os
from types import SimpleNamespace
from typing import Optional

import jax
import numpy as np

# Keras 3 binds its backend at first import; this frontend needs the JAX
# backend.  Setting the default here covers the common case (horovod_tpu
# imported before keras); if keras was already imported on another
# backend, DistributedOptimizer raises a diagnosis below.
os.environ.setdefault("KERAS_BACKEND", "jax")

from ..core import state as _state
from ..core.state import (cross_rank, cross_size, init,  # noqa: F401
                          is_initialized, local_rank, local_size,
                          mpi_threads_supported, rank, shutdown, size)
from ..ops import collective as _C
from ..ops.collective import (  # noqa: F401  (post-v0.13 API surface)
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    add_process_set,
)
from ..ops.compression import Compression  # noqa: F401  (hvd.Compression)
from ..ops.process_set import ProcessSet  # noqa: F401
from ..ops.objects import (allgather_object,  # noqa: F401  (object API)
                           broadcast_object)
from ..parallel import data as _D


def _reduce_grads(grads, average: bool, compression=None):
    """Dual-path gradient reduction shared with the optax wrapper."""
    leaves = [g for g in grads if g is not None]
    if not leaves:
        return grads
    traced = any(isinstance(g, jax.core.Tracer) for g in leaves)
    if traced:
        if _D._in_replica_context():
            red = iter(_D.allreduce_gradients(leaves, average=average,
                                              compression=compression))
            return [next(red) if g is not None else None for g in grads]
        if _state.is_initialized() and _state.global_state().multiprocess:
            # N separate jitted programs cannot be synced by a pass-
            # through; silent pass-through would train each process
            # independently after the one-time broadcast.
            import keras

            if keras.distribution.distribution() is None:
                raise RuntimeError(
                    "model.fit in multi-process mode needs a global-batch "
                    "SPMD program: set keras.distribution.set_distribution("
                    "keras.distribution.DataParallel(...)) over the global "
                    "devices (then XLA syncs gradients), or run the "
                    "training loop eagerly so the allreduce queue can.")
        # Keras's jitted train step: XLA's SPMD partitioner owns the
        # cross-device sync (keras.distribution / sharded inputs).
        return grads
    if not _state.is_initialized():
        raise _state.NotInitializedError()
    if _state.size() <= 1:
        return grads
    red = iter(_D._eager_allreduce_grads(leaves, average=average,
                                         compression=compression))
    return [next(red) if g is not None else None for g in grads]


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         average: bool = True, compression=None):
    """Wrap a ``keras.optimizers.Optimizer`` so gradients are averaged
    across replicas before the update.

    Same dynamic-subclass trick as the reference
    (horovod/keras/__init__.py:86-91): the returned object is an instance
    of a class with the wrapped optimizer's name and base class, so a
    saved model restores without horovod_tpu installed.  Keras 3 funnels
    every path — ``apply_gradients``, eager ``apply``, and the jitted
    ``stateless_apply`` — through ``apply``, which is where the
    reduction hooks in (the Keras-3 analogue of the reference's
    ``get_gradients`` override).
    """
    import keras

    if keras.backend.backend() != "jax":
        raise RuntimeError(
            f"horovod_tpu.frontends.keras needs Keras on the JAX backend, "
            f"but keras was already imported with backend "
            f"'{keras.backend.backend()}' (importing tensorflow first can "
            f"cause this).  Set KERAS_BACKEND=jax before the first keras "
            f"import.")

    base = optimizer.__class__

    def _apply(self, grads, trainable_variables=None):
        grads = _reduce_grads(list(grads), self._hvd_average,
                              self._hvd_compression)
        return super(cls, self).apply(grads, trainable_variables)

    cls = type(base.__name__, (base,),
               {"apply": _apply, "_hvd_average": average,
                "_hvd_compression": compression,
                "_hvd_name": name or f"Distributed{base.__name__}"})
    config = optimizer.get_config()
    return cls.from_config(config) if hasattr(cls, "from_config") \
        else cls(**config)


# hvd-analyze: signature records from this binding carry source=keras
# (analysis/program.py).
from ..analysis.program import tag_source as _tag_source_factory

_tag_source = _tag_source_factory("keras")


@_tag_source
def broadcast_global_variables(model_or_variables, root_rank: int = 0):
    """Broadcast all variables (model + optimizer) from ``root_rank``
    (≙ horovod/keras/__init__.py:94-102, minus the TF session).  Accepts
    a Keras model, an optimizer, or an iterable of ``keras.Variable``."""
    variables = getattr(model_or_variables, "variables", None)
    if variables is None:
        variables = list(model_or_variables)
    opt = getattr(model_or_variables, "optimizer", None)
    if opt is not None:
        variables = list(variables) + list(opt.variables)
    handles = [
        _C.broadcast_async(np.asarray(v), root_rank,
                           name=f"broadcast.keras.{i}.{v.path}")
        for i, v in enumerate(variables)
    ]
    for v, h in zip(variables, handles):
        v.assign(np.asarray(_C.synchronize(h)))


@_tag_source
def allreduce(value, name: Optional[str] = None, average=None, op=None,
              process_set=None):
    """Allreduce a tensor-compatible value (≙ keras/__init__.py:105-118);
    ``op`` (hvd.Average/Sum/Adasum/Min/Max/Product, superseding
    ``average``) and ``process_set`` carry the post-v0.13 contracts."""
    return np.asarray(_C.allreduce(np.asarray(value), average=average,
                                   name=name, op=op,
                                   process_set=process_set))


@_tag_source
def allgather(value, name: Optional[str] = None, process_set=None):
    return np.asarray(_C.allgather(np.asarray(value), name=name,
                                   process_set=process_set))


@_tag_source
def broadcast(value, root_rank: int, name: Optional[str] = None,
              process_set=None):
    return np.asarray(_C.broadcast(np.asarray(value), root_rank,
                                   name=name, process_set=process_set))


# ---------------------------------------------------------------------------
# Callbacks (≙ horovod/keras/callbacks.py)
# ---------------------------------------------------------------------------

def _make_callbacks():
    import keras

    class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
        """Broadcast initial variables from ``root_rank`` at train start
        (≙ keras/callbacks.py:24-44)."""

        def __init__(self, root_rank: int = 0):
            super().__init__()
            self.root_rank = root_rank
            self.broadcast_done = False

        def on_batch_begin(self, batch, logs=None):
            if self.broadcast_done:
                return
            broadcast_global_variables(self.model, self.root_rank)
            self.broadcast_done = True

    class MetricAverageCallback(keras.callbacks.Callback):
        """Average epoch metrics over all replicas before other callbacks
        (checkpointing, early stopping) read them
        (≙ keras/callbacks.py:47-70).  Any numeric log averages —
        scalars AND arrays (the reference averages every logged value);
        non-numeric values pass through."""

        def on_epoch_end(self, epoch, logs=None):
            from ..callbacks import _average_metric

            if not logs:
                return
            for k in sorted(logs.keys()):
                red = _average_metric(allreduce, k, logs[k])
                if red is not None:
                    logs[k] = red

    class LearningRateScheduleCallback(keras.callbacks.Callback):
        """Multiply the initial LR by ``multiplier`` over
        [start_epoch, end_epoch) (≙ keras/callbacks.py:73-129)."""

        def __init__(self, multiplier, start_epoch: int = 0,
                     end_epoch: Optional[int] = None, staircase: bool = True,
                     momentum_correction: bool = True,
                     steps_per_epoch: Optional[int] = None):
            super().__init__()
            self.multiplier = (multiplier if callable(multiplier)
                               else (lambda epoch: multiplier))
            self.start_epoch = start_epoch
            self.end_epoch = end_epoch
            if not staircase and keras.backend.backend() == "jax":
                # The Keras JAX trainer runs each epoch from state captured
                # at the first batch; mid-epoch variable writes never reach
                # the jitted step.  Degrade to epoch-granular adjustment
                # (documented deviation from the reference's per-batch
                # ramp).
                staircase = True
            self.staircase = staircase
            self.momentum_correction = momentum_correction
            self.steps_per_epoch = steps_per_epoch
            self.initial_lr = None
            self.current_epoch = None
            # (true momentum, lr at save time) — corrections are always
            # computed from these so repeated adjustments cannot compound.
            self._momentum_ref = None

        def _autodetect_initial_lr(self):
            if self.initial_lr is None:
                self.initial_lr = float(
                    np.asarray(self.model.optimizer.learning_rate))
            return self.initial_lr

        def _adjust(self, epoch):
            old_lr = float(np.asarray(self.model.optimizer.learning_rate))
            new_lr = self._autodetect_initial_lr() * self.multiplier(epoch)
            self.model.optimizer.learning_rate = new_lr
            if (self.momentum_correction
                    and hasattr(self.model.optimizer, "momentum")
                    and old_lr > 0):
                # Momentum correction: scale the TRUE momentum by
                # new_lr / lr_at_save (≙ keras/callbacks.py:104-116).
                if self._momentum_ref is None:
                    self._momentum_ref = (
                        float(np.asarray(self.model.optimizer.momentum)),
                        old_lr)
                m0, lr0 = self._momentum_ref
                self.model.optimizer.momentum = m0 * new_lr / lr0

        def on_epoch_begin(self, epoch, logs=None):
            self.current_epoch = epoch
            if self.staircase and epoch >= self.start_epoch and (
                    self.end_epoch is None or epoch < self.end_epoch):
                self._adjust(epoch)

        def on_batch_begin(self, batch, logs=None):
            if self.staircase:
                return
            epoch = self.current_epoch or 0
            if epoch >= self.start_epoch and (
                    self.end_epoch is None or epoch < self.end_epoch):
                steps = (self.steps_per_epoch
                         or self.params.get("steps") or 1)
                frac = epoch + float(batch) / max(1, steps)
                self._adjust(frac)

        def on_epoch_end(self, epoch, logs=None):
            if self._momentum_ref is not None:
                # Restore the true (uncorrected) momentum so checkpoints
                # and get_config() never see the corrected value.
                self.model.optimizer.momentum = self._momentum_ref[0]
                self._momentum_ref = None
            if logs is not None:
                logs["lr"] = float(
                    np.asarray(self.model.optimizer.learning_rate))

    class LearningRateWarmupCallback(LearningRateScheduleCallback):
        """Ramp LR from (initial / size) to initial * size-scaling over
        ``warmup_epochs`` — the gradual-warmup recipe of the large-batch
        paper the reference implements (≙ keras/callbacks.py:132-186)."""

        def __init__(self, warmup_epochs: int = 5, momentum_correction: bool
                     = True, steps_per_epoch: Optional[int] = None,
                     verbose: int = 0):
            self.warmup_epochs = warmup_epochs
            self.verbose = verbose

            def multiplier(progress):
                # progress may be fractional (per-batch ramp on backends
                # that support it) or the integer epoch (JAX backend);
                # reaches exactly 1.0 at the end of warmup either way.
                p = min(progress + 1, self.warmup_epochs)
                return 1.0 / size() + p * (1.0 - 1.0 / size()) \
                    / self.warmup_epochs

            super().__init__(multiplier, start_epoch=0,
                             end_epoch=warmup_epochs, staircase=False,
                             momentum_correction=momentum_correction,
                             steps_per_epoch=steps_per_epoch)

        def on_epoch_end(self, epoch, logs=None):
            super().on_epoch_end(epoch, logs)
            if epoch == self.warmup_epochs - 1 and self.verbose and \
                    rank() == 0:
                print(f"Epoch {epoch + 1}: finished gradual learning rate "
                      f"warmup to {np.asarray(self.model.optimizer.learning_rate)}.")

    return SimpleNamespace(
        BroadcastGlobalVariablesCallback=BroadcastGlobalVariablesCallback,
        MetricAverageCallback=MetricAverageCallback,
        LearningRateScheduleCallback=LearningRateScheduleCallback,
        LearningRateWarmupCallback=LearningRateWarmupCallback,
    )


# Lazy so `import horovod_tpu.frontends.keras` works before keras does.
class _CallbacksModule:
    _cached = None

    def __getattr__(self, item):
        if _CallbacksModule._cached is None:
            _CallbacksModule._cached = _make_callbacks()
        return getattr(_CallbacksModule._cached, item)


callbacks = _CallbacksModule()
