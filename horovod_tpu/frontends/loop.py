"""Keras-style training loop for JAX — host for the Horovod callbacks.

The reference's L5 glue assumes a Keras/Estimator loop exists to hang
callbacks on (horovod/keras/callbacks.py); JAX has no such loop, so this
module provides a minimal one with the same callback protocol
(`on_train_begin`, `on_epoch_begin/end`, `on_batch_begin/end`) while the
step itself stays a single compiled SPMD program from
:mod:`..parallel.training`.

Learning rate and momentum are *runtime-settable without recompilation*:
the optimizer is wrapped in ``optax.inject_hyperparams`` so the callbacks'
per-batch LR adjustments (warmup/schedule with momentum correction,
≙ keras/callbacks.py:90-259) mutate optimizer state, not the compiled
graph — the TPU-friendly translation of Keras' ``K.set_value`` on
optimizer variables.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core import state as _state
from ..parallel.input import prefetch_to_device
from ..parallel.training import (barrier_fence, make_train_step,
                                 make_train_step_with_state, shard_batch)


class Trainer:
    """Minimal distributed training loop.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` or, with
        ``model_state``, ``loss_fn(params, model_state, batch) ->
        (scalar, new_model_state)``.
      params: initial parameter pytree.
      optimizer_fn: optax optimizer factory, e.g. ``optax.sgd``; called as
        ``optimizer_fn(learning_rate=lr, **optimizer_kwargs)`` under
        ``inject_hyperparams``.
      lr: initial learning rate (``initial_lr`` in callback terms).
      callbacks: list of callback objects (see :mod:`horovod_tpu.callbacks`).
      model_state: optional non-trained model state (e.g. BatchNorm stats).
      zero: ZeRO-1 optimizer-state sharding (see
        :mod:`horovod_tpu.parallel.zero`; the optimizer must be
        elementwise — tree-wide transforms like ``clip_by_global_norm``
        would see only their local shard).  ``fusion_threshold`` does
        not apply in this mode: the flattened gradient is one maximal
        fusion bucket.
      fsdp: FSDP/ZeRO-3 fully-sharded storage (see
        :mod:`horovod_tpu.parallel.fsdp`): parameters AND optimizer
        state live as 1/N flat shards between steps.  ``trainer.params``
        stays the full pytree contract — reading it gathers the shards,
        assigning it re-shards — so every callback (broadcast,
        checkpoint) works unchanged; the hot loop itself runs on the
        shard.  Same elementwise-optimizer precondition as ``zero``.
    """

    def __init__(self, loss_fn, params, optimizer_fn=optax.sgd,
                 lr: float = 0.01, optimizer_kwargs: Optional[dict] = None,
                 callbacks: Optional[Sequence] = None, model_state=None,
                 average_gradients: bool = True,
                 fusion_threshold: Optional[int] = None,
                 zero: bool = False, fsdp: bool = False):
        _state._check_initialized()
        if zero and fsdp:
            raise ValueError("zero and fsdp are mutually exclusive: "
                             "fsdp shards everything zero does and the "
                             "parameters too")
        self._fsdp = fsdp
        self._fstep = None
        if not fsdp:
            self.params = params
        self.model_state = model_state
        self._has_state = model_state is not None
        kwargs = dict(optimizer_kwargs or {})
        self._momentum_key = "momentum" if "momentum" in kwargs else None
        self.optimizer = optax.inject_hyperparams(optimizer_fn)(
            learning_rate=lr, **kwargs)
        if (zero or fsdp) and fusion_threshold is not None:
            import warnings

            warnings.warn(
                f"fusion_threshold is ignored with "
                f"{'fsdp' if fsdp else 'zero'}=True: the flattened "
                "gradient is one maximal fusion bucket", stacklevel=2)
        if fsdp:
            from ..parallel.fsdp import (make_fsdp_train_step,
                                         make_fsdp_train_step_with_state)

            builder = (make_fsdp_train_step_with_state if self._has_state
                       else make_fsdp_train_step)
            self._fstep = builder(loss_fn, self.optimizer,
                                  average=average_gradients, donate=False)
            self._p_shard, self.opt_state = self._fstep.init(params)
            self._step = self._fstep.step
        elif zero:
            # ZeRO-1: sharded optimizer state (parallel/zero.py).  The
            # step/opt_state contracts match the replicated builders, so
            # callbacks (LR mutation included — hyperparams are
            # replicated scalar leaves) work unchanged.
            from ..parallel.zero import (make_zero_train_step,
                                         make_zero_train_step_with_state)

            builder = (make_zero_train_step_with_state if self._has_state
                       else make_zero_train_step)
            zstep = builder(loss_fn, self.optimizer,
                            average=average_gradients, donate=False)
            self.opt_state = zstep.init(params)
            self._step = zstep.step
        else:
            self.opt_state = self.optimizer.init(params)
            builder = (make_train_step_with_state if self._has_state
                       else make_train_step)
            self._step = builder(
                loss_fn, self.optimizer, average=average_gradients,
                fusion_threshold=fusion_threshold, donate=False)
        self.callbacks = list(callbacks or [])
        for cb in self.callbacks:
            if hasattr(cb, "set_trainer"):
                cb.set_trainer(self)
        self.history: List[dict] = []
        self.steps_per_epoch: Optional[int] = None
        self.stop_training = False

    # -- parameter access: the pytree contract survives fsdp ------------
    @property
    def params(self):
        """The full parameter pytree.  Under ``fsdp=True`` reading
        gathers the 1/N shards and assigning re-shards, so callbacks
        (broadcast at train begin, rank-0 checkpointing) see the same
        contract as every other mode."""
        if self._fsdp:
            return self._fstep.full_params(self._p_shard)
        return self._params

    @params.setter
    def params(self, value) -> None:
        if getattr(self, "_fsdp", False):
            self._p_shard = self._fstep.shard_params(value)
        else:
            self._params = value

    # -- hyperparameter access for callbacks (≙ K.get/set_value on
    #    optimizer.lr / optimizer.momentum) ------------------------------
    @property
    def lr(self) -> float:
        return float(self.opt_state.hyperparams["learning_rate"])

    @lr.setter
    def lr(self, value: float) -> None:
        self.opt_state.hyperparams["learning_rate"] = jnp.asarray(
            value, jnp.float32)

    @property
    def momentum(self) -> Optional[float]:
        if self._momentum_key is None:
            return None
        return float(self.opt_state.hyperparams[self._momentum_key])

    @momentum.setter
    def momentum(self, value: float) -> None:
        if self._momentum_key is None:
            raise AttributeError("optimizer has no momentum hyperparameter")
        self.opt_state.hyperparams[self._momentum_key] = jnp.asarray(
            value, jnp.float32)

    @property
    def size(self) -> int:
        return _state.size()

    # -- loop -------------------------------------------------------------
    def _call(self, hook: str, *args) -> None:
        for cb in self.callbacks:
            fn = getattr(cb, hook, None)
            if fn is not None:
                fn(*args)

    def fit(self, batches: Callable[[int, int], Any], epochs: int,
            steps_per_epoch: int, initial_epoch: int = 0,
            prefetch: int = 2,
            log_every: Optional[int] = None) -> List[dict]:
        """Run the loop.  ``batches(epoch, step)`` returns one global batch
        (leading axis divisible by the replica count).

        ``initial_epoch`` resumes epoch numbering after a checkpoint
        restore so epoch-indexed callbacks (warmup, schedules) continue
        where they left off — the reference example passes the broadcast
        ``resume_from_epoch`` to Keras ``fit`` the same way
        (examples/keras_imagenet_resnet50.py:130-133).

        The loop is host-overlapped (hvd-pipeline): each epoch's batches
        stage host→device through :func:`..parallel.input
        .prefetch_to_device` (``prefetch`` = queue depth; 0 restores the
        synchronous per-step ``shard_batch``), and the step's outputs
        are NOT fetched per step — losses stay device arrays until the
        epoch-end log (JAX's async dispatch then pipelines step N+1's
        launch under step N's execution).  ``log_every=k`` additionally
        fetches the current loss every k steps and hands it to the
        callbacks' ``on_batch_end`` logs — an explicit, bounded
        synchronization point for progress reporting.

        NOTE with ``prefetch>0`` the ``batches`` callable runs on a
        background stager thread, up to ``prefetch+1`` steps AHEAD of
        (and concurrent with) the step/callback sequence.  If it is not
        thread-safe, or reads state the callbacks mutate per batch
        (curriculum keyed on ``trainer.lr`` etc.), pass ``prefetch=0``.
        """
        self.steps_per_epoch = steps_per_epoch
        self._call("on_train_begin", None)
        for epoch in range(initial_epoch, epochs):
            if self.stop_training:
                break
            self._call("on_epoch_begin", epoch, None)
            losses = []

            def epoch_batches(epoch=epoch):
                for s in range(steps_per_epoch):
                    yield batches(epoch, s)

            if prefetch and prefetch > 0:
                staged = prefetch_to_device(epoch_batches(), depth=prefetch)
            else:
                staged = (shard_batch(b) for b in epoch_batches())
            try:
                for step, batch in enumerate(staged):
                    self._call("on_batch_begin", step, None)
                    if self._fsdp:
                        # The hot loop runs on the shard directly — no
                        # per-step gather through the params property.
                        if self._has_state:
                            (self._p_shard, self.model_state,
                             self.opt_state, loss) = self._step(
                                 self._p_shard, self.model_state,
                                 self.opt_state, batch)
                        else:
                            (self._p_shard, self.opt_state,
                             loss) = self._step(self._p_shard,
                                                self.opt_state, batch)
                    elif self._has_state:
                        (self.params, self.model_state, self.opt_state,
                         loss) = self._step(self.params, self.model_state,
                                            self.opt_state, batch)
                    else:
                        self.params, self.opt_state, loss = self._step(
                            self.params, self.opt_state, batch)
                    losses.append(loss)
                    batch_logs = None
                    if log_every and (step + 1) % log_every == 0:
                        # The only per-step fetch, at the caller-chosen
                        # cadence (≙ the deferred-fetch contract of
                        # docs/performance.md).
                        batch_logs = {"loss": float(np.asarray(loss))}
                    self._call("on_batch_end", step, batch_logs)
            finally:
                close = getattr(staged, "close", None)
                if close is not None:
                    close()
            # ONE deferred fetch for the whole epoch instead of a
            # float() sync per step.
            logs = {"loss": float(np.mean(
                [np.asarray(l) for l in jax.device_get(losses)]))}
            self._call("on_epoch_end", epoch, logs)
            self.history.append(logs)
        barrier_fence()
        self._call("on_train_end", None)
        return self.history
