"""PyTorch frontend: the reference's ``horovod.torch`` API over the TPU
runtime.

Re-creation of the reference Torch surface (horovod/torch/mpi_ops.py:58-344,
horovod/torch/__init__.py) with the MPI/cffi plumbing replaced by the
eager collective path of :mod:`..ops.collective`: torch CPU tensors bridge
through NumPy (zero-copy where torch allows it), collectives execute as
compiled XLA programs over the replica mesh, and the async handle API maps
onto the runtime's HandleManager exactly like the reference's
``horovod_torch_poll`` / ``wait_and_clear`` (torch/mpi_ops.cc:322-332).

Usage parity::

    import horovod_tpu.frontends.torch as hvd
    hvd.init()
    h = hvd.allreduce_async_(p.grad, name="g0")   # in-place, async
    hvd.synchronize(h)
    opt = hvd.DistributedOptimizer(opt, named_parameters=model.named_parameters())
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

Notes vs the reference:

* In-place variants write the result back into the caller's tensor in
  ``synchronize`` (the reference's C++ adapter resizes/fills the output
  TH tensor, torch/adapter.cc:109-120).
* float64 tensors compute in float32 on TPU (x64 is disabled) and cast
  back — dtype is preserved at the API boundary.
* ``DistributedOptimizer`` registers post-accumulate-grad hooks that fire
  ``allreduce_async_`` during backward and synchronizes them in
  ``step()`` — the reference's exact flow (torch/__init__.py:62-87).
"""

from __future__ import annotations

import sys
import weakref
from typing import Dict, Iterable, Optional

import numpy as np

import torch

from ..analysis import program as _analysis_program
from ..core import state as _state
from ..core.features import (  # noqa: F401  (feature-query shims)
    cuda_built, gloo_built, mpi_built, mpi_enabled, nccl_built, rocm_built)
from ..core.state import (init, is_initialized, local_rank, local_size,  # noqa: F401
                          mpi_threads_supported, rank, shutdown, size,
                          start_timeline, stop_timeline)
from ..ops import collective as _C
from ..ops.collective import (  # noqa: F401  (post-v0.13 API surface)
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    add_process_set,
    join,
)
from ..ops.compression import Compression  # noqa: F401  (hvd.Compression)
from ..ops.process_set import ProcessSet  # noqa: F401
from ..ops.objects import (allgather_object,  # noqa: F401  (object API)
                           broadcast_object)
from .torch_sync_bn import SyncBatchNorm  # noqa: F401  (hvd.SyncBatchNorm)

# handle -> pending-op record.  Strong references (the target may be a
# temporary view object like ``p.data`` whose storage we must mutate);
# ``poll`` consumes the result as soon as it observes completion by
# performing the write-back eagerly and releasing the underlying handle,
# so polled-and-abandoned in-place handles (the fire-and-forget pattern)
# pin neither the caller's tensor nor the in-flight jax.Array.  After the
# write-back only a weak reference to the target survives — enough for a
# later ``synchronize`` to honor the reference's identity contract
# (synchronize returns the mutated input tensor, torch/mpi_ops.py:328-344)
# without re-pinning it, and the weakref's callback evicts the record
# when the target dies so the table cannot grow without bound.


class _Pending:
    __slots__ = ("target", "dtype", "compression", "ctx", "done", "wref")

    def __init__(self, target: Optional[torch.Tensor], dtype: torch.dtype,
                 compression: Optional[object], ctx: Optional[object]):
        self.target = target        # in-place write-back target, or None
        self.dtype = dtype          # original torch dtype to restore
        self.compression = compression  # hvd.Compression.* or None
        self.ctx = ctx              # compressor context (original dtype)
        self.done = False           # poll-side write-back already happened
        self.wref = None            # weakref to the target after write-back


_inplace_targets: Dict[int, _Pending] = {}


def _to_numpy(tensor: torch.Tensor) -> np.ndarray:
    if not isinstance(tensor, torch.Tensor):
        raise ValueError(f"expected a torch.Tensor, got {type(tensor)}")
    if tensor.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.frontends.torch bridges CPU tensors; move the "
            "tensor to CPU first (TPU-resident training should use the "
            "JAX surface)")
    t = tensor.detach()
    if not t.is_contiguous():
        # Same contract as the reference (torch/mpi_ops.py:41-42) but we
        # make it contiguous instead of raising.
        t = t.contiguous()
    return t.numpy()


def _from_numpy(arr, dtype: torch.dtype) -> torch.Tensor:
    return torch.from_numpy(np.ascontiguousarray(arr)).to(dtype)


def _enqueue(kind: str, tensor: torch.Tensor, *, inplace: bool,
             name: Optional[str], compression=None, **kw) -> int:
    arr = _to_numpy(tensor)
    ctx = None
    if compression is not None:
        arr, ctx = compression.compress(arr)
    fn = getattr(_C, f"{kind}_async")
    # hvd-analyze: signature records from this funnel name the binding.
    with _analysis_program.collective_source("torch"):
        handle = fn(arr, name=name, **kw)
    _inplace_targets[handle] = _Pending(tensor if inplace else None,
                                        tensor.dtype, compression, ctx)
    return handle


def _finalize(entry: Optional[_Pending], raw) -> np.ndarray:
    """Decompress the wire result (if this handle was compressed) and
    bridge back to numpy."""
    if entry is not None and entry.compression is not None:
        raw = entry.compression.decompress(raw, entry.ctx)
    return np.asarray(raw)


def _write_back(entry: _Pending, result: np.ndarray) -> torch.Tensor:
    """Copy the finalized ``result`` into ``entry.target``, downgrade the
    strong target reference to a weak one, and return the target.

    Exception: when ours is (nearly) the only reference — the caller
    passed a temporary view like ``p.data``, whose view object dies the
    moment we let go even though its storage lives on in ``p`` — keep
    the strong reference, so a later ``synchronize`` can still return
    the result tensor.  (Cost: such a handle pins one view object until
    synchronized; the common fire-and-forget case, where the caller
    holds the tensor, still drops to a weakref.)"""
    target = entry.target
    out = _from_numpy(result, entry.dtype)
    if target.shape != out.shape:
        target.resize_(out.shape)
    target.copy_(out)
    entry.done = True
    # refs at this point: entry.target, local ``target``, getrefcount arg.
    if sys.getrefcount(target) > 3:
        entry.target = None
    return target


def poll(handle: int) -> bool:
    """Non-blocking completion check (≙ horovod_torch_poll,
    torch/mpi_ops.py:318-325).  On completion of an in-place op the
    write-back happens immediately and BOTH the target reference and the
    underlying handle (with its in-flight jax.Array) are released, so a
    polled-then-abandoned handle pins nothing.  A tiny weakref record
    survives for a later ``synchronize`` to return the original tensor
    (the reference's identity contract); its death callback evicts the
    record when the target is collected."""
    entry = _inplace_targets.get(handle)
    if entry is not None and entry.done:
        return True
    done = _C.poll(handle)
    if done and entry is not None and entry.target is not None:
        st = _state.global_state()
        h = st.handle_manager._get(handle)
        if not isinstance(h.result, _C.HorovodError):
            # Non-blocking: poll() just observed readiness.  synchronize
            # runs the handle's own finalizer and releases it from the
            # manager, un-pinning the device-side result.
            target = _write_back(entry, _finalize(entry,
                                                  _C.synchronize(handle)))
            if entry.target is None:  # downgraded (caller holds the ref)
                entry.wref = weakref.ref(
                    target,
                    lambda _r, h=handle: _inplace_targets.pop(h, None))
    return done


def synchronize(handle: int) -> torch.Tensor:
    """Block until ``handle`` completes; returns the result tensor (and
    copies it into the original for in-place ops, returning that same
    tensor object) — ≙ torch/mpi_ops.py:328-344."""
    entry = _inplace_targets.get(handle)
    if entry is not None and entry.done:
        # poll() already consumed the result and released the handle.
        _inplace_targets.pop(handle, None)
        if entry.target is not None:  # temporary-view target kept strong
            return entry.target
        target = entry.wref() if entry.wref is not None else None
        if target is None:
            raise ValueError(
                f"Handle {handle} completed via poll() and its in-place "
                "target tensor has since been garbage-collected; the "
                "result was written into that tensor and is gone with it.")
        return target
    result = _finalize(entry, _C.synchronize(handle))
    _inplace_targets.pop(handle, None)
    if entry is not None and entry.target is not None:
        return _write_back(entry, result)
    if entry is not None:
        dtype = entry.dtype
    else:
        dtype = torch.from_numpy(result).dtype
    return _from_numpy(result, dtype)


# -- allreduce --------------------------------------------------------------

def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    compression=None, op=None, process_set=None) -> int:
    return _enqueue("allreduce", tensor, inplace=False, name=name,
                    compression=compression, average=average, op=op,
                    process_set=process_set)


def allreduce_async_(tensor, average=None, name: Optional[str] = None,
                     compression=None, op=None, process_set=None) -> int:
    return _enqueue("allreduce", tensor, inplace=True, name=name,
                    compression=compression, average=average, op=op,
                    process_set=process_set)


def allreduce(tensor, average=None, name: Optional[str] = None,
              compression=None, op=None,
              process_set=None) -> torch.Tensor:
    """``compression`` (``hvd.Compression.fp16``/``bf16``) casts the
    tensor down for the wire and restores its dtype after; ``op`` takes
    hvd.Average/Sum/Adasum/Min/Max/Product, is mutually exclusive with
    ``average`` (passing both raises ValueError; with neither the call
    averages by default); ``process_set`` (from ``add_process_set``)
    restricts the collective to a rank subset — the kwarg contracts
    Horovod later standardized for this API."""
    return synchronize(allreduce_async(tensor, average, name, compression,
                                       op, process_set))


def allreduce_(tensor, average=None, name: Optional[str] = None,
               compression=None, op=None,
               process_set=None) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, average, name, compression,
                                        op, process_set))


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    """The post-v0.13 ``hvd.alltoall``: scatter this rank's dim-0 rows
    by ``splits`` and receive every rank's rows in rank order.
    Multi-process returns the caller's received rows; single-process
    returns a list of per-replica tensors."""
    arr = _to_numpy(tensor)
    with _analysis_program.collective_source("torch"):
        out = _C.alltoall(arr, splits=splits, name=name,
                          process_set=process_set)
    if isinstance(out, list):
        return [_from_numpy(np.asarray(o), tensor.dtype) for o in out]
    return _from_numpy(np.asarray(out), tensor.dtype)


barrier = _C.barrier  # post-v0.13 hvd.barrier


def reducescatter_async(tensor, average=None, name: Optional[str] = None,
                        op=None, process_set=None) -> int:
    return _enqueue("reducescatter", tensor, inplace=False, name=name,
                    average=average, op=op, process_set=process_set)


def reducescatter(tensor, average=None, name: Optional[str] = None,
                  op=None, process_set=None) -> torch.Tensor:
    """The post-v0.13 ``hvd.reducescatter``: reduce across ranks, split
    dim 0 — this rank receives its chunk (op ∈ {Average, Sum})."""
    return synchronize(reducescatter_async(tensor, average, name, op,
                                           process_set))


def _grouped_allreduce_async(tensors, *, inplace: bool, average,
                             name: Optional[str], compression,
                             op=None) -> list:
    """Shared body of the four grouped entry points: per-call-unique
    base name (overlapping anonymous groups must not collide), one
    handle per tensor, back-to-back enqueue so the fusion queue batches
    the group (≙ the post-v0.13 hvd.grouped_allreduce API)."""
    base = name or _C._auto_name("grouped.allreduce")
    return [_enqueue("allreduce", t, inplace=inplace, name=f"{base}.{i}",
                     compression=compression, average=average, op=op)
            for i, t in enumerate(tensors)]


def grouped_allreduce_async(tensors, average=None,
                            name: Optional[str] = None,
                            compression=None, op=None) -> list:
    return _grouped_allreduce_async(tensors, inplace=False,
                                    average=average, name=name,
                                    compression=compression, op=op)


def grouped_allreduce(tensors, average=None,
                      name: Optional[str] = None,
                      compression=None, op=None) -> list:
    return [synchronize(h) for h in grouped_allreduce_async(
        tensors, average, name, compression, op)]


def grouped_allreduce_async_(tensors, average=None,
                             name: Optional[str] = None,
                             compression=None, op=None) -> list:
    return _grouped_allreduce_async(tensors, inplace=True,
                                    average=average, name=name,
                                    compression=compression, op=op)


def grouped_allreduce_(tensors, average=None,
                       name: Optional[str] = None,
                       compression=None, op=None) -> list:
    return [synchronize(h) for h in grouped_allreduce_async_(
        tensors, average, name, compression, op)]


# -- allgather --------------------------------------------------------------

def allgather_async(tensor, name: Optional[str] = None) -> int:
    return _enqueue("allgather", tensor, inplace=False, name=name)


def allgather(tensor, name: Optional[str] = None) -> torch.Tensor:
    return synchronize(allgather_async(tensor, name))


# -- broadcast --------------------------------------------------------------

def broadcast_async(tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    return _enqueue("broadcast", tensor, inplace=False, name=name,
                    root_rank=root_rank)


def broadcast_async_(tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    return _enqueue("broadcast", tensor, inplace=True, name=name,
                    root_rank=root_rank)


def broadcast(tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async(tensor, root_rank, name))


def broadcast_(tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


# -- high-level glue --------------------------------------------------------

def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Sync a ``state_dict`` or iterable of ``(name, tensor)`` from
    ``root_rank`` — launch all broadcasts async, then synchronize
    (≙ torch/__init__.py:125-152)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if not torch.is_tensor(p):
            continue
        if not torch.is_floating_point(p) and p.dtype not in (
                torch.int32, torch.int64, torch.uint8, torch.int8,
                torch.int16, torch.bool):
            continue
        t = p.data if isinstance(p, torch.nn.Parameter) else p
        handles.append(broadcast_async_(t, root_rank,
                                        name=f"broadcast.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Sync an optimizer's full state from ``root_rank`` (≙ the
    post-v0.13 ``hvd.broadcast_optimizer_state``).

    Redesign note: Horovod broadcasts each state tensor individually and
    needs workarounds for lazily-created state (non-root ranks may not
    have momentum buffers yet, so it fabricates them with a dummy step).
    Here the whole ``state_dict`` rides ONE ``broadcast_object`` over
    the ragged-allgather wire — arbitrary optimizer state (tensors,
    scalars, per-group hyperparameters) with no lazy-init special case;
    the pickled payload is a few model-sizes at most and this runs once
    at startup/restore, not per step.
    """
    inner = optimizer
    if isinstance(inner, _DistributedOptimizer):
        inner = inner._inner
    sd = broadcast_object(inner.state_dict(), root_rank=root_rank,
                          name="broadcast.optimizer.state")
    inner.load_state_dict(sd)


class _DistributedOptimizer:
    """Wraps a torch optimizer: per-parameter hooks fire async allreduce
    during backward; ``step`` synchronizes then delegates
    (≙ torch/__init__.py:30-122).  A plain wrapper rather than the
    reference's dynamic subclass — the full Optimizer surface is delegated
    through ``__getattr__``."""

    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters: Optional[Iterable] = None,
                 average: bool = True, compression=None):
        self._inner = optimizer
        self._average = average
        self._compression = compression
        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [(f"allreduce.noname.{i}.{j}", p)
                     for i, group in enumerate(optimizer.param_groups)
                     for j, p in enumerate(group["params"])]
        self._param_names = {p: name for name, p in named}
        self._handles: Dict[torch.Tensor, int] = {}
        self._hook_handles = []
        self._register_hooks()

    # Delegate the Optimizer surface to the wrapped instance.
    def __getattr__(self, item):
        return getattr(self.__dict__["_inner"], item)

    @property
    def param_groups(self):
        return self._inner.param_groups

    @property
    def state(self):
        return self._inner.state

    def _register_hooks(self) -> None:
        for group in self._inner.param_groups:
            for p in group["params"]:
                if not p.requires_grad:
                    continue
                self._hook_handles.append(
                    p.register_post_accumulate_grad_hook(self._make_hook()))

    def _make_hook(self):
        def hook(p: torch.Tensor) -> None:
            if p.grad is None:
                return
            name = self._param_names.get(
                p, f"allreduce.noname.{id(p)}")
            self._handles[p] = allreduce_async_(
                p.grad, average=self._average, name=f"grad.{name}",
                compression=self._compression)

        return hook

    def synchronize(self) -> None:
        for p, handle in list(self._handles.items()):
            synchronize(handle)
        self._handles.clear()

    def step(self, closure=None):
        self.synchronize()
        return self._inner.step(closure)

    def zero_grad(self, set_to_none: bool = True):
        return self._inner.zero_grad(set_to_none=set_to_none)

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, sd):
        return self._inner.load_state_dict(sd)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters: Optional[Iterable] = None,
                         average: bool = True,
                         compression=None) -> _DistributedOptimizer:
    """Distributed wrapper for any ``torch.optim.Optimizer``
    (≙ hvd.DistributedOptimizer, torch/__init__.py:90-122).
    ``compression=hvd.Compression.fp16`` matches the kwarg GPU Horovod
    scripts pass (bf16 recommended on TPU)."""
    return _DistributedOptimizer(optimizer, named_parameters, average,
                                 compression)
