"""TensorFlow frontend: the reference's ``horovod.tensorflow`` API over
the TPU runtime, re-targeted at TF2 eager execution.

The reference surface (horovod/tensorflow/__init__.py:49-192) is TF1
graph ops: custom MPI kernels registered as TF ops, a
``SessionRunHook``, and a ``tf.train.Optimizer`` wrapper.  Under TF2 the
same capabilities map to eager tensors bridging through NumPy into the
runtime's dynamic-path collective queue (the identical path the Torch
frontend rides), plus:

* :func:`allreduce` — dense tensors AND ``tf.IndexedSlices`` (the sparse
  gather-of-(values, indices) branch, reference
  tensorflow/__init__.py:67-78).
* :class:`DistributedGradientTape` — the TF2-idiomatic replacement for
  wrapping ``compute_gradients`` (reference DistributedOptimizer,
  tensorflow/__init__.py:135-192): gradients are allreduced as they come
  out of ``tape.gradient``.
* :func:`DistributedOptimizer` — wraps a ``tf.keras`` optimizer so
  ``apply_gradients`` reduces first.
* :func:`broadcast_variables` / :func:`broadcast_global_variables` — the
  consistent-initialization broadcast (reference
  BroadcastGlobalVariablesHook, tensorflow/__init__.py:100-130; TF2 has
  no sessions, so this is a direct call).

Compiled graphs (round 4): collectives now also work INSIDE
``tf.function`` — the graph-mode analogue of the reference's
``AsyncOpKernel`` enqueue-from-graph-execution
(reference horovod/tensorflow/mpi_ops.cc:270-298).  During tracing each
collective becomes one ``tf.py_function`` node whose body re-enters the
eager queue path at graph-execution time with concrete tensors, so
``fn = tf.function(train_step); fn(batch)`` negotiates and reduces
mid-graph exactly like ``session.run(train_op)`` did in the reference.
Collective names are captured at trace time (one stable name per graph
node, like the reference's TF op names), so repeated executions reuse
the negotiation slot; the py_function boundary keeps the cross-process
queue OUT of the compiled cluster, which is what makes this sound — the
collective is a host callback, not a TF op XLA would try to compile.

Bridge cost model (round 5, documented): each py_function node is ONE
host round trip (graph executor → Python → eager queue → back), and TF
auto-chains stateful nodes, so N *separate* collective calls in one
traced step execute sequentially — N host hops, N lone negotiations, no
fusion.  The reference's in-graph AsyncOpKernels kept enqueue on the
runtime thread and fused via the coordinator; here the equivalent is
BATCHING: ``DistributedGradientTape``/``DistributedOptimizer`` bridge
the entire gradient batch through one node (one hop, one fused wire
collective — asserted by
tests/test_tf_frontend.py::test_tf_function_gradients_fuse_into_one_wire_collective),
and :func:`grouped_allreduce` exposes the same batch drain directly.

TPU note: TF does not drive the TPU here — JAX/XLA does.  This frontend
exists so TF-based data/eval pipelines and models can participate in the
same job (rank topology, collectives, validation, timeline) without a
rewrite; compiled TPU training belongs to the JAX surface.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional

import numpy as np

from ..core import state as _state
from ..core.features import (  # noqa: F401  (feature-query shims)
    cuda_built, gloo_built, mpi_built, mpi_enabled, nccl_built, rocm_built)
from ..core.state import (cross_rank, cross_size, init,  # noqa: F401
                          is_initialized, local_rank, local_size,
                          mpi_threads_supported, rank, shutdown, size,
                          start_timeline, stop_timeline)
from ..analysis import program as _analysis_program
from ..ops import collective as _C
from ..ops import sparse as _S
from ..ops.collective import (  # noqa: F401  (post-v0.13 API surface)
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    add_process_set,
    join,
)
from ..ops.compression import Compression  # noqa: F401  (hvd.Compression)
from ..ops.process_set import ProcessSet  # noqa: F401
from ..ops.objects import (allgather_object,  # noqa: F401  (object API)
                           broadcast_object)


def _tf():
    import tensorflow as tf

    return tf


def _to_numpy(t) -> np.ndarray:
    tf = _tf()
    if isinstance(t, tf.Variable):
        t = t.value()
    if hasattr(t, "numpy"):
        try:
            return t.numpy()
        except Exception as e:  # symbolic tensor outside our graph bridge
            raise RuntimeError(
                "horovod_tpu.frontends.tensorflow got a symbolic tensor "
                "on the eager path; inside tf.function the collectives "
                "bridge through tf.py_function automatically — pass the "
                "tf.Tensor itself, not a structure the bridge cannot "
                "see.") from e
    return np.asarray(t)


def _tracing() -> bool:
    """True while tf.function traces the caller (graph construction) —
    the moment to plant a ``tf.py_function`` bridge node instead of
    touching tensor values.  Inside the py_function body eager execution
    is back on, so the bridge cannot recurse."""
    tf = _tf()
    try:
        return not tf.executing_eagerly()
    except Exception:
        return False


def _graph_bridge(eager_fn, inputs, out_dtypes, op_name: str):
    """One ``tf.py_function`` node calling ``eager_fn`` with concrete
    tensors at graph-execution time (≙ the reference's AsyncOpKernel
    enqueue from inside the execution engine, mpi_ops.cc:270-298).

    The bridge body runs on a TF-managed thread, so the hvd-analyze
    source tag (analysis/program.py) is applied here, inside the body,
    not around the trace: signature records for in-graph collectives
    still name this frontend."""
    tf = _tf()

    def tagged(*args):
        with _analysis_program.collective_source("tf"):
            return eager_fn(*args)

    flat = tf.py_function(func=tagged, inp=list(inputs),
                          Tout=list(out_dtypes),
                          name=op_name.replace(".", "_"))
    return flat if isinstance(flat, (list, tuple)) else [flat]


def _wrap(out, like: np.ndarray):
    """Result array → tf tensor with the caller's dtype preserved (the
    JAX runtime has x64 disabled; cast back at the API boundary like the
    Torch frontend does, torch.py:66-67)."""
    tf = _tf()
    return tf.constant(np.asarray(out).astype(like.dtype, copy=False))


def _allreduce_in_graph(tensor, average, name: Optional[str],
                        compression, op=None, process_set=None):
    """tf.function branch of :func:`allreduce`: one py_function node per
    collective, name fixed at trace time (≙ the reference's per-TF-op
    names, mpi_ops.cc:270-298)."""
    tf = _tf()
    if isinstance(tensor, tf.IndexedSlices):
        op_name = name or _C._auto_name("allreduce.tf.fn.sparse")
        vdt, idt = tensor.values.dtype, tensor.indices.dtype

        def _eager(values, indices):
            red = _S.allreduce(
                _S.IndexedSlices(values=values.numpy(),
                                 indices=indices.numpy(), dense_shape=()),
                average=average, name=op_name)
            return (np.asarray(red.values).astype(vdt.as_numpy_dtype,
                                                  copy=False),
                    np.asarray(red.indices).astype(idt.as_numpy_dtype,
                                                   copy=False))

        vals, idxs = _graph_bridge(_eager,
                                   [tensor.values, tensor.indices],
                                   [vdt, idt], op_name)
        # The gathered row count is data-dependent (it sums every rank's
        # slice count) — only the trailing dims are static.
        vals.set_shape([None] + list(tensor.values.shape[1:]))
        idxs.set_shape([None])
        return tf.IndexedSlices(vals, idxs,
                                dense_shape=tensor.dense_shape)

    op_name = name or _C._auto_name("allreduce.tf.fn", process_set)
    dt = tensor.dtype

    def _eager(t):
        arr = t.numpy()
        if compression is None:
            out = _C.allreduce(arr, average=average, name=op_name, op=op,
                               process_set=process_set)
        else:
            wire, ctx = compression.compress(arr)
            out = compression.decompress(
                _C.allreduce(wire, average=average, name=op_name, op=op,
                             process_set=process_set), ctx)
        return np.asarray(out).astype(dt.as_numpy_dtype, copy=False)

    (out,) = _graph_bridge(_eager, [tensor], [dt], op_name)
    out.set_shape(tensor.shape)
    return out


# Eager entry points record source=tf (analysis/program.py); in-graph
# calls are tagged inside the py_function bridge instead.
_tag_source = _analysis_program.tag_source("tf")


@_tag_source
def allreduce(tensor, average=None, name: Optional[str] = None,
              compression=None, op=None, process_set=None):
    """Allreduce a ``tf.Tensor``/``tf.Variable``/``tf.IndexedSlices``.

    IndexedSlices dispatch to the sparse gather-of-(values, indices)
    exchange exactly like the reference (tensorflow/__init__.py:67-78);
    they already ship a minimal payload, so ``compression`` (the dense
    wire cast, ``hvd.Compression.fp16``/``bf16``) applies to dense
    tensors only.  ``op`` (hvd.Average/Sum/Adasum/Min/Max/Product) and
    ``average`` are mutually exclusive — passing both raises
    ValueError, and with neither the call averages by default;
    ``process_set`` carries the post-v0.13 contract; sparse inputs
    accept sum/average only.

    Inside ``tf.function`` the collective becomes a ``tf.py_function``
    bridge node executing the same eager queue path mid-graph (see the
    module docstring).
    """
    tf = _tf()
    if _tracing():
        return _allreduce_in_graph(tensor, average, name, compression,
                                   op=op, process_set=process_set)
    if isinstance(tensor, tf.IndexedSlices):
        red_op = _C._resolve_op(average, op)
        if red_op not in (_C.Average, _C.Sum):
            raise ValueError(
                "sparse (IndexedSlices) allreduce supports only "
                "sum/average.")
        # dense_shape may legally be None; the exchange never needs it
        # (it only gathers values + indices, like the reference).
        dense_shape = (None if tensor.dense_shape is None
                       else tuple(int(d) for d in tensor.dense_shape))
        values = np.asarray(_to_numpy(tensor.values))
        indices = np.asarray(_to_numpy(tensor.indices))
        red = _S.allreduce(
            _S.IndexedSlices(values=values, indices=indices,
                             dense_shape=dense_shape or ()),
            average=red_op == _C.Average, name=name,
            process_set=process_set)
        return tf.IndexedSlices(
            _wrap(red.values, values), _wrap(red.indices, indices),
            dense_shape=None if dense_shape is None
            else tf.constant(dense_shape, dtype="int64"))
    arr = _to_numpy(tensor)
    if compression is None:
        return _wrap(_C.allreduce(arr, average=average, name=name, op=op,
                                  process_set=process_set), arr)
    wire, ctx = compression.compress(arr)
    red = _C.allreduce(wire, average=average, name=name, op=op,
                       process_set=process_set)
    return _wrap(compression.decompress(red, ctx), arr)


@_tag_source
def allgather(tensor, name: Optional[str] = None):
    if _tracing():
        op_name = name or _C._auto_name("allgather.tf.fn")
        dt = tensor.dtype

        def _eager(t):
            arr = t.numpy()
            return np.asarray(_C.allgather(arr, name=op_name)).astype(
                dt.as_numpy_dtype, copy=False)

        (out,) = _graph_bridge(_eager, [tensor], [dt], op_name)
        # Ragged gather: dim 0 sums every rank's (possibly different)
        # extent — static only in the trailing dims.
        out.set_shape([None] + list(tensor.shape[1:]))
        return out
    arr = _to_numpy(tensor)
    return _wrap(_C.allgather(arr, name=name), arr)


@_tag_source
def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    if _tracing():
        op_name = name or _C._auto_name("broadcast.tf.fn")
        dt = tensor.dtype

        def _eager(t):
            arr = t.numpy()
            return np.asarray(
                _C.broadcast(arr, root_rank, name=op_name)).astype(
                    dt.as_numpy_dtype, copy=False)

        (out,) = _graph_bridge(_eager, [tensor], [dt], op_name)
        out.set_shape(tensor.shape)
        return out
    arr = _to_numpy(tensor)
    return _wrap(_C.broadcast(arr, root_rank, name=name), arr)


@_tag_source
def broadcast_variables(variables: Iterable, root_rank: int = 0) -> None:
    """Assign every variable the root's value — launch all broadcasts
    async, then synchronize (the Torch frontend's pattern, matching the
    reference's grouped bcast op, tensorflow/__init__.py:100-107)."""
    variables = list(variables)
    handles = [
        _C.broadcast_async(_to_numpy(v), root_rank,
                           name=f"broadcast.tf.{i}.{v.name}")
        for i, v in enumerate(variables)
    ]
    for v, h in zip(variables, handles):
        v.assign(np.asarray(_C.synchronize(h)))


def broadcast_global_variables(model_or_variables, root_rank: int = 0):
    """TF2 spelling of the reference's broadcast_global_variables: there
    is no global-variables collection, so pass a model (``.variables``)
    or an iterable of variables."""
    variables = getattr(model_or_variables, "variables", model_or_variables)
    broadcast_variables(variables, root_rank)


class DistributedGradientTape:
    """Wrap a ``tf.GradientTape`` so ``gradient()`` returns allreduced
    gradients — the TF2 idiom for the reference's DistributedOptimizer
    ``compute_gradients`` override (tensorflow/__init__.py:158-177)."""

    def __init__(self, tape, average: bool = True, compression=None):
        self._tape = tape
        self._average = average
        self._compression = compression

    def __getattr__(self, item):
        return getattr(self.__dict__["_tape"], item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, *args, **kwargs):
        tf = _tf()
        grads = self._tape.gradient(target, sources, *args, **kwargs)
        flat = tf.nest.flatten(grads)
        red = _allreduce_batch(flat, self._average, prefix="tape.grad",
                               compression=self._compression)
        return tf.nest.pack_sequence_as(grads, red)


def _allreduce_batch(tensors, average, prefix: str,
                     compression=None, op=None) -> List[Any]:
    """Fire every allreduce async, then synchronize — so the runtime's
    tensor fusion batches the small gradients into one collective
    (ops/collective.py fused buckets) instead of N round trips.
    ``compression`` casts the wire payload down; ``_wrap`` restores each
    gradient's original dtype on the way out.

    Inside ``tf.function`` the WHOLE batch becomes one py_function node
    whose body re-runs this function eagerly — preserving the
    async+fusion behavior mid-graph (the reference's graph path equally
    fused through its per-op kernels + fusion buffer)."""
    if _tracing():
        tf = _tf()
        idx = [i for i, t in enumerate(tensors) if t is not None]
        base = _C._auto_name(f"{prefix}.fn")

        def _eager(*concrete):
            return _allreduce_batch(list(concrete), average, base,
                                    compression, op=op)

        outs = _graph_bridge(_eager, [tensors[i] for i in idx],
                             [tensors[i].dtype for i in idx], base)
        result: List[Any] = [None] * len(tensors)
        for o, i in zip(outs, idx):
            o.set_shape(tensors[i].shape)
            result[i] = o
        return result
    comp = compression
    arrs = [None if t is None else _to_numpy(t) for t in tensors]
    handles, ctxs = [], []
    for i, a in enumerate(arrs):
        if a is None:
            handles.append(None)
            ctxs.append(None)
            continue
        wire, ctx = (a, None) if comp is None else comp.compress(a)
        handles.append(_C.allreduce_async(wire, average=average,
                                          name=f"{prefix}.{i}", op=op))
        ctxs.append(ctx)
    return [
        None if h is None else _wrap(
            _C.synchronize(h) if comp is None
            else comp.decompress(_C.synchronize(h), ctxs[i]), arrs[i])
        for i, h in enumerate(handles)
    ]


def grouped_allreduce(tensors, average=None,
                      name: Optional[str] = None, compression=None,
                      op=None):
    """Allreduce a list of tensors as ONE fused group (≙ the post-v0.13
    ``hvd.grouped_allreduce``, sync variant — the async handle surface
    stays on the torch frontend, matching the reference's split).

    ``op`` takes hvd.Average/Sum/Adasum/Min/Max/Product; ``op`` and
    ``average`` are mutually exclusive (passing both raises
    ValueError), and with neither the group averages by default.
    Eager: every op is submitted
    async before any synchronize, so Tensor Fusion packs the group into
    ~one wire collective.  Inside ``tf.function`` the whole group
    becomes ONE ``tf.py_function`` node — the batch drain that keeps
    fusion alive in graph mode, and the API to reach for instead of N
    separate :func:`allreduce` calls (which trace to N stateful nodes
    TF executes sequentially, each paying its own host hop and
    negotiating alone)."""
    base = name or _C._auto_name("grouped.allreduce.tf")
    return _allreduce_batch(list(tensors), average, base, compression,
                            op=op)


def DistributedOptimizer(optimizer, name: Optional[str] = None,
                         average: bool = True, compression=None):
    """Wrap a ``tf.keras`` optimizer so ``apply_gradients`` allreduces
    the gradients first (≙ reference DistributedOptimizer,
    tensorflow/__init__.py:135-192, minus the TF1 graph machinery).
    Same dynamic-subclass trick: the returned instance keeps the wrapped
    class's name."""
    base = optimizer.__class__
    overrides = {"_hvd_average": average,
                 "_hvd_compression": compression,
                 "_hvd_name": name or f"Distributed{base.__name__}"}

    if hasattr(base, "apply"):
        # Keras-3-style optimizer (tf.keras in TF >= 2.16): every path —
        # apply_gradients, eager apply, stateless_apply — funnels through
        # apply(), so that is the one hook (same reasoning as
        # frontends/keras.py).
        def _apply(self, grads, trainable_variables=None):
            red = _allreduce_batch(list(grads), self._hvd_average,
                                   prefix="grad",
                                   compression=self._hvd_compression)
            return super(cls, self).apply(red, trainable_variables)

        overrides["apply"] = _apply
    else:
        # Legacy optimizer: apply_gradients is the entry point.
        def _apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv = list(grads_and_vars)
            red = _allreduce_batch([g for g, _ in gv], self._hvd_average,
                                   prefix="grad",
                                   compression=self._hvd_compression)
            return super(cls, self).apply_gradients(
                [(r, v) for r, (_, v) in zip(red, gv)], *args, **kwargs)

        overrides["apply_gradients"] = _apply_gradients

    cls = type(base.__name__, (base,), overrides)
    return cls.from_config(optimizer.get_config()) \
        if hasattr(cls, "from_config") else cls(**optimizer.get_config())
