"""SyncBatchNorm for the torch frontend (≙ the post-v0.13
``hvd.SyncBatchNorm``): BatchNorm whose statistics span every rank's
batch shard, not just the local one.

Redesign vs the reference lineage: Horovod's implementation leans on
``torch.batch_norm_gather_stats_with_counts`` (a CUDA kernel family);
here both passes compute the global moments with plain allreduces over
the eager wire — a grouped allreduce of (sum, sum-of-squares, count) in
forward, and of the two gradient sums in backward — so the module works
on CPU tensors and rides the same negotiation/validation/timeline path
as every other collective.

The math: with global mean/var over n = Σ n_r rows,
``dx = (w/σ) (g − mean_n(g) − x̂ · mean_n(g·x̂))`` where both means are
GLOBAL (they normalize the population the statistics came from);
``dw = Σ_local(g·x̂)`` and ``db = Σ_local(g)`` stay local — the
DistributedOptimizer averages parameter gradients afterwards, exactly
like every other layer.
"""

from __future__ import annotations

import torch
import torch.nn.functional as F

from ..core import state as _state
from ..ops import collective as _C


def _global_sums(tensors, name: str):
    """Grouped allreduce (sum) of same-shape-per-rank vectors; returns
    torch tensors.  One wire collective via Tensor Fusion."""
    outs = _C.grouped_allreduce(
        [t.detach().numpy() for t in tensors], average=False, name=name)
    import numpy as np

    return [torch.from_numpy(np.ascontiguousarray(np.asarray(o)))
            for o in outs]


class _SyncBatchNormFn(torch.autograd.Function):
    @staticmethod
    def forward(ctx, x, weight, bias, running_mean, running_var,
                eps, momentum, name):
        dims = [0] + list(range(2, x.dim()))
        n_local = float(x.numel() // x.shape[1])
        # Compute the moments with a float32 floor: fp16 sum-of-squares
        # overflows past ~65504, the fp16 count loses integer precision
        # above 2048, and even the fp16 *product* x·x carries a rounding
        # bias that skews the variance (upstream's gather_stats kernels
        # accumulate in float for the same reason).  float64 inputs keep
        # f64 through the LOCAL accumulation; the allreduce wire itself
        # reduces in float32 unless jax x64 mode is enabled.
        acc = torch.float64 if x.dtype == torch.float64 else torch.float32
        xf = x.to(acc)
        local_sum = xf.sum(dim=dims)
        local_sumsq = (xf * xf).sum(dim=dims)
        count = torch.tensor([n_local], dtype=acc)
        g_sum, g_sumsq, g_count = _global_sums(
            [local_sum, local_sumsq, count], name=f"{name}.fwd")
        n = float(g_count[0])
        mean = g_sum / n
        var = g_sumsq / n - mean * mean
        var = torch.clamp(var, min=0.0)
        std = torch.sqrt(var + eps)
        shape = [1, -1] + [1] * (x.dim() - 2)
        xhat = ((x - mean.to(x.dtype).reshape(shape))
                / std.to(x.dtype).reshape(shape))
        out = xhat * weight.reshape(shape) + bias.reshape(shape)
        if running_mean is not None:
            with torch.no_grad():
                unbiased = var * (n / max(n - 1.0, 1.0))
                running_mean.mul_(1 - momentum).add_(
                    momentum * mean.to(running_mean.dtype))
                running_var.mul_(1 - momentum).add_(
                    momentum * unbiased.to(running_var.dtype))
        ctx.save_for_backward(xhat, weight, std)
        ctx.n_global = n
        ctx.name = name
        return out

    @staticmethod
    def backward(ctx, grad_out):
        xhat, weight, std = ctx.saved_tensors
        dims = [0] + list(range(2, grad_out.dim()))
        shape = [1, -1] + [1] * (grad_out.dim() - 2)
        acc = (torch.float64 if grad_out.dtype == torch.float64
               else torch.float32)
        gf = grad_out.to(acc)
        local_g = gf.sum(dim=dims)
        local_gx = (gf * xhat.to(acc)).sum(dim=dims)
        g_g, g_gx = _global_sums([local_g, local_gx],
                                 name=f"{ctx.name}.bwd")
        n = ctx.n_global
        dx = ((weight.to(acc).reshape(shape) / std.to(acc).reshape(shape)) * (
            gf - (g_g / n).reshape(shape)
            - xhat.to(acc) * (g_gx / n).reshape(shape))
        ).to(grad_out.dtype)
        # Parameter grads stay LOCAL sums: DistributedOptimizer averages
        # them with every other parameter gradient.
        return (dx, local_gx.to(weight.dtype), local_g.to(weight.dtype),
                None, None, None, None, None)


class SyncBatchNorm(torch.nn.modules.batchnorm._BatchNorm):
    """Drop-in BatchNorm1d/2d/3d whose training-time statistics span all
    ranks (≙ ``hvd.SyncBatchNorm``).  Eval mode (and single-contributor
    jobs) falls back to the stock batch_norm on running statistics."""

    _instances = 0

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        SyncBatchNorm._instances += 1
        self._hvd_name = f"sync_bn.{SyncBatchNorm._instances}"

    def _check_input_dim(self, input) -> None:
        if input.dim() < 2:
            raise ValueError(
                f"expected at least 2D input (got {input.dim()}D)")

    def forward(self, x):
        self._check_input_dim(x)
        _state._check_initialized()
        if self.training and self.num_batches_tracked is not None:
            with torch.no_grad():
                self.num_batches_tracked += 1
        # momentum=None means a cumulative moving average (stock
        # _BatchNorm semantics: factor = 1/num_batches_tracked).
        if self.momentum is not None:
            factor = self.momentum
        elif self.training and self.num_batches_tracked is not None:
            factor = 1.0 / float(self.num_batches_tracked)
        else:
            factor = 0.0
        if not self.training or _state.contributor_count() == 1:
            return F.batch_norm(
                x, self.running_mean, self.running_var, self.weight,
                self.bias, self.training, factor, self.eps)
        return _SyncBatchNormFn.apply(
            x, self.weight, self.bias, self.running_mean,
            self.running_var, self.eps, factor, self._hvd_name)
