"""horovod_tpu.frontends"""
