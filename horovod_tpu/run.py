"""Process launcher: ``python -m horovod_tpu.run -np N script.py [args...]``.

TPU-native stand-in for the reference's ``mpirun -np N python train.py``
launch recipe (reference: README.md:148-177 and the Travis CI legs,
.travis.yml:96-123).  Spawns N local worker processes wired together via
``jax.distributed`` (the ``HVD_TPU_*`` env contract in core/cluster.py);
each worker's stdout/stderr is prefixed with its rank, mpirun-style.

For multi-node jobs, run one ``python script.py`` per node under your
scheduler with HVD_TPU_COORDINATOR / HVD_TPU_NUM_PROCESSES /
HVD_TPU_PROCESS_ID exported — the same contract this launcher uses.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def _free_ports(n: int) -> list:
    # Hold all sockets open while allocating so the kernel can't hand the
    # same ephemeral port out twice.
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _pump(stream, rank: int, out) -> None:
    for line in iter(stream.readline, b""):
        out.buffer.write(f"[{rank}] ".encode() + line)
        out.flush()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.run",
        description="Launch N cooperating horovod_tpu processes locally.")
    ap.add_argument("-np", "--num-proc", type=int, required=True)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform for workers (e.g. cpu)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="script (and args) to run in each process")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("missing script to launch")

    # Reserve a distinct port for the eager-op controller up front; the
    # rendezvous-port+1 default could land on an in-use port.
    coord_port, controller_port = _free_ports(2)
    procs = []
    pumps = []
    for rank in range(args.num_proc):
        env = dict(os.environ)
        env["HVD_TPU_COORDINATOR"] = f"127.0.0.1:{coord_port}"
        env["HVD_TPU_CONTROLLER_PORT"] = str(controller_port)
        env["HVD_TPU_NUM_PROCESSES"] = str(args.num_proc)
        env["HVD_TPU_PROCESS_ID"] = str(rank)
        if args.platform:
            env["JAX_PLATFORMS"] = args.platform
            if args.platform == "cpu":
                env.pop("PALLAS_AXON_POOL_IPS", None)
        # -u: a worker that dies abruptly (or is torn down by the JAX
        # coordination service) must not lose block-buffered output —
        # mpirun's stdout forwarding has the same property.
        p = subprocess.Popen([sys.executable, "-u"] + args.command, env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        procs.append(p)
        t = threading.Thread(target=_pump, args=(p.stdout, rank, sys.stdout),
                             daemon=True)
        t.start()
        pumps.append(t)

    rc = 0
    try:
        for p in procs:
            rc = p.wait() or rc
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        rc = 130
    for t in pumps:
        t.join(timeout=2.0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
