"""Process launcher: ``python -m horovod_tpu.run -np N script.py [args...]``.

TPU-native stand-in for the reference's ``mpirun -np N python train.py``
launch recipe (reference: README.md:148-177 and the Travis CI legs,
.travis.yml:96-123).  Spawns N local worker processes wired together via
``jax.distributed`` (the ``HVD_TPU_*`` env contract in core/cluster.py);
each worker's stdout/stderr is prefixed with its rank, mpirun-style.

For multi-node jobs, run one ``python script.py`` per node under your
scheduler with HVD_TPU_COORDINATOR / HVD_TPU_NUM_PROCESSES /
HVD_TPU_PROCESS_ID exported — the same contract this launcher uses.

``--elastic`` adds fault tolerance (≙ the post-v0.13 ``horovodrun``
elastic mode): the launcher supervises the workers and, when the job
fails — a worker crash, or a survivor exiting EX_TEMPFAIL(75) after
diagnosing a dead peer — tears the job down and relaunches it, up to
``--max-restarts`` times.  ``HVD_TPU_ELASTIC_DIR`` (exported to the
workers) carries the committed ``horovod_tpu.elastic.State`` across
incarnations, so training resumes from the last ``state.commit()``
rather than from scratch.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time


def _free_ports(n: int) -> list:
    # Hold all sockets open while allocating so the kernel can't hand the
    # same ephemeral port out twice.
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _pump(stream, rank: int, out) -> None:
    for line in iter(stream.readline, b""):
        out.buffer.write(f"[{rank}] ".encode() + line)
        out.flush()


def _launch_once(args, extra_env=None) -> int:
    """One job incarnation: spawn N workers, forward output, wait.

    Returns the first nonzero worker exit code (0 when all succeed).
    A failed worker's surviving peers diagnose the death themselves and
    exit (ops/transport.py failure detection); ``--grace`` bounds how
    long the launcher waits for that before terminating stragglers.
    """
    # Reserve a distinct port for the eager-op controller up front; the
    # rendezvous-port+1 default could land on an in-use port.
    coord_port, controller_port = _free_ports(2)
    procs = []
    pumps = []
    for rank in range(args.num_proc):
        env = dict(os.environ)
        env.update(extra_env or {})
        env["HVD_TPU_COORDINATOR"] = f"127.0.0.1:{coord_port}"
        env["HVD_TPU_CONTROLLER_PORT"] = str(controller_port)
        env["HVD_TPU_NUM_PROCESSES"] = str(args.num_proc)
        env["HVD_TPU_PROCESS_ID"] = str(rank)
        if args.platform:
            env["JAX_PLATFORMS"] = args.platform
            if args.platform == "cpu":
                env.pop("PALLAS_AXON_POOL_IPS", None)
        # -u: a worker that dies abruptly (or is torn down by the JAX
        # coordination service) must not lose block-buffered output —
        # mpirun's stdout forwarding has the same property.
        p = subprocess.Popen([sys.executable, "-u"] + args.command, env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        procs.append(p)
        t = threading.Thread(target=_pump, args=(p.stdout, rank, sys.stdout),
                             daemon=True)
        t.start()
        pumps.append(t)

    rc = 0
    try:
        deadline = None
        # Poll EVERY worker each tick: any(...) would short-circuit at
        # the first live process and never set returncode on the ranks
        # behind it, so a crash behind a blocked rank 0 would go
        # undetected and the grace window would never arm.
        while None in [p.poll() for p in procs]:
            if rc == 0:
                rc = next((p.returncode for p in procs
                           if p.returncode not in (None, 0)), 0)
                if rc and args.grace > 0:
                    deadline = time.monotonic() + args.grace
            if deadline is not None and time.monotonic() > deadline:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        p.kill()
                        p.wait()
                break
            time.sleep(0.2)
        for p in procs:
            if p.returncode is None:
                p.wait()
            rc = rc or (p.returncode or 0)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        rc = 130
    for t in pumps:
        t.join(timeout=2.0)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.run",
        description="Launch N cooperating horovod_tpu processes locally.")
    ap.add_argument("-np", "--num-proc", type=int, required=True)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform for workers (e.g. cpu)")
    ap.add_argument("--elastic", action="store_true",
                    help="relaunch the job on worker failure, resuming "
                         "from the last horovod_tpu.elastic.State commit")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="elastic mode: maximum relaunches before giving "
                         "up (default 3)")
    ap.add_argument("--elastic-dir", default=None,
                    help="directory carrying committed elastic state "
                         "across incarnations (default: a fresh temp dir)")
    ap.add_argument("--grace", type=float, default=60.0,
                    help="seconds to let surviving workers diagnose a "
                         "peer failure and exit before the launcher "
                         "terminates them (0 disables)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="script (and args) to run in each process")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("missing script to launch")

    if not args.elastic:
        return _launch_once(args)

    elastic_dir = args.elastic_dir or tempfile.mkdtemp(
        prefix="hvd_tpu_elastic_")
    extra = {"HVD_TPU_ELASTIC": "1", "HVD_TPU_ELASTIC_DIR": elastic_dir}
    for attempt in range(args.max_restarts + 1):
        rc = _launch_once(args, extra)
        if rc == 0:
            return 0
        if rc == 130:  # Ctrl+C is the user stopping the job, not a failure
            return rc
        if attempt == args.max_restarts:
            print(f"[elastic] giving up after {attempt} restart(s): "
                  f"rc={rc}", file=sys.stderr)
            return rc
        print(f"[elastic] job failed (rc={rc}); relaunching from the "
              f"last commit in {elastic_dir} "
              f"(restart {attempt + 1}/{args.max_restarts})",
              file=sys.stderr, flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
