"""Step critical-path / straggler analyzer (hvd-trace piece 3).

``python -m horovod_tpu.trace <fleet-trace.json>`` answers "where did
the cycle go": for every negotiation cycle it names the straggler rank
(from the controller's per-rank request-arrival instants — the same
signal StragglerWatch uses live) with a blame category, decomposes
each rank's spans into the classic legs —

  host          input/prefetch stalls (the loader was the bound)
  pack          dispatch time before the fused launch (fusion-buffer
                memcpy-in)
  collective    the compiled reduction's ICI share
  dcn           its cross-slice DCN share (hierarchical launches,
                split by the wire-byte accounting the launch records)
  unpack        dispatch time after the launch (memcpy-out + divide)
  dispatch      execute spans with no launch inside (eager path)
  dispatch-gap  wall time inside the straggler's cycle covered by no
                span at all
  negotiate     coordinator wait (and the default blame for a rank
                that was simply late with no local span explaining it)

— and aggregates the straggler-chain legs per step: the **critical
path** attribution.  Blame for a straggler is the category where its
busy time most EXCEEDS the fleet median for the step, so "rank 5 was
host-bound" emerges even when every rank also paid the same collective
cost.  Output is a human report plus JSON (``--json``; ``bench.py``'s
``trace`` section and the CI determinism gate consume it) — both are
pure functions of the input file, so two replays of one trace are
byte-identical.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

LEGS = ("host", "pack", "collective", "dcn", "unpack", "dispatch",
        "dispatch-gap", "negotiate", "checkpoint", "serving")

# Span category -> leg for the directly-mapped categories.
_DIRECT = {"host": "host", "negotiate": "negotiate",
           "checkpoint": "checkpoint", "serving": "serving"}


def load_trace(path: str) -> List[dict]:
    """Events from a fleet trace (``{"traceEvents": [...]}``) or a bare
    Chrome timeline array."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        return list(data.get("traceEvents", []))
    if isinstance(data, list):
        return data
    raise ValueError(f"{path}: not a Chrome trace (object or array)")


def _key(ev: dict) -> Optional[Tuple[int, int]]:
    args = ev.get("args") or {}
    if "step" not in args or "cycle" not in args:
        return None
    try:
        return int(args["step"]), int(args["cycle"])
    except (TypeError, ValueError):
        return None


def _collective_legs(legs: Dict[str, float], ev: dict) -> None:
    """Split one launch span into ICI vs DCN by the wire-byte
    accounting it carries (ops/megakernel.launch)."""
    dur = float(ev.get("dur", 0.0))
    args = ev.get("args") or {}
    wire = args.get("wire_bytes") or 0
    dcn = args.get("dcn_bytes") or 0
    if wire and dcn:
        frac = min(1.0, float(dcn) / float(wire))
        legs["dcn"] += dur * frac
        legs["collective"] += dur * (1.0 - frac)
    else:
        legs["collective"] += dur


def _decompose(spans: List[dict]) -> Dict[str, float]:
    """One rank's spans (any grouping window) -> busy µs per leg."""
    legs: Dict[str, float] = {}
    for leg in LEGS:
        legs[leg] = 0.0
    coll = [s for s in spans if s.get("cat") == "collective"]
    used = set()
    for d in (s for s in spans if s.get("cat") == "dispatch"):
        d0 = float(d.get("ts", 0.0))
        d1 = d0 + float(d.get("dur", 0.0))
        inner = [c for c in coll
                 if d0 - 1.0 <= float(c.get("ts", 0.0))
                 and float(c.get("ts", 0.0)) + float(c.get("dur", 0.0))
                 <= d1 + 1.0]
        if inner:
            first = min(float(c["ts"]) for c in inner)
            last = max(float(c["ts"]) + float(c.get("dur", 0.0))
                       for c in inner)
            legs["pack"] += max(0.0, first - d0)
            legs["unpack"] += max(0.0, d1 - last)
            for c in inner:
                used.add(id(c))
                _collective_legs(legs, c)
        else:
            legs["dispatch"] += float(d.get("dur", 0.0))
    for c in coll:
        if id(c) not in used:
            _collective_legs(legs, c)
    for s in spans:
        leg = _DIRECT.get(str(s.get("cat")))
        if leg is not None:
            legs[leg] += float(s.get("dur", 0.0))
    return legs


def _median(vals: List[float]) -> float:
    vals = sorted(vals)
    return vals[len(vals) // 2] if vals else 0.0


def window_legs(events: List[dict]) -> Dict[str, float]:
    """hvd-tune sensor surface: a raw in-memory span buffer
    (``trace.export_events()``) -> busy µs per critical-path leg,
    including the per-(step, cycle) wall-minus-busy residual booked as
    ``dispatch-gap``.  Same leg model as :func:`analyze`, but windowed
    and file-free — the online tuner calls this every decision window
    instead of round-tripping ``dump_fleet_trace``."""
    spans = [e for e in events if e.get("ph") == "X"]
    legs = _decompose(spans)
    groups: Dict[Tuple[int, int], List[dict]] = {}
    for s in spans:
        key = _key(s)
        if key is not None:
            groups.setdefault(key, []).append(s)
    for ss in groups.values():
        wall = (max(float(s["ts"]) + float(s.get("dur", 0.0)) for s in ss)
                - min(float(s["ts"]) for s in ss))
        busy = sum(_decompose(ss).values())
        legs["dispatch-gap"] += max(0.0, wall - busy)
    return legs


def analyze(events: List[dict]) -> dict:
    """The full report over one merged trace (see module docstring for
    the model).  Deterministic: every aggregate is ordered and floats
    are rounded once at the edge."""
    spans: Dict[Tuple[int, int], Dict[int, List[dict]]] = {}
    by_step_rank: Dict[Tuple[int, int], List[dict]] = {}
    arrivals: Dict[Tuple[int, int], Dict[int, float]] = {}
    nspans = 0
    for ev in events:
        key = _key(ev)
        if key is None:
            continue
        if ev.get("ph") == "i" and ev.get("name") == "BATCH_ARRIVAL":
            rank = int((ev.get("args") or {}).get("rank", -1))
            arrivals.setdefault(key, {}).setdefault(
                rank, float(ev.get("ts", 0.0)))
            continue
        if ev.get("ph") != "X":
            continue
        nspans += 1
        rank = int(ev.get("pid", 0))
        spans.setdefault(key, {}).setdefault(rank, []).append(ev)
        by_step_rank.setdefault((key[0], rank), []).append(ev)
    ranks = sorted({r for per in spans.values() for r in per}
                   | {r for per in arrivals.values() for r in per
                      if r >= 0})
    step_rank_legs = {k: _decompose(v) for k, v in by_step_rank.items()}

    cycles_out: List[dict] = []
    straggler_counts: Dict[int, int] = {}
    step_crit: Dict[int, Dict[str, float]] = {}
    step_cycles: Dict[int, int] = {}
    step_stragglers: Dict[int, Dict[int, int]] = {}
    for key in sorted(set(spans) | set(arrivals)):
        step, cycle = key
        step_cycles[step] = step_cycles.get(step, 0) + 1
        per_rank = spans.get(key, {})
        arr = {r: t for r, t in arrivals.get(key, {}).items() if r >= 0}
        straggler: Optional[int] = None
        skew_us = 0.0
        if len(arr) >= 1:
            # Arrival-based: rank 0 submits locally (implicit t=first),
            # so ANY wire arrival spread names the late worker; with
            # several, the latest wins (ties -> lowest rank).
            latest = max(arr.values())
            skew_us = latest - min(arr.values())
            straggler = min(r for r, t in arr.items() if t == latest)
        elif per_rank:
            ends = {r: max(float(s["ts"]) + float(s.get("dur", 0.0))
                           for s in ss) for r, ss in per_rank.items()}
            latest = max(ends.values())
            skew_us = latest - min(ends.values())
            straggler = min(r for r, e in ends.items() if e == latest)
        if straggler is None:
            continue
        # Blame: the leg where the straggler's step-window busy most
        # exceeds the fleet median (a cost every rank pays equally —
        # the collective itself — can never be the blame).
        mine = step_rank_legs.get((step, straggler))
        blame = "negotiate"
        if mine is not None:
            best_excess = 0.0
            for leg in LEGS:
                others = [step_rank_legs[(step, r)][leg]
                          for r in ranks if r != straggler
                          and (step, r) in step_rank_legs]
                excess = mine[leg] - _median(others)
                if excess > best_excess:
                    best_excess, blame = excess, leg
        crit = step_crit.setdefault(step, {leg: 0.0 for leg in LEGS})
        cyc_legs = _decompose(per_rank.get(straggler, []))
        busy = 0.0
        for leg in LEGS:
            crit[leg] += cyc_legs[leg]
            busy += cyc_legs[leg]
        if per_rank.get(straggler):
            ss = per_rank[straggler]
            wall = (max(float(s["ts"]) + float(s.get("dur", 0.0))
                        for s in ss)
                    - min(float(s["ts"]) for s in ss))
            crit["dispatch-gap"] += max(0.0, wall - busy)
        else:
            # No local span explains the lateness: the skew itself is
            # the critical-path cost, booked under the blame leg.
            crit[blame] += skew_us
        straggler_counts[straggler] = \
            straggler_counts.get(straggler, 0) + 1
        per_step = step_stragglers.setdefault(step, {})
        per_step[straggler] = per_step.get(straggler, 0) + 1
        cycles_out.append({"step": step, "cycle": cycle,
                           "straggler": straggler, "blame": blame,
                           "skew_us": round(skew_us, 1)})

    steps_out = []
    total = {leg: 0.0 for leg in LEGS}
    for step in sorted(step_crit):
        crit = step_crit[step]
        for leg in LEGS:
            total[leg] += crit[leg]
        steps_out.append({
            "step": step,
            "cycles": step_cycles.get(step, 0),
            "critical_path_us": {leg: round(crit[leg], 1)
                                 for leg in LEGS},
            "straggler_counts": {str(r): n for r, n in
                                 sorted(step_stragglers
                                        .get(step, {}).items())},
        })
    return {
        "format": "hvd-trace-analysis-v1",
        "ranks": ranks,
        "total_spans": nspans,
        "steps": steps_out,
        "cycles": cycles_out,
        "stragglers": {str(r): n
                       for r, n in sorted(straggler_counts.items())},
        "attribution_us": {leg: round(total[leg], 1) for leg in LEGS},
    }


def render(report: dict) -> str:
    """The human report."""
    lines = ["hvd-trace analysis",
             "==================",
             f"ranks: {report['ranks'] or '[none]'}   spans: "
             f"{report['total_spans']}   cycles: "
             f"{len(report['cycles'])}", ""]
    attr = report["attribution_us"]
    total = sum(attr.values()) or 1.0
    lines.append("critical-path attribution (straggler chain):")
    for leg in LEGS:
        us = attr.get(leg, 0.0)
        if us <= 0:
            continue
        lines.append(f"  {leg:<13} {us / 1e3:10.3f} ms  "
                     f"({100.0 * us / total:5.1f}%)")
    if not any(attr.get(leg, 0) > 0 for leg in LEGS):
        lines.append("  [no attributable spans — was HVD_TPU_TRACE=0, "
                     "or is this a bare rank-0 timeline?]")
    lines.append("")
    if report["stragglers"]:
        lines.append("stragglers (cycles led by each rank):")
        worst = max(report["stragglers"].items(),
                    key=lambda kv: (kv[1], -int(kv[0])))
        for rank, n in report["stragglers"].items():
            lines.append(f"  rank {rank:>3}: {n} cycle(s)")
        blames = [c["blame"] for c in report["cycles"]
                  if str(c["straggler"]) == worst[0]]
        if blames:
            top = max(sorted(set(blames)), key=blames.count)
            lines.append(f"  => rank {worst[0]} led {worst[1]} "
                         f"cycle(s); dominant blame: {top}")
        lines.append("")
    for s in report["steps"]:
        crit = s["critical_path_us"]
        busy = {k: v for k, v in crit.items() if v > 0}
        head = max(sorted(busy), key=lambda k: busy[k]) if busy else "-"
        lines.append(f"step {s['step']:>4}: {s['cycles']} cycle(s), "
                     f"dominant leg: {head}, stragglers: "
                     f"{s['straggler_counts'] or '{}'}")
    return "\n".join(lines) + "\n"
