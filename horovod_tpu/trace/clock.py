"""Clock alignment over the TCP control plane (hvd-trace piece 2).

Every rank times its spans on its own ``time.monotonic()`` — two
processes' monotonic clocks share no epoch, so merging timelines needs
the per-peer offset.  The estimator is the classic NTP exchange over
the existing control connection:

* rank 0 broadcasts FRAME_PING carrying its send stamp ``t0``;
* each worker answers FRAME_PONG immediately with ``t0`` echoed and its
  own receive stamp ``t1``;
* rank 0 stamps the pong's arrival ``t2`` and derives::

      rtt    = t2 - t0
      offset = t1 - (t0 + t2) / 2     # worker clock minus rank-0 clock

The symmetric-path assumption errs by at most ``rtt / 2``, so the
estimator keeps a bounded window of samples and reports the offset of
the **minimum-RTT** sample — a queueing delay (or an hvd-chaos
``transport.delay``/``transport.stall`` injection) inflates RTT and is
filtered out rather than averaged in.  A chaos ``transport.dup``
merely lands one extra sample.  On a session resume
(ops/transport.py reconnect protocol) the peer's window is RESET: the
old socket's samples measured a path that no longer exists, and stale
pings replayed out of the resume ring produce huge-RTT pongs the
filter discards anyway.

Per-peer offsets are exported as ``trace.clock_offset_seconds.rank<N>``
gauges (docs/metrics.md) and consumed by the fleet-trace merge
(trace/merge.py).
"""

from __future__ import annotations

import collections
from typing import Dict, Optional

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..analysis import races as _races

# Samples retained per peer.  Small: the minimum over ~32 probes is
# already within a few microseconds on a healthy fabric, and a bounded
# window lets a real clock drift (or a migrated peer) age out.
WINDOW = 64


class OffsetEstimator:
    """Min-RTT-filtered offset estimate for ONE peer clock."""

    def __init__(self, window: int = WINDOW) -> None:
        self._samples: collections.deque = collections.deque(
            maxlen=window)
        self.count = 0  # samples ever accepted (re-convergence probe)

    def add(self, t0: float, t1: float, t2: float) -> Optional[float]:
        """Fold one ping/pong exchange in; returns the new best offset
        (None when the sample is unusable — a reordered/replayed pong
        whose stamps are not causally ordered)."""
        rtt = t2 - t0
        if rtt < 0:
            return None
        self._samples.append((rtt, t1 - (t0 + t2) / 2.0))
        self.count += 1
        return self.offset()

    def offset(self) -> Optional[float]:
        """Peer clock minus local clock, from the min-RTT sample in the
        window; None before the first sample."""
        if not self._samples:
            return None
        return min(self._samples)[1]

    def error_bound(self) -> Optional[float]:
        """Worst-case estimate error: half the best RTT seen."""
        if not self._samples:
            return None
        return min(self._samples)[0] / 2.0

    def reset(self) -> None:
        self._samples.clear()


@_races.race_checked
class ClockSync:
    """Controller-side per-peer estimator set.

    ``on_pong`` runs on the per-worker receive threads while
    ``offsets``/``reset`` run on drain/user threads — the dict is
    guarded; the estimators themselves are only ever touched under it.
    The lock is a leaf on the hvd-analyze lock-order graph."""

    def __init__(self) -> None:
        self._lock = _lockorder.make_lock("trace.ClockSync._lock")
        self._peers: Dict[int, OffsetEstimator] = {}  # guarded_by: _lock

    def on_pong(self, rank: int, t0: float, t1: float,
                t2: float) -> None:
        with self._lock:
            est = self._peers.get(rank)
            if est is None:
                est = self._peers[rank] = OffsetEstimator()
            off = est.add(t0, t1, t2)
        if off is not None:
            _telemetry.gauge(
                f"trace.clock_offset_seconds.rank{rank}",
                "estimated peer-clock offset vs rank 0 (min-RTT "
                "filtered)").set(round(off, 9))

    def reset(self, rank: int) -> None:
        """Session resume: the peer's path changed — re-measure."""
        with self._lock:
            est = self._peers.get(rank)
            if est is not None:
                est.reset()

    def offsets(self) -> Dict[int, float]:
        """rank -> offset seconds for every peer with an estimate."""
        with self._lock:
            return {r: est.offset() for r, est in self._peers.items()
                    if est.offset() is not None}

    def error_bounds(self) -> Dict[int, float]:
        with self._lock:
            return {r: est.error_bound()
                    for r, est in self._peers.items()
                    if est.error_bound() is not None}

    def sample_counts(self) -> Dict[int, int]:
        with self._lock:
            return {r: est.count for r, est in self._peers.items()}
