"""Fleet-trace merge: one clock-aligned Perfetto file for the job.

``hvd.dump_fleet_trace(path)`` on the rank-0 controller pulls every
rank's span buffer over the control plane (FRAME_TRACE — the
``cluster_metrics`` round-keyed rendezvous pattern, ops/transport.py),
shifts each worker's timestamps by its estimated clock offset
(trace/clock.py; a probe burst refreshes the estimates right before
the pull), and writes ONE ``chrome://tracing`` / Perfetto-loadable
JSON object: each rank is a trace "process" (pid = rank), each span
category a named thread row, and every event keeps its
``(step, cycle)`` args — the keys the analyzer
(``python -m horovod_tpu.trace``) groups by.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

# Stable category -> thread-row order (unknown categories append after).
CATEGORIES = ("negotiate", "dispatch", "collective", "host",
              "checkpoint", "serving")


def merge_events(per_rank: Dict[int, List[dict]],
                 offsets: Dict[int, float]) -> List[dict]:
    """Pure merge: assign pids, apply clock offsets, emit metadata rows.

    ``offsets[rank]`` is that rank's clock minus rank 0's
    (trace/clock.py), so correction SUBTRACTS it.  A rank with no
    estimate (single-process, or no pong yet) merges uncorrected —
    better a skewed row than a dropped rank."""
    out: List[dict] = []
    tids: Dict[str, int] = {c: i + 1 for i, c in enumerate(CATEGORIES)}
    for rank in sorted(per_rank):
        shift_us = float(offsets.get(rank, 0.0)) * 1e6
        out.append({"name": "process_name", "ph": "M", "pid": rank,
                    "args": {"name": f"rank {rank}"}})
        out.append({"name": "process_sort_index", "ph": "M",
                    "pid": rank, "args": {"sort_index": rank}})
        named: Dict[int, str] = {}
        for ev in per_rank[rank]:
            cat = str(ev.get("cat", "misc"))
            tid = tids.setdefault(cat, len(tids) + 1)
            if tid not in named:
                named[tid] = cat
                out.append({"name": "thread_name", "ph": "M",
                            "pid": rank, "tid": tid,
                            "args": {"name": cat}})
            merged = dict(ev)
            merged["pid"] = rank
            merged["tid"] = tid
            merged["ts"] = float(ev.get("ts", 0.0)) - shift_us
            out.append(merged)
    out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0),
                            e.get("pid", 0), e.get("name", "")))
    return out


def dump_fleet_trace(path: str, timeout: float = 10.0) -> str:
    """Merge every rank's span buffer into ``path`` (rank-0-only in
    multi-process mode, like ``cluster_metrics``); returns the path.

    Single-process mode writes the one local buffer.  Multi-process:
    a ping burst refreshes the clock offsets, then FRAME_TRACE pulls
    each worker's buffer — a rank that died or timed out is simply
    absent (coverage is recorded in the metadata; observability must
    not fail the job)."""
    from ..core import state as _state
    from . import current_ctx, export_events

    _state._check_initialized()
    st = _state.global_state()
    local = export_events()
    offsets: Dict[int, float] = {}
    bounds: Dict[int, float] = {}
    if not st.multiprocess:
        per_rank = {0: local}
    else:
        if st.process_index != 0:
            raise RuntimeError(
                "dump_fleet_trace() merges on the rank-0 controller; "
                "workers answer the controller's FRAME_TRACE pull "
                "automatically — use horovod_tpu.trace.export_events() "
                "for this rank's local buffer.")
        tp = st.transport
        tp.measure_clock_offsets(timeout=min(2.0, timeout))
        per_rank = tp.collect_traces(local, timeout=timeout)
        offsets = tp.clock.offsets()
        bounds = tp.clock.error_bounds()
    events = merge_events(per_rank, offsets)
    step, cycle, trace_id = current_ctx()
    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "format": "hvd-fleet-trace-v1",
            "trace_id": trace_id,
            "ranks": sorted(per_rank),
            "clock_offsets_seconds": {str(r): v
                                      for r, v in sorted(offsets.items())},
            "clock_error_bounds_seconds": {
                str(r): v for r, v in sorted(bounds.items())},
            "last_step": step,
            "last_cycle": cycle,
        },
    }
    with open(path, "w") as f:
        json.dump(payload, f)
    return path
