"""hvd-trace: fleet-wide distributed tracing over the runtime.

The timeline (utils/timeline.py) answers "what happened on rank 0";
the metrics registry (hvd-telemetry) answers "is the fleet healthy".
Neither can *explain a slow step*: each rank's Chrome timeline runs on
its own clock, so nobody can see that rank 5's input stall delayed the
whole fleet's allreduce, or which leg (host, pack, collective, DCN,
unpack, dispatch gap) owns the cycle.  hvd-trace closes that gap with
three pieces (docs/tracing.md):

1. **Span propagation** (this module) — every rank keeps a bounded
   in-memory buffer of *spans* (Chrome complete events on the rank's
   own monotonic clock).  A ``(step, cycle, trace_id)`` context rides
   the existing control frames — the worker's coalesced
   FRAME_REQUEST_BATCH carries its current context as a trailer, and
   every controller response broadcast carries rank 0's — so spans on
   different ranks are causally linkable: the same ``(step, cycle)``
   names the same fleet-wide negotiation cycle everywhere.  The same
   context is mirrored into the rank-0 Chrome timeline's event args
   (utils/timeline.set_context_provider).

2. **Clock alignment** (:mod:`~horovod_tpu.trace.clock`) — a
   ping/pong offset estimator over the TCP control plane (NTP-style
   min-RTT filter, re-measured on reconnect) lets rank 0 merge all
   ranks' span buffers into ONE ``chrome://tracing`` / Perfetto
   -loadable fleet trace: :func:`dump_fleet_trace`
   (:mod:`~horovod_tpu.trace.merge`, per-rank buffers pulled over
   FRAME_TRACE, the ``cluster_metrics`` round-keyed rendezvous
   pattern).

3. **Analysis** (:mod:`~horovod_tpu.trace.analyze`) — ``python -m
   horovod_tpu.trace <file>`` computes per-step critical-path
   attribution, names the straggler rank per cycle with its blame
   category, and emits a human report + JSON (``bench.py``'s ``trace``
   section).  :class:`~horovod_tpu.trace.watch.StragglerWatch` warns
   live when one rank's skew exceeds a threshold for N consecutive
   steps.

Hot-path budget mirrors the flight recorder's: recording a span is one
flag check, two ``time.monotonic`` reads (taken by the caller) and one
``deque.append`` (atomic in CPython — no lock).  ``HVD_TPU_TRACE=0``
opts out; ``set_enabled(False)`` is the runtime switch the bench's
overhead A/B flips (gated ≤ 5 % like telemetry was).

Env contract:
  HVD_TPU_TRACE=0           disable span recording (default on)
  HVD_TPU_TRACE_EVENTS      span buffer capacity per rank (default 20000)
  HVD_TPU_TRACE_PING        controller ping cadence seconds (default 1,
                            0 disables the periodic clock probes)
"""

from __future__ import annotations

import collections
import os
import struct
import time
from typing import Dict, List, Optional

from .. import telemetry as _telemetry

DEFAULT_CAPACITY = 20000

_M_SPANS = _telemetry.counter(
    "trace.spans", "hvd-trace spans recorded into the local buffer")

# Wire layout of the propagated context: <u32 step><u32 cycle>
# <u64 trace_id>, appended as a TRAILER to existing control frames
# (FRAME_REQUEST_BATCH worker->controller; FRAME_RESPONSES /
# FRAME_RESPONSE_BATCH controller->worker).  A trailer keeps the frames
# parseable by pre-trace peers: every existing payload is
# self-delimiting, so 16 extra bytes after it are simply ignored by a
# parser that does not know them.
CTX_STRUCT = struct.Struct("<IIQ")


def trace_enabled_env() -> bool:
    return os.environ.get("HVD_TPU_TRACE", "1") != "0"


def _capacity() -> int:
    return int(os.environ.get("HVD_TPU_TRACE_EVENTS",
                              str(DEFAULT_CAPACITY)))


def ping_interval() -> float:
    return float(os.environ.get("HVD_TPU_TRACE_PING", "1"))


class TraceState:
    """Per-process span buffer + the propagated (step, cycle, trace_id)
    context.

    The context fields are plain ints mutated by single writers (step:
    the training thread; cycle: the drain tick / receive thread) and
    read racily by span recorders — a span that lands on the previous
    cycle's id is fine (the analyzer groups per cycle, and cycle
    boundaries ARE the drain tick), so no lock is taken anywhere on the
    record path."""

    def __init__(self) -> None:
        self.enabled = trace_enabled_env()
        self.step = 0
        self.cycle = 0
        self.trace_id = 0
        self._events: collections.deque = collections.deque(
            maxlen=_capacity())

    # -- hot path ----------------------------------------------------------
    def record(self, ev: dict) -> None:
        """The one append path every event kind funnels through (the
        event-shape and accounting stay in one place)."""
        self._events.append(ev)
        _M_SPANS.inc()

    def span(self, name: str, cat: str, t0: float, t1: float,
             args: Optional[dict] = None) -> None:
        """Record one complete span.  ``t0``/``t1`` are
        ``time.monotonic()`` seconds (the clock the offset estimator
        aligns); stored as Chrome-trace microseconds."""
        if not self.enabled:
            return
        self.record({"name": name, "cat": cat, "ph": "X",
                     "ts": t0 * 1e6, "dur": max(0.0, (t1 - t0)) * 1e6,
                     "args": {"step": self.step, "cycle": self.cycle,
                              **(args or {})}})

    def instant(self, name: str, cat: str,
                args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        self.record({"name": name, "cat": cat, "ph": "i", "s": "t",
                     "ts": time.monotonic() * 1e6,
                     "args": {"step": self.step, "cycle": self.cycle,
                              **(args or {})}})

    # -- cold paths --------------------------------------------------------
    def export(self) -> List[dict]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()


_state = TraceState()


def state() -> TraceState:
    return _state


def enabled() -> bool:
    return _state.enabled


def set_enabled(v: bool) -> None:
    """Runtime switch for span recording (the bench overhead A/B flips
    this exactly like ``telemetry.set_enabled``).  Re-enabling restores
    the env gate."""
    _state.enabled = bool(v) and trace_enabled_env()


def span(name: str, cat: str, t0: float, t1: float,
         args: Optional[dict] = None) -> None:
    _state.span(name, cat, t0, t1, args)


def instant(name: str, cat: str, args: Optional[dict] = None) -> None:
    _state.instant(name, cat, args)


def export_events() -> List[dict]:
    """This rank's local span buffer (Chrome complete events, local
    monotonic microseconds, no pid — the merge assigns ranks)."""
    return _state.export()


def clear() -> None:
    _state.clear()


# -- propagated context ----------------------------------------------------

def set_step(n: int) -> None:
    """Stamp the training step every subsequent span carries.  Called
    by the train-step wrapper (parallel/training.py) once per step;
    explicit calls override (serving loops, tests)."""
    _state.step = int(n)


def on_step() -> int:
    """Advance the step counter by one (the train-step wrapper's
    per-call hook); returns the new step."""
    _state.step += 1
    return _state.step


def current_step() -> int:
    return _state.step


def next_cycle() -> tuple:
    """Advance the negotiation-cycle counter (rank 0 / single-process
    only: one increment per response broadcast — the fleet-wide cycle
    id every rank's spans then share).  Returns the new context."""
    _state.cycle += 1
    return (_state.step, _state.cycle, _state.trace_id)


def observe_ctx(step: int, cycle: int, trace_id: int) -> None:
    """Adopt rank 0's broadcast context (worker side).  The STEP is
    deliberately not adopted: steps are a local training-loop notion
    each rank stamps itself (ranks run the same loop), while the cycle
    id must be the controller's so cross-rank spans line up."""
    _state.cycle = int(cycle)
    _state.trace_id = int(trace_id)


def current_ctx() -> tuple:
    return (_state.step, _state.cycle, _state.trace_id)


def current_args() -> Dict[str, int]:
    """The context dict mirrored into timeline event args
    (utils/timeline.set_context_provider)."""
    if not _state.enabled:
        return {}
    return {"step": _state.step, "cycle": _state.cycle}


def pack_ctx() -> bytes:
    """The 16-byte wire trailer (see CTX_STRUCT)."""
    return CTX_STRUCT.pack(_state.step & 0xFFFFFFFF,
                           _state.cycle & 0xFFFFFFFF, _state.trace_id)


def unpack_ctx(buf: bytes, off: int) -> Optional[tuple]:
    """Parse a context trailer at ``off`` when present (None when the
    payload predates the trace layer — old peer / tests poking raw
    frames)."""
    if len(buf) - off < CTX_STRUCT.size:
        return None
    return CTX_STRUCT.unpack_from(buf, off)


def reset_run(rank: int = 0, trace_id: Optional[int] = None) -> None:
    """Fresh trace for a (re-)init: new trace id on rank 0 (workers
    adopt it from the first broadcast), counters to zero, buffer
    cleared."""
    _state.step = 0
    _state.cycle = 0
    _state.enabled = trace_enabled_env()
    if trace_id is not None:
        _state.trace_id = int(trace_id)
    elif rank == 0:
        _state.trace_id = int.from_bytes(os.urandom(8), "little") or 1
    _state.clear()
    # The arrival tracker restarts with the counters: the new run
    # reuses the same (step, cycle) keys, and stale stamps would both
    # dedup away the new run's arrivals and poison its skew baseline.
    from . import watch as _watch

    _watch.tracker.clear()


def note_batch_arrival(rank: int, step: int, cycle: int) -> None:
    """Controller-side: one rank's negotiation traffic for a cycle
    arrived — a worker's coalesced request frame (with its trace
    trailer), or rank 0's own first local submit of the tick.  Feeds
    the live skew tracker (:mod:`~horovod_tpu.trace.watch`) and
    records an arrival instant — the analyzer's per-cycle straggler
    signal.  Deduplicated per (rank, step, cycle): rank 0 submits once
    per tensor but only the cycle's FIRST stamp is an arrival."""
    if not _state.enabled:
        return
    now = time.monotonic()
    from . import watch as _watch

    if not _watch.tracker.note(rank, step, cycle, now):
        return  # duplicate stamp for this (rank, step, cycle)
    _state.record({"name": "BATCH_ARRIVAL", "cat": "negotiate",
                   "ph": "i", "s": "t", "ts": now * 1e6,
                   "args": {"step": int(step), "cycle": int(cycle),
                            "rank": int(rank)}})


# Mirror the propagated context into rank 0's Chrome timeline events.
from ..utils import timeline as _timeline  # noqa: E402

_timeline.set_context_provider(current_args)


def __getattr__(name):
    # Lazy resolution for cycle safety: this package is imported by
    # low-level modules (ops/collective, ops/transport) while
    # watch/merge import back into higher layers (callbacks, core
    # state), so those submodules must not load at trace-import time.
    # horovod_tpu/__init__ re-exports both eagerly at the END of the
    # package import, when every layer exists.
    if name == "dump_fleet_trace":
        from .merge import dump_fleet_trace

        return dump_fleet_trace
    if name == "StragglerWatch":
        from .watch import StragglerWatch

        return StragglerWatch
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
