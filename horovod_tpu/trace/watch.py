"""Live straggler detection (hvd-trace piece 3, the online half).

The controller sees every rank's coalesced request frame per
negotiation cycle (FRAME_REQUEST_BATCH with its trace trailer):
the spread of those arrival stamps IS the fleet's skew for that cycle,
on one clock, with no extra wire traffic.  :class:`SkewTracker`
accumulates it; :class:`StragglerWatch` is the training callback that
warns — live, while the job runs — when ONE rank's skew exceeds a
threshold for N consecutive steps, naming the rank (the offline
analyzer, trace/analyze.py, then explains *why* from the merged
trace).
"""

from __future__ import annotations

import collections
import sys
import time
from typing import Dict, List, Optional

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..analysis import races as _races
from ..telemetry import flight as _flight

_M_WARNINGS = _telemetry.counter(
    "trace.straggler_warnings",
    "StragglerWatch firings (one rank's skew over threshold for N "
    "consecutive steps)")

# Cycles of arrival data retained for skew queries.
HISTORY = 256


@_races.race_checked
class SkewTracker:
    """Per-cycle request-arrival skew, fed by
    ``trace.note_batch_arrival``: workers' frames stamp on receipt,
    and rank 0 stamps its own first local submit of the cycle
    (ops/transport.ControllerTransport.submit), so even the minimal
    controller + one-worker fleet produces two entries per cycle.
    Skew for a rank = its arrival minus the cycle's first arrival."""

    def __init__(self, history: int = HISTORY) -> None:
        self._lock = _lockorder.make_lock("trace.SkewTracker._lock")
        # (step, cycle) -> {rank: arrival monotonic}, insertion-ordered
        # and bounded.  guarded_by: _lock
        self._cycles: "collections.OrderedDict" = collections.OrderedDict()
        self._history = history

    def note(self, rank: int, step: int, cycle: int, t: float) -> bool:
        """Record one arrival stamp; returns False when this
        (rank, step, cycle) already has one (the dedup the per-tensor
        rank-0 feed relies on)."""
        with self._lock:
            key = (int(step), int(cycle))
            entry = self._cycles.get(key)
            if entry is None:
                entry = self._cycles[key] = {}
                while len(self._cycles) > self._history:
                    self._cycles.popitem(last=False)
            if int(rank) in entry:
                return False
            entry[int(rank)] = float(t)
            return True

    def skew_by_rank(self, last_n: int = 32) -> Dict[int, float]:
        """rank -> median skew seconds over the last ``last_n`` cycles
        (arrival minus the cycle's earliest arrival; cycles with one
        rank contribute nothing)."""
        with self._lock:
            cycles = list(self._cycles.values())[-last_n:]
        per_rank: Dict[int, List[float]] = {}
        for entry in cycles:
            if len(entry) < 2:
                continue
            first = min(entry.values())
            for rank, t in entry.items():
                per_rank.setdefault(rank, []).append(t - first)
        out = {}
        for rank, skews in per_rank.items():
            skews.sort()
            out[rank] = skews[len(skews) // 2]
        return out

    def clear(self) -> None:
        with self._lock:
            self._cycles.clear()


# Process-global tracker the controller transport feeds.
tracker = SkewTracker()


class StragglerWatch:
    """Training callback: warn live when one rank's negotiation skew
    exceeds ``threshold`` seconds for ``patience`` consecutive steps.

    Drop it into the callback list of any training loop (it implements
    the same duck-typed ``on_batch_end``/``on_epoch_end`` surface as
    horovod_tpu.callbacks.Callback); effective on the rank-0 controller
    — workers see no arrival stream and no-op.  Each firing prints the
    rank, its median skew and the threshold, bumps
    ``trace.straggler_warnings`` and flight-records the event, so a
    slow host is named within ``patience`` steps instead of discovered
    in a post-mortem.
    """

    def __init__(self, threshold: float = 0.05, patience: int = 5,
                 tracker_: Optional[SkewTracker] = None) -> None:
        if threshold <= 0 or patience < 1:
            raise ValueError(
                f"StragglerWatch needs threshold > 0 and patience >= 1 "
                f"(got {threshold}, {patience})")
        self.threshold = float(threshold)
        self.patience = int(patience)
        self._tracker = tracker_ if tracker_ is not None else tracker
        self._streaks: Dict[int, int] = {}
        self.warnings: List[dict] = []

    def set_trainer(self, trainer) -> None:  # Callback surface
        pass

    # -- the check, callable from any loop cadence -------------------------
    def check(self, skews: Optional[Dict[int, float]] = None
              ) -> Optional[List[dict]]:
        """One step's evaluation; returns the list of warning dicts
        when any rank fired this step — EVERY rank past its patience is
        named (two simultaneously slow hosts produce two warnings, not
        one), else None.  Tests drive this directly with synthetic
        skews."""
        if skews is None:
            skews = self._tracker.skew_by_rank()
        fired: List[dict] = []
        for rank in sorted(skews):
            skew = skews[rank]
            if skew > self.threshold:
                self._streaks[rank] = self._streaks.get(rank, 0) + 1
            else:
                self._streaks.pop(rank, None)
            if self._streaks.get(rank, 0) >= self.patience:
                fired.append({"rank": rank, "skew": skew,
                              "threshold": self.threshold,
                              "steps": self._streaks[rank]})
                self._streaks[rank] = 0
        for rank in list(self._streaks):
            if rank not in skews:
                del self._streaks[rank]
        for w in fired:
            self.warnings.append(w)
            _M_WARNINGS.inc()
            _flight.record("straggler", w["rank"],
                           round(w["skew"], 6))
            print(f"WARNING: hvd-trace StragglerWatch: rank "
                  f"{w['rank']} has lagged the fleet by "
                  f"{w['skew'] * 1e3:.1f} ms (threshold "
                  f"{self.threshold * 1e3:.1f} ms) for "
                  f"{self.patience} consecutive steps — run "
                  f"python -m horovod_tpu.trace on a fleet trace to "
                  f"attribute the stall (docs/tracing.md)",
                  file=sys.stderr)
        return fired or None

    # -- Callback surface --------------------------------------------------
    def on_batch_end(self, batch: int, logs=None) -> None:
        self.check()

    def on_epoch_end(self, epoch: int, logs=None) -> None:
        self.check()
