"""CLI: ``python -m horovod_tpu.trace <fleet-trace.json> [--json out]``.

Prints the human critical-path / straggler report (trace/analyze.py);
``--json`` additionally writes the machine report (``-`` for stdout —
the form ``bench.py`` and the CI determinism gate consume).
"""

from __future__ import annotations

import argparse
import json
import sys

from .analyze import analyze, load_trace, render


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.trace",
        description="hvd-trace fleet-trace analyzer (docs/tracing.md)")
    ap.add_argument("trace", help="merged fleet trace "
                    "(hvd.dump_fleet_trace output) or a rank-0 "
                    "Chrome timeline")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the JSON report ('-' = stdout, "
                    "suppressing the human report)")
    args = ap.parse_args(argv)
    try:
        events = load_trace(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    report = analyze(events)
    text = json.dumps(report, sort_keys=True, indent=1)
    if args.json == "-":
        print(text)
        return 0
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    sys.stdout.write(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
