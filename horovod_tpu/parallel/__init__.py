"""horovod_tpu.parallel"""
