"""Tensor (model) parallelism: Megatron-style sharded matmuls.

Beyond-parity extension (the reference shards nothing — SURVEY.md §2.3
"Tensor parallelism: NO").  Weight matrices shard over the
:data:`..core.topology.MODEL_AXIS` mesh axis; activations stay replicated
within a model group.  The classic pairing keeps communication to one
``psum`` per block:

* :func:`column_parallel` — weight split on the *output* feature axis;
  each device computes a disjoint slice of the outputs.  No communication
  (outputs stay sharded), so it starts a block.
* :func:`row_parallel` — weight split on the *input* feature axis; each
  device contracts its input slice and the partial products are summed
  with ``lax.psum``.  It ends a block, consuming column-parallel outputs
  directly.

``tp_mlp`` composes them into the standard 2-layer block (one collective
per MLP); attention uses column-parallel QKV (heads sharded) + row-
parallel output projection the same way — see models/transformer.py.

**Fused closers/openers** (hvd-fuse, ops/fused.py): ``row_parallel``'s
GEMM+psum closer is chunked along the token axis so chunk *i*'s
partial-product reduction flies while chunk *i+1* multiplies, inside one
XLA program — bitwise-identical to the unfused program (rows are
reduction-free; psum is elementwise).  The sequence-parallel-style pair
:func:`row_parallel_scatter` (matmul + reduce_scatter: each device keeps
its feature shard of the sum) and :func:`gather_column_parallel`
(all_gather + matmul: re-gather the feature shards into the next
block's GEMM) hand activations off feature-sharded between blocks, and
both chunk the same way.  ``fuse``/``fuse_chunks`` default to the
``HVD_TPU_FUSE`` / ``HVD_TPU_FUSE_CHUNKS`` knobs.

All functions are for use inside ``shard_map`` over a mesh that has the
model axis.  Helpers to place full weights shard-wise live here too.
"""

from __future__ import annotations

from typing import Optional

import jax

from ..core import compat as _compat
import jax.numpy as jnp

from ..core.topology import MODEL_AXIS
from ..ops import fused as _fused


def column_parallel(x, w, b=None, *, axis_name: str = MODEL_AXIS,
                    gather_output: bool = False):
    """``y_local = x @ w_local (+ b_local)`` with ``w`` sharded on its
    last (output) axis.  Outputs are feature-sharded unless
    ``gather_output``.
    """
    y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
    if b is not None:
        y = y + b
    if gather_output:
        y = jax.lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel(x, w, b=None, *, axis_name: str = MODEL_AXIS,
                 input_is_parallel: bool = True,
                 fuse: Optional[bool] = None,
                 fuse_chunks: Optional[int] = None):
    """``y = psum_axis(x_local @ w_local) (+ b)`` with ``w`` sharded on its
    first (input) axis.

    ``input_is_parallel=True`` (the default) means ``x`` is already
    feature-sharded — i.e. it came from :func:`column_parallel`; otherwise
    the local input slice is taken here.

    When fusion is on (the default; ``HVD_TPU_FUSE``), the GEMM is
    chunked along the token axis and each chunk's psum is emitted inside
    the same program, so chunk *i*'s reduction overlaps chunk *i+1*'s
    multiply.  Bitwise-identical to the unfused program: the per-chunk
    leg repeats the exact unfused dot→cast→psum ordering and psum is
    elementwise in the chunked rows.
    """
    if not input_is_parallel:
        n = _compat.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        shard = x.shape[-1] // n
        x = jax.lax.dynamic_slice_in_dim(x, idx * shard, shard, axis=-1)

    def closer(xc):
        yc = jnp.dot(xc, w,
                     preferred_element_type=jnp.float32).astype(x.dtype)
        return jax.lax.psum(yc, axis_name)

    y = _fused.chunked_map(closer, x, axis=0, chunks=fuse_chunks,
                           fuse=fuse)
    if b is not None:
        y = y + b
    return y


def row_parallel_scatter(x, w, b_local=None, *,
                         axis_name: str = MODEL_AXIS,
                         fuse: Optional[bool] = None,
                         fuse_chunks: Optional[int] = None):
    """Matmul + reduce_scatter closer: ``psum_scatter(x_local @ w_local)``
    — each device keeps only its shard of the summed output's LAST
    (feature) axis, 1/n the bytes of :func:`row_parallel`'s full psum.

    The feature-sharded output hands off directly to
    :func:`gather_column_parallel` in the next block (the fused
    sequence-parallel-style pair).  ``b_local`` is the caller's shard of
    the bias (e.g. via :func:`local_shard`).  Chunked along the token
    axis like :func:`row_parallel`; psum_scatter is elementwise in rows,
    so the fused program is bitwise-identical to the unfused one.
    """
    def closer(xc):
        yc = jnp.dot(xc, w,
                     preferred_element_type=jnp.float32).astype(x.dtype)
        return jax.lax.psum_scatter(yc, axis_name,
                                    scatter_dimension=yc.ndim - 1,
                                    tiled=True)

    y = _fused.chunked_map(closer, x, axis=0, chunks=fuse_chunks,
                           fuse=fuse)
    if b_local is not None:
        y = y + b_local
    return y


def gather_column_parallel(x, w, b=None, *, axis_name: str = MODEL_AXIS,
                           fuse: Optional[bool] = None,
                           fuse_chunks: Optional[int] = None):
    """All_gather + matmul opener: ``all_gather(x) @ w_local`` where ``x``
    arrives feature-sharded (from :func:`row_parallel_scatter`) and ``w``
    is sharded on its last (output) axis like :func:`column_parallel`.

    Chunked along the token axis: chunk *i+1*'s gather flies while chunk
    *i* multiplies.  Gathering the contraction axis per row-chunk never
    reorders any element's dot, so the fused program is
    bitwise-identical to the unfused one.
    """
    def opener(xc):
        xg = jax.lax.all_gather(xc, axis_name, axis=xc.ndim - 1,
                                tiled=True)
        return jnp.dot(xg, w,
                       preferred_element_type=jnp.float32).astype(x.dtype)

    y = _fused.chunked_map(opener, x, axis=0, chunks=fuse_chunks,
                           fuse=fuse)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w_in, b_in, w_out, b_out, *, axis_name: str = MODEL_AXIS,
           activation=jax.nn.gelu, fuse: Optional[bool] = None,
           fuse_chunks: Optional[int] = None):
    """The Megatron MLP block: column-parallel up-projection, elementwise
    activation on the sharded features, row-parallel down-projection.
    Exactly one ``psum`` of communication (chunk-fused with the down-
    projection GEMM unless ``HVD_TPU_FUSE=off``)."""
    h = column_parallel(x, w_in, b_in, axis_name=axis_name)
    h = activation(h)
    return row_parallel(h, w_out, b_out, axis_name=axis_name, fuse=fuse,
                        fuse_chunks=fuse_chunks)


def local_shard(full, dim: int, *, axis_name: str = MODEL_AXIS):
    """``full``'s shard for the calling device along ``dim`` (inside
    shard_map)."""
    n = _compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    size = full.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(full, idx * size, size, axis=dim)
