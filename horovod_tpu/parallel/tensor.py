"""Tensor (model) parallelism: Megatron-style sharded matmuls.

Beyond-parity extension (the reference shards nothing — SURVEY.md §2.3
"Tensor parallelism: NO").  Weight matrices shard over the
:data:`..core.topology.MODEL_AXIS` mesh axis; activations stay replicated
within a model group.  The classic pairing keeps communication to one
``psum`` per block:

* :func:`column_parallel` — weight split on the *output* feature axis;
  each device computes a disjoint slice of the outputs.  No communication
  (outputs stay sharded), so it starts a block.
* :func:`row_parallel` — weight split on the *input* feature axis; each
  device contracts its input slice and the partial products are summed
  with ``lax.psum``.  It ends a block, consuming column-parallel outputs
  directly.

``tp_mlp`` composes them into the standard 2-layer block (one collective
per MLP); attention uses column-parallel QKV (heads sharded) + row-
parallel output projection the same way — see models/transformer.py.

All functions are for use inside ``shard_map`` over a mesh that has the
model axis.  Helpers to place full weights shard-wise live here too.
"""

from __future__ import annotations

from typing import Optional

import jax

from ..core import compat as _compat
import jax.numpy as jnp

from ..core.topology import MODEL_AXIS


def column_parallel(x, w, b=None, *, axis_name: str = MODEL_AXIS,
                    gather_output: bool = False):
    """``y_local = x @ w_local (+ b_local)`` with ``w`` sharded on its
    last (output) axis.  Outputs are feature-sharded unless
    ``gather_output``.
    """
    y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
    if b is not None:
        y = y + b
    if gather_output:
        y = jax.lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel(x, w, b=None, *, axis_name: str = MODEL_AXIS,
                 input_is_parallel: bool = True):
    """``y = psum_axis(x_local @ w_local) (+ b)`` with ``w`` sharded on its
    first (input) axis.

    ``input_is_parallel=True`` (the default) means ``x`` is already
    feature-sharded — i.e. it came from :func:`column_parallel`; otherwise
    the local input slice is taken here.
    """
    if not input_is_parallel:
        n = _compat.axis_size(axis_name)
        idx = jax.lax.axis_index(axis_name)
        shard = x.shape[-1] // n
        x = jax.lax.dynamic_slice_in_dim(x, idx * shard, shard, axis=-1)
    y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
    y = jax.lax.psum(y, axis_name)
    if b is not None:
        y = y + b
    return y


def tp_mlp(x, w_in, b_in, w_out, b_out, *, axis_name: str = MODEL_AXIS,
           activation=jax.nn.gelu):
    """The Megatron MLP block: column-parallel up-projection, elementwise
    activation on the sharded features, row-parallel down-projection.
    Exactly one ``psum`` of communication."""
    h = column_parallel(x, w_in, b_in, axis_name=axis_name)
    h = activation(h)
    return row_parallel(h, w_out, b_out, axis_name=axis_name)


def local_shard(full, dim: int, *, axis_name: str = MODEL_AXIS):
    """``full``'s shard for the calling device along ``dim`` (inside
    shard_map)."""
    n = _compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    size = full.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(full, idx * size, size, axis=dim)
