"""Data-parallel training glue: DistributedOptimizer + parameter broadcast.

TPU-native re-design of the reference's L5 layer:

* ``DistributedOptimizer`` — reference wraps a TF optimizer's
  ``compute_gradients`` (tensorflow/__init__.py:133-192), a Torch
  optimizer's grad-accumulator hooks (torch/__init__.py:62-87), or a Keras
  optimizer's ``get_gradients`` (keras/__init__.py:29-89).  The JAX
  analogue of "the thing that transforms gradients before the update" is an
  :mod:`optax` gradient transformation, so ours wraps any
  ``optax.GradientTransformation`` and averages gradients across replicas
  before the inner update.
* ``broadcast_parameters`` / ``broadcast_global_variables`` — replica-
  consistent initialization (reference: torch/__init__.py:125-152,
  tensorflow/__init__.py:88-130).

Two execution contexts, chosen automatically:

* **static path** (inside a ``shard_map``/``pmap`` trace over the replica
  axis): gradients reduce with ``lax.psum`` using Tensor-Fusion bucketing —
  same-dtype gradients are flattened and concatenated into buckets of at
  most ``HOROVOD_FUSION_THRESHOLD`` bytes (default 64 MB, reference
  operations.cc:140) so small tensors ride one collective
  (reference: docs/tensor-fusion.md).  XLA then overlaps these collectives
  with remaining backprop compute.
* **eager path** (no replica axis bound, e.g. host-driven loops): each
  gradient goes through the dynamic-path collective queue as
  ``allreduce_async`` and all handles are synchronized before the update —
  exactly the reference Torch optimizer's hook + ``step()`` flow
  (torch/__init__.py:62-87).
"""

from __future__ import annotations

import math
import os
from typing import Any, NamedTuple, Optional

import jax

from ..core import compat as _compat
import jax.numpy as jnp
import numpy as np

from ..core import state as _state
from ..core.state import REPLICA_AXIS
from ..ops.wire import ReduceOp


def _resolve_grad_op(average: bool, op) -> ReduceOp:
    """Gradient-reduction operator: op supersedes average (the post-v0.13
    contract); only sum/average/adasum are meaningful for gradients."""
    if op is None:
        return ReduceOp.AVERAGE if average else ReduceOp.SUM
    red = ReduceOp(op)
    if red not in (ReduceOp.AVERAGE, ReduceOp.SUM, ReduceOp.ADASUM):
        raise ValueError(
            f"gradient reduction supports op=Average/Sum/Adasum; got "
            f"{red.name.lower()} (min/max/product are not gradient "
            f"combiners).")
    return red


def _in_replica_context() -> bool:
    """True when tracing under a mesh axis named ``REPLICA_AXIS`` (i.e.
    inside shard_map/pmap over the replica mesh)."""
    try:
        jax.lax.psum(jnp.zeros((), jnp.float32), REPLICA_AXIS)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _fusion_threshold_bytes() -> int:
    st = _state.global_state()
    if st.initialized:
        return st.fusion_threshold_bytes
    return int(os.environ.get("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024))


def partition_fusion_buckets(leaves, threshold: int):
    """Greedy Tensor-Fusion partition of a flat leaf list.

    Group by dtype in first-appearance order, then pack each dtype's
    leaves — in order — into buckets of at most ``threshold`` bytes (a
    leaf bigger than the threshold alone forms its own bucket; a
    threshold <= 0 disables fusion, one bucket per leaf).  ``leaves``
    may be arrays or aval-likes (anything with ``shape``/``dtype``).
    Returns a list of index lists covering every leaf exactly once.

    This is THE partition rule of the repo: the static path's wire
    packing below, the coordinator's fusion planning over one
    submission window (``ops/cache.plan_fusion`` reproduces it for the
    tensors a single drain tick sees) and the overlap path's
    dispatch-boundary planning (``parallel/overlap.py``) all derive
    from it — keeping them identical is what makes the overlapped
    step's per-bucket quantized reduction bitwise-comparable to a
    serialized dispatch of the same buckets (same bucket partition ⇒
    same pow2-scale blocks and error-feedback keys per bucket).
    """
    by_dtype: dict = {}
    for i, g in enumerate(leaves):
        by_dtype.setdefault(jnp.dtype(g.dtype), []).append(i)
    buckets: list = []
    for dtype, idxs in by_dtype.items():
        itemsize = jnp.dtype(dtype).itemsize
        bucket: list = []
        bucket_bytes = 0
        for i in idxs:
            nbytes = int(np.prod(leaves[i].shape, dtype=np.int64)) \
                * itemsize if leaves[i].shape else itemsize
            if threshold <= 0 or (
                    bucket and bucket_bytes + nbytes > threshold):
                if bucket:
                    buckets.append(bucket)
                bucket, bucket_bytes = [], 0
            bucket.append(i)
            bucket_bytes += nbytes
        if bucket:
            buckets.append(bucket)
    return buckets


def _adasum_gradients(grads):
    """Whole-gradient Adasum inside the replica trace.

    The model gradient is ONE logical vector here (unlike user-visible
    eager allreduces, which are independent per-tensor ops and therefore
    never fuse under adasum), so the scale-insensitive combination
    (arXiv:2006.02924) runs on the flattened concatenation: log2(n)
    ``ppermute`` exchange rounds on ICI, each combining partner vectors
    with ``(1 - a·b/2||a||²) a + (1 - a·b/2||b||²) b`` — total wire cost
    log2(n) × |grad|, vs 2×|grad|(n-1)/n for a ring allreduce.
    """
    from ..ops.sparse import IndexedSlices

    leaves, treedef = jax.tree_util.tree_flatten(
        grads, is_leaf=lambda g: isinstance(g, IndexedSlices))
    if any(isinstance(g, IndexedSlices) for g in leaves):
        raise ValueError(
            "op=Adasum does not support sparse (IndexedSlices) gradients; "
            "pass sparse_as_dense=True to densify them first.")
    n = _compat.axis_size(REPLICA_AXIS)
    if n & (n - 1) != 0:
        raise ValueError(
            f"op=Adasum requires a power-of-two replica count for its "
            f"recursive-doubling ppermute ladder; got {n}.")
    # Accumulation dtype: promote over the leaf dtypes with a float32
    # floor, matching the eager _adasum_ladder's promote_types rule.
    # (Without jax x64 mode this always resolves to float32; the loop
    # keeps the two Adasum paths' precision contract identical.)
    acc_dtype = jnp.float32
    for g in leaves:
        acc_dtype = jnp.promote_types(acc_dtype, g.dtype)
    v = jnp.concatenate(
        [jnp.ravel(g).astype(acc_dtype) for g in leaves])
    for r in range(int(math.log2(n))):
        dist = 1 << r
        perm = [(i, i ^ dist) for i in range(n)]
        other = jax.lax.ppermute(v, REPLICA_AXIS, perm)
        dot = jnp.sum(v * other)
        na = jnp.sum(v * v)
        nb = jnp.sum(other * other)
        ca = 1.0 - jnp.where(na > 0, dot / (2.0 * na), 0.0)
        cb = 1.0 - jnp.where(nb > 0, dot / (2.0 * nb), 0.0)
        v = ca * v + cb * other
    out, off = [], 0
    for g in leaves:
        out.append(v[off:off + g.size].reshape(g.shape).astype(g.dtype))
        off += g.size
    return jax.tree_util.tree_unflatten(treedef, out)


def allreduce_gradients(grads, average: bool = True,
                        fusion_threshold: Optional[int] = None,
                        compression=None, op=None):
    """Cross-replica gradient reduction with Tensor Fusion bucketing.

    Must be called inside a replica-axis trace (shard_map/pmap).  Gradients
    are grouped by dtype and packed into flat buckets up to the fusion
    threshold; each bucket is one ``lax.psum`` — mirroring the reference's
    fusion buffer (operations.cc:941-1034) but letting XLA schedule and
    overlap the collectives.  A threshold of 0 disables fusion (one psum
    per tensor, reference docs/tensor-fusion.md).

    The threshold is not only a wire-packing knob: under the overlap
    mode (``HVD_TPU_OVERLAP``, docs/performance.md) the SAME partition
    (:func:`partition_fusion_buckets`) sets the dispatch-boundary
    granularity — each bucket becomes one megakernel launch streamed
    out of the backward pass.  ``op=Adasum`` ignores the threshold (and
    ``compression``) entirely: its dot products are defined on the
    whole full-precision gradient, so it never buckets, never fuses and
    never overlaps (see :func:`_adasum_gradients`).

    ``compression`` (a :class:`~horovod_tpu.ops.compression.Compressor`,
    e.g. ``hvd.Compression.bf16``) casts dense gradients down for the
    wire and restores the dtype after — sparse leaves already ship a
    minimal payload and pass through uncompressed.

    ``op`` (hvd.Average/Sum/Adasum, superseding ``average``) selects the
    combiner; Adasum runs the whole-gradient ppermute ladder (see
    :func:`_adasum_gradients`) and ignores fusion_threshold/compression
    (its dots are defined on the full-precision gradient).

    :class:`~horovod_tpu.ops.sparse.IndexedSlices` leaves exchange as an
    all_gather of (values, indices) — the reference's sparse branch
    (tensorflow/__init__.py:67-78) — and stay sparse in the result.
    """
    from ..ops.compression import NoneCompressor
    from ..ops.sparse import IndexedSlices

    red = _resolve_grad_op(average, op)
    if red == ReduceOp.ADASUM:
        return _adasum_gradients(grads)
    average = red == ReduceOp.AVERAGE
    compression = compression or NoneCompressor
    threshold = (_fusion_threshold_bytes()
                 if fusion_threshold is None else fusion_threshold)
    leaves, treedef = jax.tree_util.tree_flatten(
        grads, is_leaf=lambda g: isinstance(g, IndexedSlices))
    if not leaves:
        return grads
    # Compress dense leaves for the wire; remember each ctx for the
    # decompress after the reduction.  Bucketing below then groups by the
    # *compressed* dtype, so fused buckets stay narrow end-to-end.
    ctxs: list = [None] * len(leaves)
    for i, g in enumerate(leaves):
        if not isinstance(g, IndexedSlices):
            leaves[i], ctxs[i] = compression.compress(g)
    denom = None
    if average:
        # Under shard_map the axis size is static.
        denom = jax.lax.psum(jnp.ones((), jnp.float32), REPLICA_AXIS)

    def finish(x):
        # Applied AFTER decompress for dense leaves, so averaging divides
        # in the restored dtype (f32), not the narrow wire dtype —
        # matching the ZeRO-1 path's numerics (zero.py) at no wire cost.
        return (x / denom.astype(x.dtype)) if average else x

    def gather_sparse(g):
        vals = jax.lax.all_gather(g.values, REPLICA_AXIS, axis=0,
                                  tiled=True)
        idxs = jax.lax.all_gather(g.indices, REPLICA_AXIS, axis=0,
                                  tiled=True)
        return IndexedSlices(finish(vals), idxs, g.dense_shape)

    if threshold <= 0:
        red = [gather_sparse(g) if isinstance(g, IndexedSlices)
               else finish(compression.decompress(
                   jax.lax.psum(g, REPLICA_AXIS), ctx))
               for g, ctx in zip(leaves, ctxs)]
        return jax.tree_util.tree_unflatten(treedef, red)

    # Bucket by dtype, preserving leaf order for unflatten.  Sparse leaves
    # bypass bucketing (their payload is already minimal).  The partition
    # itself is the shared fusion rule (partition_fusion_buckets) so the
    # overlap path's dispatch boundaries match the wire packing exactly.
    out: list = [None] * len(leaves)
    dense: list = []
    for i, g in enumerate(leaves):
        if isinstance(g, IndexedSlices):
            out[i] = gather_sparse(g)
        else:
            dense.append(i)
    for bucket_pos in partition_fusion_buckets(
            [jnp.asarray(leaves[i]) for i in dense], threshold):
        bucket = [dense[p] for p in bucket_pos]
        if len(bucket) == 1:
            i = bucket[0]
            out[i] = jax.lax.psum(leaves[i], REPLICA_AXIS)
            continue
        flat = jnp.concatenate([jnp.ravel(leaves[i]) for i in bucket])
        red = jax.lax.psum(flat, REPLICA_AXIS)
        off = 0
        for i in bucket:
            n = leaves[i].size
            out[i] = red[off:off + n].reshape(leaves[i].shape)
            off += n
    out = [o if isinstance(g, IndexedSlices)
           else finish(compression.decompress(o, ctx))
           for o, g, ctx in zip(out, leaves, ctxs)]
    return jax.tree_util.tree_unflatten(treedef, out)


def _eager_allreduce_grads(grads, average: bool = True, compression=None):
    """Dynamic-path gradient reduction: fire all allreduces async, then
    synchronize — the Torch hook + step() pattern (torch/__init__.py:62-87),
    with coordinator-level fusion batching the small tensors.  Sparse
    (IndexedSlices) leaves take the allgather exchange transparently."""
    from ..ops import collective as C
    from ..ops import sparse as S
    from ..ops.compression import NoneCompressor

    compression = compression or NoneCompressor
    leaves, treedef = jax.tree_util.tree_flatten(
        grads, is_leaf=lambda g: isinstance(g, S.IndexedSlices))

    def _is_traced(g):
        if isinstance(g, S.IndexedSlices):
            return any(isinstance(f, jax.core.Tracer)
                       for f in (g.values, g.indices))
        return isinstance(g, jax.core.Tracer)

    if any(_is_traced(g) for g in leaves):
        raise RuntimeError(
            "DistributedOptimizer.update was traced (jit) outside a replica "
            "context. Either call it inside shard_map/pmap over the "
            f"'{REPLICA_AXIS}' axis, or build the step with "
            "horovod_tpu.parallel.training.make_train_step, which wires the "
            "reduction into the SPMD program.")
    # Fire EVERYTHING async first (sparse = one allgather pair per leaf),
    # then synchronize — so sparse and dense exchanges all overlap.
    handles = []
    for i, g in enumerate(leaves):
        if isinstance(g, S.IndexedSlices):
            handles.append((g, C.allgather_async(g.values,
                                                 name=f"grad.{i}.values"),
                            C.allgather_async(g.indices,
                                              name=f"grad.{i}.indices")))
        else:
            wire, ctx = compression.compress(g)
            handles.append((ctx, C.allreduce_async(wire, average=average,
                                                   name=f"grad.{i}")))
    denom = _state.contributor_count()
    red = []
    for h in handles:
        if len(h) == 3:
            g, hv, hi = h
            values = C.synchronize(hv)
            red.append(S.IndexedSlices(
                values / denom if average else values,
                C.synchronize(hi), g.dense_shape))
        else:
            ctx, handle = h
            red.append(compression.decompress(C.synchronize(handle), ctx))
    return jax.tree_util.tree_unflatten(treedef, red)


def _eager_adasum_grads(grads):
    """Dynamic-path whole-gradient Adasum: one flattened vector through
    the eager wire (same semantics as the static ladder — each process
    contributes its gradient as one logical vector)."""
    from ..ops import collective as C
    from ..ops.sparse import IndexedSlices

    leaves, treedef = jax.tree_util.tree_flatten(
        grads, is_leaf=lambda g: isinstance(g, IndexedSlices))
    if any(isinstance(g, IndexedSlices) for g in leaves):
        raise ValueError(
            "op=Adasum does not support sparse (IndexedSlices) gradients; "
            "pass sparse_as_dense=True to densify them first.")
    flat = jnp.concatenate([jnp.ravel(jnp.asarray(g, jnp.float32))
                            for g in leaves])
    red = C.allreduce(flat, op=ReduceOp.ADASUM, name="grad.adasum")
    out, off = [], 0
    for g in leaves:
        out.append(red[off:off + np.size(g)].reshape(np.shape(g)).astype(
            jnp.asarray(g).dtype))
        off += np.size(g)
    return jax.tree_util.tree_unflatten(treedef, out)


class DistributedOptimizer:
    """Wrap an optax optimizer so gradients are averaged across replicas
    before the update (≙ hvd.DistributedOptimizer in every reference
    frontend).  Usable exactly like the wrapped transformation:

        opt = hvd.DistributedOptimizer(optax.sgd(lr))
        opt_state = opt.init(params)
        updates, opt_state = opt.update(grads, opt_state, params)

    Inside a shard_map'd step the reduction is fused ``lax.psum``; outside,
    it is the eager async-handle path.  ``average=False`` sums instead
    (reference allreduce's average flag, tensorflow/__init__.py:49-60).
    """

    def __init__(self, optimizer, average: bool = True,
                 fusion_threshold: Optional[int] = None,
                 name: Optional[str] = None, sparse_as_dense: bool = False,
                 compression=None, op=None):
        self._inner = optimizer
        self._average = average
        # op=hvd.Adasum selects scale-insensitive whole-gradient combining
        # (the post-v0.13 DistributedOptimizer op= kwarg); validated here
        # so a bad op fails at construction, not mid-training.
        self._op = None if op is None else _resolve_grad_op(average, op)
        self._fusion_threshold = fusion_threshold
        self._name = name or "DistributedOptimizer"
        # ≙ the reference's device_dense/device_sparse per-op routing
        # choice (tensorflow/__init__.py:49-60): True forces sparse grads
        # through the dense psum path (cheaper when most rows are touched).
        self._sparse_as_dense = sparse_as_dense
        # hvd.Compression.{none,fp16,bf16}: cast dense grads down for the
        # wire, restore after (bf16 recommended on TPU).
        self._compression = compression

    def init(self, params):
        return self._inner.init(params)

    def _map_sparse(self, grads, fn):
        from ..ops.sparse import IndexedSlices

        return jax.tree_util.tree_map(
            lambda g: fn(g) if isinstance(g, IndexedSlices) else g, grads,
            is_leaf=lambda g: isinstance(g, IndexedSlices))

    def update(self, grads, opt_state, params=None, **kw):
        from ..ops import sparse as S

        if self._sparse_as_dense:
            grads = self._map_sparse(grads, S.as_dense)
        if _in_replica_context():
            grads = allreduce_gradients(
                grads, average=self._average,
                fusion_threshold=self._fusion_threshold,
                compression=self._compression, op=self._op)
        elif _state.is_initialized() and _state.size() > 1:
            if self._op == ReduceOp.ADASUM:
                grads = _eager_adasum_grads(grads)
            else:
                grads = _eager_allreduce_grads(grads,
                                               average=self._average,
                                               compression=self._compression)
        elif _state.is_initialized():
            pass  # size 1: reduction is the identity (reference behaves the
            #       same — collectives still run but are trivial).
        else:
            raise _state.NotInitializedError()
        # The exchange is sparse (the wire win); optax transformations are
        # dense, so scatter-sum the gathered slices before the update.
        # (The reference hands IndexedSlices to TF's sparse apply instead —
        # tensorflow/__init__.py:178-192 — optax has no sparse apply.)
        grads = self._map_sparse(grads, S.as_dense)
        return self._inner.update(grads, opt_state, params, **kw)

    # optax GradientTransformation duck-typing.
    def __iter__(self):
        yield self.init
        yield self.update


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a pytree of parameters from ``root_rank`` so every replica
    starts identical (≙ hvd.broadcast_parameters, torch/__init__.py:125-152:
    launch all broadcasts async, then synchronize).

    In single-controller SPMD the parameters are already one logical copy;
    the broadcast re-materializes them with a fully-replicated sharding over
    the replica mesh — the operation that guarantees consistency when
    parameters arrive process-local in multi-process mode.
    """
    from ..ops import collective as C

    leaves, treedef = jax.tree_util.tree_flatten(params)
    handles = [
        C.broadcast_async(leaf, root_rank, name=f"broadcast.param.{i}")
        for i, leaf in enumerate(leaves)
    ]
    out = [C.synchronize(h) for h in handles]
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_global_variables(params, root_rank: int = 0):
    """TF-style name for :func:`broadcast_parameters`
    (≙ hvd.broadcast_global_variables, tensorflow/__init__.py:88-96)."""
    return broadcast_parameters(params, root_rank)
