"""ZeRO-3 / FSDP-style data parallelism: parameters, gradients AND
optimizer state sharded across replicas.

Beyond-parity extension, one rung past :mod:`.zero` (ZeRO-1).  The
reference — and Horovod generally — replicates parameters on every
worker; fully-sharded storage arrived in the ecosystem later (DeepSpeed
ZeRO-3, PyTorch FSDP).  On TPU the idiomatic construction extends the
same allreduce decomposition ZeRO-1 uses:

    between steps : each replica stores only its contiguous 1/N slice
                    of the flattened parameters (plus 1/N of the
                    optimizer state) — resident memory for params +
                    Adam state drops from 3x model size to 3/N x.
    in the step   : all_gather(param shards) -> full params -> forward/
                    backward -> the gradient's reduce_scatter is the
                    TRANSPOSE of that all_gather -> each replica updates
                    only its slice.  Wire cost per step: one all_gather
                    + one reduce_scatter = the same bytes as plain DP's
                    fused allreduce.

Scope note (honest ZeRO-3 comparison): the full parameter vector is
gathered ONCE per step and lives for the duration of forward+backward —
peak memory includes one transient full-parameter copy (what DeepSpeed
calls ZeRO-3 with a single prefetch bucket; per-layer gather/release
needs model cooperation and is what the mesh-axis partition specs in
:mod:`.training`/`models.transformer` provide).  The *resident*
footprint between steps — where Adam's f32 moments dominate — is fully
sharded, which is the memory that limits model size in practice.

The elementwise-optimizer precondition and its build-time probe are
shared with ZeRO-1 (see :mod:`.zero`'s docstring): each replica applies
the optimizer to its flat slice with its slice of state.

Usage::

    fstep = make_fsdp_train_step(loss_fn, optax.adamw(3e-4))
    p_shard, opt_state = fstep.init(params)   # shard + free replicas
    for batch in data:
        p_shard, opt_state, loss = fstep.step(p_shard, opt_state, batch)
    params = fstep.full_params(p_shard)       # rank-0 checkpoint / eval
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import compat as _compat
from ..core import state as _state
from ..core.state import REPLICA_AXIS
from .data import DistributedOptimizer
from .training import _throttle_on_cpu
from .zero import (_abstract_state_or_raise, _check_elementwise,
                   _pad_flat, _replica_count, _sharded_state_specs)

try:
    import optax
except Exception:  # pragma: no cover - optax is baked into the image
    optax = None


class FsdpTrainStep(NamedTuple):
    """``init(params) -> (param_shard, opt_state)`` (both sharded 1/N
    per replica), ``step(param_shard, opt_state, batch) ->
    (param_shard, opt_state, loss)`` (stateful variant threads
    ``model_state`` after ``param_shard``), ``full_params(param_shard)
    -> params`` (the unsharded pytree, for checkpointing and
    evaluation), and ``shard_params(params) -> param_shard`` (re-shard
    a full pytree without touching optimizer state — checkpoint restore,
    broadcast-then-reshard)."""

    init: Callable[[Any], Any]
    step: Callable[..., Any]
    full_params: Callable[[Any], Any]
    shard_params: Callable[[Any], Any]


def make_fsdp_train_step(
    loss_fn,
    optimizer,
    mesh=None,
    average: bool = True,
    compression=None,
    donate: bool = True,
    has_state: bool = False,
    validate_elementwise: bool = True,
) -> FsdpTrainStep:
    """Build a ZeRO-3/FSDP-style train step over the replica mesh.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` on the local batch
        shard (or, with ``has_state=True``, ``loss_fn(params,
        model_state, batch) -> (scalar, new_model_state)``), with NO
        internal cross-replica reduction — the same contract as
        :func:`~horovod_tpu.parallel.training.make_train_step`.
      optimizer: an elementwise optax ``GradientTransformation`` (or a
        :class:`DistributedOptimizer` wrapping one — averaging flag and
        compression honored, as in :func:`.zero.make_zero_train_step`).
      compression: ``hvd.Compression.{bf16,fp16}`` casts the gradient
        for the reduce_scatter wire; the parameter all_gather stays
        uncompressed (it carries the master weights).

    Returns:
      :class:`FsdpTrainStep`.  ``init`` consumes the full (replicated)
      parameter pytree and returns the sharded flat parameter vector +
      sharded optimizer state; drop the original ``params`` reference
      afterwards or the memory saving never materializes.  One builder
      serves one parameter structure (the flat layout is captured at
      ``init``).
    """
    mesh = mesh or _state.mesh()
    n = _replica_count(mesh)

    if isinstance(optimizer, DistributedOptimizer):
        average = optimizer._average
        if optimizer._compression is not None:
            compression = optimizer._compression
        optimizer = optimizer._inner

    if validate_elementwise:
        _check_elementwise(optimizer, feature="FSDP",
                           api_name="make_fsdp_train_step")

    # Flat layout (unravel closure, true size, chunk) is fixed by the
    # parameter structure at init()/shard_params() time; step()/
    # full_params() read it.  One builder = one structure (enforced in
    # _capture_layout), so the jitted re-shard slicer is a single slot.
    layout: dict = {}

    def _capture_layout(params):
        # One builder serves one parameter structure: a later pytree
        # with the same element count but different leaf order would
        # silently misalign the already-sharded optimizer state, so any
        # structural change fails loudly here.
        sig = (jax.tree_util.tree_structure(params),
               tuple((tuple(leaf.shape), str(leaf.dtype)) for leaf in
                     jax.tree_util.tree_leaves(params)))
        if layout and layout["sig"] != sig:
            raise ValueError(
                "make_fsdp_train_step: parameter pytree structure "
                "differs from the one captured at init() — the sharded "
                "optimizer state is laid out for the original flat "
                "ordering, so re-sharding a different structure would "
                "silently apply wrong per-element state.  Build a new "
                "step for a new model structure.")
        flat, unravel, true_size = _pad_flat(params, n)
        layout["sig"] = sig
        layout["unravel"] = unravel
        layout["true_size"] = true_size
        layout["chunk"] = flat.size // n
        return flat, layout["chunk"]

    def _local_chunk(flat_padded, chunk):
        idx = jax.lax.axis_index(REPLICA_AXIS)
        return jax.lax.dynamic_slice(flat_padded, (idx * chunk,),
                                     (chunk,))

    def init(params):
        flat, chunk = _capture_layout(params)
        abstract = _abstract_state_or_raise(
            optimizer, chunk, flat.dtype, feature="FSDP",
            api_name="make_fsdp_train_step")

        def shard_and_init(flat_padded):
            p_chunk = _local_chunk(flat_padded, chunk)
            return p_chunk, optimizer.init(p_chunk)

        jitted = jax.jit(_compat.shard_map(
            shard_and_init, mesh=mesh, in_specs=(P(),),
            out_specs=(P(REPLICA_AXIS), _sharded_state_specs(abstract)),
            check_vma=False), donate_argnums=(0,))
        return jitted(flat)

    def shard_params(params):
        """Re-shard a full parameter pytree (same structure as the one
        given to ``init``) without touching optimizer state — for
        checkpoint restore or broadcast-then-reshard."""
        flat, chunk = _capture_layout(params)
        if "shard_fn" not in layout:
            layout["shard_fn"] = jax.jit(_compat.shard_map(
                lambda f: _local_chunk(f, chunk), mesh=mesh,
                in_specs=(P(),), out_specs=P(REPLICA_AXIS),
                check_vma=False), donate_argnums=(0,))
        return layout["shard_fn"](flat)

    def _layout():
        if not layout:
            raise RuntimeError(
                "make_fsdp_train_step: call init(params) before "
                "step()/full_params() — the flat parameter layout is "
                "captured there")
        return layout["unravel"], layout["true_size"], layout["chunk"]

    def per_replica_step(p_chunk, model_state, opt_state, batch):
        unravel, true_size, chunk = _layout()
        # One all_gather materializes the full parameters for the step;
        # its AD transpose is exactly the gradient reduce_scatter, but
        # the wire is kept explicit below so compression can ride it.
        flat_p = jax.lax.all_gather(p_chunk, REPLICA_AXIS, axis=0,
                                    tiled=True)

        if has_state:
            def flat_loss(fp):
                params = unravel(fp[:true_size])
                loss, new_state = loss_fn(params, model_state, batch)
                return loss, new_state

            loss, pull, new_model_state = jax.vjp(flat_loss, flat_p,
                                                  has_aux=True)
            # Synchronized BatchNorm, like the ZeRO-1/plain-DP builders.
            new_model_state = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, REPLICA_AXIS), new_model_state)
        else:
            def flat_loss(fp):
                return loss_fn(unravel(fp[:true_size]), batch)

            loss, pull = jax.vjp(flat_loss, flat_p)
            new_model_state = None
        (flat_g,) = pull(jnp.ones((), loss.dtype))

        ctx = None
        if compression is not None:
            flat_g, ctx = compression.compress(flat_g)
        g_chunk = jax.lax.psum_scatter(
            flat_g.reshape(n, chunk), REPLICA_AXIS, scatter_dimension=0)
        if compression is not None:
            g_chunk = compression.decompress(g_chunk, ctx)
        if average:
            g_chunk = g_chunk / n

        updates, opt_state = optimizer.update(g_chunk, opt_state, p_chunk)
        p_chunk = optax.apply_updates(p_chunk, updates)
        loss = jax.lax.pmean(loss, REPLICA_AXIS)
        if has_state:
            return p_chunk, new_model_state, opt_state, loss
        return p_chunk, opt_state, loss

    step_cache: dict = {}

    def _compiled(opt_state):
        specs = _sharded_state_specs(opt_state)
        key = jax.tree_util.tree_structure(specs), tuple(
            str(s) for s in jax.tree_util.tree_leaves(
                specs, is_leaf=lambda x: isinstance(x, P)))
        if key not in step_cache:
            if has_state:
                fn = per_replica_step
                in_specs = (P(REPLICA_AXIS), P(), specs, P(REPLICA_AXIS))
                out_specs = (P(REPLICA_AXIS), P(), specs, P())
                donate_argnums = (0, 1, 2) if donate else ()
            else:
                def fn(p_chunk, opt_state, batch):
                    return per_replica_step(p_chunk, None, opt_state,
                                            batch)
                in_specs = (P(REPLICA_AXIS), specs, P(REPLICA_AXIS))
                out_specs = (P(REPLICA_AXIS), specs, P())
                donate_argnums = (0, 1) if donate else ()
            jitted = jax.jit(
                _compat.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False),
                donate_argnums=donate_argnums)
            step_cache[key] = _throttle_on_cpu(jitted, mesh)
        return step_cache[key]

    if has_state:
        def step(p_shard, model_state, opt_state, batch):
            _layout()
            return _compiled(opt_state)(p_shard, model_state, opt_state,
                                        batch)
    else:
        def step(p_shard, opt_state, batch):
            _layout()
            return _compiled(opt_state)(p_shard, opt_state, batch)

    # Built once so repeat full_params calls hit the jit cache instead
    # of recompiling a fresh lambda every time.
    _gather = jax.jit(lambda x: x,
                      out_shardings=NamedSharding(mesh, P()))

    def full_params(p_shard):
        """The unsharded parameter pytree (device-gathered, replicated)
        — for rank-0 checkpointing (utils/checkpoint.py) or eval."""
        unravel, true_size, _ = _layout()
        return unravel(_gather(p_shard)[:true_size])

    return FsdpTrainStep(init=init, step=step, full_params=full_params,
                         shard_params=shard_params)


def make_fsdp_train_step_with_state(loss_fn, optimizer, mesh=None,
                                    average: bool = True,
                                    compression=None,
                                    donate: bool = True,
                                    validate_elementwise: bool = True,
                                    ) -> FsdpTrainStep:
    """Stateful-model spelling (BatchNorm etc.): ``loss_fn(params,
    model_state, batch) -> (loss, new_state)``; ``step(p_shard,
    model_state, opt_state, batch) -> (p_shard, model_state, opt_state,
    loss)`` — mirroring :func:`.zero.make_zero_train_step_with_state`."""
    return make_fsdp_train_step(loss_fn, optimizer, mesh=mesh,
                                average=average, compression=compression,
                                donate=donate, has_state=True,
                                validate_elementwise=validate_elementwise)
