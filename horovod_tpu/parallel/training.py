"""SPMD training-step builder — the static fast path.

The reference has no trainer of its own (training loops live in user
scripts, e.g. examples/tensorflow_mnist.py:83-119); what it provides is the
wiring of collectives into the step.  On TPU the idiomatic wiring is a
single jitted SPMD program: batch sharded over the replica mesh axis,
parameters replicated, per-replica gradients reduced with fused ``psum``
(Tensor Fusion, ≙ docs/tensor-fusion.md), optimizer update computed
redundantly per replica — exactly the data-parallel semantics of
``hvd.DistributedOptimizer`` (tensorflow/__init__.py:170-192) with the
5 ms-tick negotiation replaced by compiler-scheduled ICI collectives.

``make_train_step`` is what the examples, benchmarks and the multi-chip
dryrun build on.
"""

from __future__ import annotations

import collections
import os
import time
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry as _telemetry
from .. import trace as _trace
from ..core import compat as _compat
from ..core import state as _state
from ..core.state import REPLICA_AXIS
from ..memory import ledger as _mem
from ..memory import oom as _oom
from .data import DistributedOptimizer, allreduce_gradients

try:
    import optax
except Exception:  # pragma: no cover - optax is baked into the image
    optax = None

# Shared with parallel/input.py and frontends/loop.py (same registry
# entry): every place the loop blocks on the device/input feeds one
# histogram, so "is training host-bound?" is a single metric.
_M_HOST_STALL = _telemetry.histogram(
    "host.stall_seconds", "seconds",
    "time the training loop blocked waiting on the input queue")


def batch_sharding(mesh=None) -> NamedSharding:
    """Sharding that splits the leading (batch) axis across replicas."""
    mesh = mesh or _state.mesh()
    return NamedSharding(mesh, P(REPLICA_AXIS))


def replicated_sharding(mesh=None) -> NamedSharding:
    mesh = mesh or _state.mesh()
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh=None):
    """Place a host batch onto the mesh, leading axis split across replicas
    (the per-rank data sharding the reference gets from DistributedSampler /
    dataset shards, examples/pytorch_mnist.py:48-51).

    One batched ``jax.device_put`` over the whole pytree: a single
    transfer program per batch instead of one dispatch per leaf (the
    hvd-pipeline host-overlap contract; ``input.device_put_batch`` is
    the one implementation, which :func:`.input.prefetch_to_device`
    stages from a background thread)."""
    from .input import device_put_batch

    return device_put_batch(batch, mesh, sharding=batch_sharding(mesh))


def replicate(tree, mesh=None):
    from .input import device_put_batch

    return device_put_batch(tree, mesh, sharding=replicated_sharding(mesh))


def shard_local_batch(local_batch, mesh=None):
    """Assemble the global sharded batch from each process's LOCAL rows.

    The reference's input model: every rank loads only its own slice of
    the data (DistributedSampler / ``dataset.shard``, reference
    examples/pytorch_mnist.py:48-51) — no process ever materializes the
    global batch.  Each process passes its local leading-axis rows here;
    the processes' shards concatenate process-major into the global
    batch.  Complements :func:`shard_batch`, which expects the full
    global batch on every host (fine single-process; wasteful beyond).

    Every process MUST pass the same number of rows (the global leading
    axis is ``local_rows × process_count`` — drop or pad the dataset
    tail, as DistributedSampler does); the global shape is passed
    explicitly so a disagreement fails loudly instead of assembling
    inconsistent global arrays.
    """
    sh = batch_sharding(mesh)
    n_proc = _state.process_count()

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sh, x, global_shape=(x.shape[0] * n_proc,) + x.shape[1:])

    return jax.tree_util.tree_map(put, local_batch)


def _is_cpu_mesh(mesh) -> bool:
    try:
        return mesh.devices.flat[0].platform == "cpu"
    except Exception:  # noqa: BLE001 — any exotic mesh: don't throttle
        return False


def _max_inflight_cpu() -> int:
    """In-flight step bound on CPU meshes (``HVD_TPU_MAX_INFLIGHT``,
    default 2 = dispatch step N+1 while step N executes)."""
    try:
        return max(1, int(os.environ.get("HVD_TPU_MAX_INFLIGHT", "2")))
    except ValueError:
        return 2


def _throttle_on_cpu(step_fn, mesh):
    """Bound async dispatch to a small in-flight window on CPU meshes.

    The host-platform backend (virtual devices for testing) runs every
    replica's collective on one shared thread pool; with unbounded async
    dispatch a long training loop stacks dozens of executions and the
    cross-replica rendezvous starves past XLA's 40 s abort
    (rendezvous.cc "Expected N threads to join").  Real TPU meshes are
    untouched — their pipelining is the performance model.

    The window defaults to 2 (``HVD_TPU_MAX_INFLIGHT``): calling the
    step for N+1 blocks on step N-1's outputs, so one step is always
    executing while the host dispatches the next — the pre-PR-5 hard
    per-step barrier (block on N before dispatching N+1) put a dispatch
    bubble between every pair of steps.  The blocked time is observed
    as ``host.stall_seconds``.
    """
    if not _is_cpu_mesh(mesh):
        return step_fn
    return _ThrottledStep(step_fn, _max_inflight_cpu())


class _ThrottledStep:
    """Callable wrapper keeping at most ``depth`` invocations in flight
    (see :func:`_throttle_on_cpu`); delegates the rest of the jit API
    (``lower``, ``trace``, ``clear_cache``, ...) to the wrapped step."""

    def __init__(self, step_fn, depth: int = 2):
        self._step_fn = step_fn
        self._depth = depth
        self._inflight = collections.deque()
        from ..tuning import actuation as _actuation

        _actuation.register_inflight_window(self)

    def resize(self, depth: int) -> None:
        """hvd-tune live retune: a shrink takes effect by draining down
        to the new depth on the next call — no flush here (the drain
        tick must never block on device results)."""
        self._depth = max(1, int(depth))

    def __call__(self, *args, **kw):
        while len(self._inflight) >= self._depth:
            popped = self._inflight.popleft()
            t0 = time.perf_counter()
            for leaf in jax.tree_util.tree_leaves(popped):
                # A leaf donated into a later dispatch is deleted; that
                # dispatch is ordered behind this one on every device,
                # so blocking on the surviving leaves suffices.
                deleted = getattr(leaf, "is_deleted", None)
                if deleted is not None and deleted():
                    continue
                jax.block_until_ready(leaf)
            _M_HOST_STALL.observe(time.perf_counter() - t0)
        out = self._step_fn(*args, **kw)
        self._inflight.append(out)
        return out

    def __getattr__(self, name):
        return getattr(self._step_fn, name)


class _TracedStep:
    """Per-step bookkeeping wrapper: advance the hvd-trace step id
    (trace/__init__.py) so every span carries the step that owns it,
    close the hvd-mem ledger's step window (the per-step high-watermark
    gauge), and — first call only — pre-flight-warn when the working
    set this step implies (params + gradients + optimizer slots +
    batch) exceeds the advertised HBM capacity (memory/oom.py).
    Arithmetic is untouched; the jit surface passes through like
    :class:`_ThrottledStep`'s."""

    def __init__(self, step_fn):
        self._step_fn = step_fn
        self._preflighted = False

    def _preflight(self, args) -> None:
        self._preflighted = True
        if _oom.advertised_capacity() is None or not args:
            return
        try:
            params_b = _mem.tree_nbytes(args[0])
            batch_b = _mem.tree_nbytes(args[-1]) if len(args) > 1 else 0
            # params + grads + two optimizer slots (the adam-shaped
            # upper bound) + the batch: the static working-set model
            # of docs/memory.md.
            _oom.preflight_warn(
                4 * params_b + batch_b, "make_train_step",
                f"params {params_b} B x (1 grad + 2 opt slots) + "
                f"batch {batch_b} B")
        except Exception:  # noqa: BLE001 — sizing is observability
            pass

    def __call__(self, *args, **kw):
        if _trace.trace_enabled_env():
            _trace.on_step()
        if not self._preflighted:
            self._preflight(args)
        out = self._step_fn(*args, **kw)
        if _mem.enabled():
            _mem.ledger.note_step()
        return out

    def __getattr__(self, name):
        return getattr(self._step_fn, name)


def _traced(step_fn):
    return _TracedStep(step_fn)


def _make_step(loss_fn, optimizer, mesh, average, fusion_threshold,
               has_aux, donate, has_state, op=None, overlap=None):
    """Shared builder behind :func:`make_train_step` and
    :func:`make_train_step_with_state` — one place wires the reduction,
    pmean placement, shard_map specs and donation for both variants.

    ``overlap`` (default: the ``HVD_TPU_OVERLAP`` env knob) selects the
    backward/communication-overlap schedule (parallel/overlap.py):
    ``off`` keeps this monolithic single-program step; ``on``/``serial``
    build the bucketed-backward path whose gradient buckets ride the
    dynamic megakernel executor per bucket.
    """
    from . import overlap as _overlap
    from .data import _resolve_grad_op

    mesh = mesh or _state.mesh()

    compression = None
    if isinstance(optimizer, DistributedOptimizer):
        average = optimizer._average
        if op is None:
            op = optimizer._op
        if optimizer._fusion_threshold is not None:
            fusion_threshold = optimizer._fusion_threshold
        compression = optimizer._compression
        optimizer = optimizer._inner

    schedule = _overlap.resolve_mode(overlap, mesh)
    red_op = _resolve_grad_op(average, op)
    # Adasum never overlaps (its scale-insensitive combination is
    # defined on the WHOLE gradient vector) — but the overlap builder
    # owns that decision now, so the fallback is warned, counted
    # (overlap.fallbacks) and flight-recorded under its name like
    # every other unbucketable case.
    if schedule != "off":
        inner_optimizer = optimizer

        def fallback_builder():
            return _build_static_step(loss_fn, inner_optimizer, mesh,
                                      average, fusion_threshold, has_aux,
                                      donate, has_state, op, compression)

        step = _overlap.make_overlapped_step(
            loss_fn, optimizer, mesh, red_op, fusion_threshold, has_aux,
            donate, has_state, compression, stream=schedule == "stream",
            fallback_builder=fallback_builder)
        return _traced(_throttle_on_cpu(step, mesh))
    return _traced(_build_static_step(loss_fn, optimizer, mesh, average,
                                      fusion_threshold, has_aux, donate,
                                      has_state, op, compression))


def _build_static_step(loss_fn, optimizer, mesh, average, fusion_threshold,
                       has_aux, donate, has_state, op, compression):
    """The pre-overlap monolithic step: forward + backward + in-program
    bucketed reduction + optimizer apply compiled as ONE SPMD program
    (exactly what ``HVD_TPU_OVERLAP=off`` must restore)."""
    # The stateful loss returns (loss, new_state) — an aux output.
    grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux or has_state)

    def per_replica(params, model_state, batch):
        args = (params, model_state, batch) if has_state else (params, batch)
        out, grads = grad_fn(*args)
        loss = out[0] if (has_aux or has_state) else out
        aux = out[1] if (has_aux or has_state) else None
        # Fused cross-replica gradient reduction (Tensor Fusion over psum;
        # op=Adasum swaps in the whole-gradient ppermute ladder).
        grads = allreduce_gradients(grads, average=average,
                                    fusion_threshold=fusion_threshold,
                                    compression=compression, op=op)
        # Report the global mean loss, like MetricAverageCallback would
        # (keras/callbacks.py:37-87).  Aux outputs — metrics, or the
        # updated BatchNorm statistics in the stateful variant — are
        # averaged the same way; for BN stats this is synchronized
        # BatchNorm riding the same compiled collective schedule as the
        # gradients (the reference instead leaves stats per-worker and
        # relies on rank-0 checkpointing, README.md:102-104).
        loss = jax.lax.pmean(loss, REPLICA_AXIS)
        aux = jax.tree_util.tree_map(
            lambda x: jax.lax.pmean(x, REPLICA_AXIS), aux)
        return loss, grads, aux

    sharded = _compat.shard_map(
        per_replica, mesh=mesh,
        in_specs=(P(), P(), P(REPLICA_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False)

    def apply(grads, opt_state, params):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    if has_state:
        def step(params, model_state, opt_state, batch):
            loss, grads, model_state = sharded(params, model_state, batch)
            params, opt_state = apply(grads, opt_state, params)
            return params, model_state, opt_state, loss

        donate_argnums = (0, 1, 2) if donate else ()
    else:
        def step(params, opt_state, batch):
            loss, grads, aux = sharded(params, None, batch)
            params, opt_state = apply(grads, opt_state, params)
            if has_aux:
                return params, opt_state, loss, aux
            return params, opt_state, loss

        donate_argnums = (0, 1) if donate else ()
    return _throttle_on_cpu(jax.jit(step, donate_argnums=donate_argnums),
                            mesh)


def make_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    average: bool = True,
    fusion_threshold: Optional[int] = None,
    has_aux: bool = False,
    donate: bool = True,
    op=None,
    overlap: Optional[str] = None,
):
    """Build the jitted data-parallel train step.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` (or ``(scalar, aux)``
        with ``has_aux=True``).  Called per replica on the local shard.
        A :class:`~horovod_tpu.parallel.overlap.ChainedLoss` additionally
        lets the overlap mode segment the backward pass per stage.
      optimizer: an optax ``GradientTransformation`` or a
        :class:`DistributedOptimizer` (unwrapped — its averaging flags are
        honored; reduction happens once, inside the replica context).
      mesh: replica mesh; defaults to the global one from ``init()``.
      average: average (True) or sum (False) gradients across replicas.
      fusion_threshold: Tensor-Fusion bucket size in bytes; defaults to
        ``HOROVOD_FUSION_THRESHOLD`` (64 MB).  This is more than a
        wire-packing knob: under the overlap mode the SAME partition
        sets the dispatch-boundary granularity (each bucket = one
        megakernel streamed out of the backward pass,
        docs/performance.md).  ``op=Adasum`` ignores it entirely — the
        whole-gradient combination neither buckets nor overlaps.
      op: hvd.Average/Sum/Adasum (supersedes ``average``); Adasum compiles
        the whole-gradient ppermute ladder into the step.
      overlap: backward/communication-overlap schedule —
        ``auto``/``on``/``off``/``serial``; defaults to the
        ``HVD_TPU_OVERLAP`` env knob (parallel/overlap.py).

    Returns:
      ``step(params, opt_state, batch) -> (params, opt_state, loss[, aux])``
      — one compiled SPMD program (overlap off), or the bucketed-backward
      sub-program pipeline with bitwise-identical results (overlap on);
      batch's leading axis must be divisible by the replica count.
    """
    return _make_step(loss_fn, optimizer, mesh, average, fusion_threshold,
                      has_aux, donate, has_state=False, op=op,
                      overlap=overlap)


def make_train_step_with_state(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    average: bool = True,
    fusion_threshold: Optional[int] = None,
    donate: bool = True,
    op=None,
    overlap: Optional[str] = None,
):
    """Train-step builder for models carrying non-trained state (BatchNorm
    statistics): ``loss_fn(params, model_state, batch) -> (loss, new_state)``;
    the updated statistics are ``pmean``-ed every step (synchronized
    BatchNorm).  ``fusion_threshold`` and ``overlap`` behave exactly as
    in :func:`make_train_step` (the stateful variant overlaps through
    the single-backward streaming schedule).

    Returns ``step(params, model_state, opt_state, batch) ->
    (params, model_state, opt_state, loss)``.
    """
    return _make_step(loss_fn, optimizer, mesh, average, fusion_threshold,
                      has_aux=False, donate=donate, has_state=True, op=op,
                      overlap=overlap)


def make_parallel_train_step(loss_fn: Callable[..., Any], optimizer,
                             mesh, batch_spec, donate: bool = True):
    """Train-step builder for multi-axis (dp/tp/sp/pp/ep) parallelism.

    ``loss_fn(params, batch)`` is a *local-shard* loss (e.g. from
    ``models.transformer.make_loss_fn``) that pmean-reduces itself over
    every mesh axis, so the shard_map output is a replicated logical
    scalar and ``jax.grad`` outside the shard_map produces exact global
    gradients (the replicated-parameter transpose inserts the psum — no
    manual gradient reduction step, unlike the 1-axis DP builders above).

    ``batch_spec`` is the PartitionSpec (or pytree of specs) describing
    how the host batch is laid out over the mesh.
    """
    sharded_loss = _compat.shard_map(
        loss_fn, mesh=mesh, in_specs=(P(), batch_spec), out_specs=P(),
        check_vma=False)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(sharded_loss)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return _traced(_throttle_on_cpu(
        jax.jit(step, donate_argnums=donate_argnums), mesh))


def shard_parallel_batch(batch, mesh, batch_spec):
    """Place a host batch onto a multi-axis mesh per ``batch_spec``
    (a PartitionSpec, or a pytree of specs matching ``batch``) — one
    batched ``jax.device_put`` over the whole pytree, preserving the
    per-leaf shardings (same single-transfer contract as
    :func:`shard_batch`)."""
    from .input import device_put_batch

    return device_put_batch(batch, mesh, sharding=batch_spec)


# ---------------------------------------------------------------------------
# Completion fencing for the async-dispatch loop
# ---------------------------------------------------------------------------

@partial(jax.jit, donate_argnums=())
def _fence_program(x):
    return x + 1


def barrier_fence(*trees) -> None:
    """Block the host until previously dispatched device work completes.

    The async-dispatch loop (hvd-pipeline) returns un-fetched device
    arrays and defers metric fetches, so the Python loop runs ahead of
    the hardware.  Code that needs a completion point — wall-clock
    measurement, checkpoint-consistent reads, handing buffers to
    non-JAX code — calls this fence:

    * ``barrier_fence(tree, ...)`` blocks until every leaf of the given
      pytrees is computed (``jax.block_until_ready``).
    * ``barrier_fence()`` blocks until EVERY local device of the replica
      mesh has drained its execution stream: a trivial program is
      dispatched per device behind all queued work and blocked on
      (per-device programs execute in dispatch order).

    Host-side only — no collective, no control-plane traffic (unlike
    ``hvd.barrier()``, which synchronizes *ranks*).  The blocked time is
    recorded in ``host.stall_seconds``.
    """
    t0 = time.perf_counter()
    if trees:
        for t in trees:
            jax.block_until_ready(t)
    else:
        if _state.is_initialized():
            devices = [d for d in _state.global_state().devices
                       if d.process_index == jax.process_index()]
        else:
            devices = jax.local_devices()
        probes = [_fence_program(jax.device_put(jnp.zeros((), jnp.int32), d))
                  for d in devices]
        for p in probes:
            jax.block_until_ready(p)
    _M_HOST_STALL.observe(time.perf_counter() - t0)


def make_eval_step(metric_fn: Callable[..., Any], mesh=None):
    """Build a jitted eval step: per-replica metrics averaged across the
    mesh (≙ MetricAverageCallback's end-of-epoch allreduce,
    keras/callbacks.py:37-87)."""
    mesh = mesh or _state.mesh()

    def per_replica(params, batch):
        m = metric_fn(params, batch)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.pmean(x, REPLICA_AXIS), m)

    sharded = _compat.shard_map(
        per_replica, mesh=mesh, in_specs=(P(), P(REPLICA_AXIS)),
        out_specs=P(), check_vma=False)
    return _throttle_on_cpu(jax.jit(sharded), mesh)
