"""Backward/communication overlap: bucketed-backward training path.

The monolithic train step (``parallel/training.py``) runs
``jax.value_and_grad`` to completion and hands the WHOLE gradient pytree
to one in-program reduction — every collective fires only after the
backward pass has materialized every gradient.  Overlapping the
reduction of layer N's gradients with the backward compute of layer N-1
is the original Horovod throughput story (arXiv:1802.05799) and the
core of fused computation-collective scheduling (arXiv:2305.06942).

This module is that overlap, built on the repo's own steady-state
machinery instead of a new runtime:

* **Bucket plan** — gradient leaves partition into dtype/size buckets
  with the SAME greedy rule as the static path's wire packing
  (:func:`.data.partition_fusion_buckets`, bounded by the coordinator's
  fusion threshold), so each bucket is exactly one coordinator fusion
  group: one pack→reduce→unpack megakernel launch (PR 3), one response
  cache entry group replayed without negotiation (PR 2), one
  error-feedback residual key under quantized wire formats (PR 6).
* **Segmented backward** — when the loss is a :class:`ChainedLoss`
  (a sequence of stages), the step compiles one forward program that
  saves the stage-boundary activations plus one backward program per
  stage (``jax.vjp`` with in-segment rematerialization — the
  ``jax.checkpoint`` decomposition made explicit so the host owns the
  segment boundaries).  Each stage's gradient buckets are handed to the
  dynamic reduction path the moment that stage's backward program is
  *dispatched* — reduce-of-bucket-K pipelines under
  backward-of-bucket-K+1 in the device stream, and the per-bucket
  control plane (negotiation on step one, cache replay after) runs on
  the host while the device is still inside earlier backward segments.
  A plain callable loss keeps one backward program and streams its
  buckets afterwards (control-plane + apply overlap only).
* **Partial cycles** — a training step is now a SEQUENCE of per-bucket
  sub-programs, not one fused cycle.  The response cache needed no
  schema change for this: entries are per-tensor and ``take_ready``
  replays whatever subset is fully hit, so each bucket replays as its
  own fusion plan (memoized per bucket).  Each bucket's submission is
  made atomic against the 5 ms background drain tick
  (``collective._drain_lock``) so a tick can never split one bucket
  into two fused responses — the per-bucket launch count, and under
  int8/int4 the per-bucket quantization blocks and EF residual keys,
  stay deterministic.

**Bitwise contract** (tested in tests/test_overlap.py and gated by
``bench.py --mode overlap``): with full-precision wire formats the
overlapped step's parameters are bitwise identical to the monolithic
``HVD_TPU_OVERLAP=off`` step after any number of steps — the segmented
VJP chain is the same jaxpr AD produces, and the megakernel's flat
psum is the same reduction the in-program bucketed psum runs.  Under
quantized wire formats (``HVD_TPU_COMPRESSION=int8``/``int4``) the
monolithic static path does not quantize at all, so the comparator is
the ``serial`` schedule: the SAME per-bucket sub-programs dispatched
strictly after the full backward (same bucket partition ⇒ same
pow2-scale blocks, same stochastic-rounding ticks, same per-bucket EF
residual keys ⇒ bitwise-identical parameters).

Env contract (docs/performance.md, validated at ``hvd.init`` and
carried in the control-plane HELLO env fingerprint like the
compression/topology knobs — the knob selects which compiled programs
a rank runs, so it must be uniform fleet-wide):

  HVD_TPU_OVERLAP=auto|on|off|serial
      auto (default): overlap on real accelerator meshes with >1
      replica; off on CPU/virtual-device meshes (where the
      single-program static step is already optimal and tests pin
      behavior explicitly).
      on: bucketed-backward streaming dispatch.
      serial: the same bucketed sub-programs with hard fences —
      reduction strictly after backward (the measurement/identity
      comparator; what a non-overlapped dynamic path would do).
      off: the pre-overlap monolithic static step, unchanged.

Scope: single-process (single-controller SPMD) AND multi-process
builds.  Multi-process negotiation runs at process granularity with
process-local contributions, and the overlapped mp step rides exactly
that contract: the forward/backward programs are the same global-mesh
SPMD programs the single-process schedule compiles, and each bucket's
fusion group is submitted as this process's LOCAL gradient rows —
negotiated over the TCP control plane as a partial cycle (one
coalesced request frame per bucket, atomic against the drain tick),
replayed per-tensor from the response cache on the steady state, and
executed by the mp megakernel (one donated reduce+unpack over the
process mesh per bucket).  ``take_async`` waits for the broadcast
response (control plane) but NOT for device completion, so the
optimizer apply consumes in-flight reductions exactly like
single-process.  The mp overlapped step is bitwise-identical to the
monolithic mp step for the same reason the sp one is: same backward
jaxprs, and the per-bucket psum over the process mesh reduces the
same contributions the in-program psum reduces.

Named fallbacks (each warns once, increments ``overlap.fallbacks``
and flight-records an ``overlap_fallback`` event carrying the
reason): ``adasum`` (whole-gradient by definition), ``sparse``
(IndexedSlices leaves ship a negotiated-size payload the bucket
planner cannot size), ``sub-mesh`` (a subset mesh must keep its
in-program reduction), ``mp-local-replicas`` (a process holding >1
local replica has no per-process contribution the mp data plane can
carry), ``mp-mesh-order`` (process-mesh/global-mesh device order
skew), ``grad-tree`` and ``nonstatic-compression``.  Plain
multi-process mode is NOT a fallback anymore.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import telemetry as _telemetry
from ..core import compat as _compat
from ..core import state as _state
from ..core.state import REPLICA_AXIS
from ..ops import collective as C
from ..ops.wire import ReduceOp
from .data import _fusion_threshold_bytes, partition_fusion_buckets

try:
    import optax
except Exception:  # pragma: no cover - optax is baked into the image
    optax = None

OVERLAP_ENV = "HVD_TPU_OVERLAP"
_VALID_MODES = ("auto", "on", "off", "serial")

# hvd-telemetry (docs/metrics.md "Backward/communication overlap").
_M_BUCKETS = _telemetry.counter(
    "overlap.buckets_dispatched",
    "gradient buckets handed to the dynamic reduction path")
_M_MP_BUCKETS = _telemetry.counter(
    "overlap.mp_buckets_dispatched",
    "gradient buckets negotiated as multi-process partial cycles "
    "(subset of overlap.buckets_dispatched)")
_M_FALLBACKS = _telemetry.counter(
    "overlap.fallbacks",
    "overlap-mode steps that fell back to the monolithic path")
_M_EXPOSED = _telemetry.histogram(
    "overlap.exposed_comm_seconds", "seconds",
    "host seconds completing bucket reductions after every backward "
    "segment was dispatched — reduction work NOT hidden under backward")
# Same registry entry as parallel/training.py / parallel/input.py: every
# place the loop blocks feeds one histogram.
_M_HOST_STALL = _telemetry.histogram(
    "host.stall_seconds", "seconds",
    "time the training loop blocked waiting on the input queue")


def overlap_mode() -> str:
    """Normalized ``HVD_TPU_OVERLAP`` value (``1``/``0`` accepted as
    on/off aliases, like the other runtime gates)."""
    v = os.environ.get(OVERLAP_ENV, "auto").strip().lower()
    if v == "1":
        return "on"
    if v == "0":
        return "off"
    return v or "auto"


def validate_env() -> None:
    """Fail ``hvd.init()`` — not the first training step — on a
    malformed overlap knob (same contract as the compression/topology
    knobs; cross-rank uniformity is checked by the HELLO env
    fingerprint, ops/transport.py)."""
    v = os.environ.get(OVERLAP_ENV)
    if v and overlap_mode() not in _VALID_MODES:
        raise ValueError(
            f"{OVERLAP_ENV}={v!r}: expected one of "
            f"{'|'.join(_VALID_MODES)} (1/0 alias on/off)")


def resolve_mode(override: Optional[str], mesh) -> str:
    """Resolve the step builder's overlap schedule: ``"stream"``,
    ``"serial"`` or ``"off"``.  ``auto`` enables streaming only on real
    accelerator meshes with more than one replica — on CPU/virtual
    meshes the monolithic single-program step is already optimal and
    the dynamic path's per-bucket control plane would be pure cost."""
    mode = (override or overlap_mode()).strip().lower()
    if mode == "1":
        mode = "on"
    elif mode == "0":
        mode = "off"
    if mode not in _VALID_MODES:
        raise ValueError(
            f"overlap={mode!r}: expected one of {'|'.join(_VALID_MODES)}")
    if mode == "auto":
        try:
            devs = list(mesh.devices.flat)
            if len(devs) < 2 or devs[0].platform == "cpu":
                return "off"
        except Exception:  # noqa: BLE001 — exotic mesh: stay monolithic
            return "off"
        return "stream"
    if mode == "on":
        return "stream"
    return mode  # "off" | "serial"


@jax.custom_vjp
def stage_boundary(carry):
    """Bucket-boundary marker: an identity whose forward AND cotangent
    materialize at an ``optimization_barrier`` — the custom_vjp boundary
    the overlap schedule cuts the backward at.  In the monolithic
    evaluation it reproduces exactly the materialization points the
    segmented schedule gets for free from its program boundaries
    (without it, XLA fuses stage K+1's cotangent into stage K's
    gradient contractions and drifts a ULP from the per-program
    backward — the bitwise on≡off contract would break).  jax 0.4.37's
    ``optimization_barrier`` has no AD rule, so the custom_vjp supplies
    the (linear, self-transpose) differentiation."""
    return jax.lax.optimization_barrier(carry)


def _stage_boundary_fwd(carry):
    return stage_boundary(carry), None


def _stage_boundary_bwd(_res, ct):
    return (jax.lax.optimization_barrier(ct),)


stage_boundary.defvjp(_stage_boundary_fwd, _stage_boundary_bwd)


class ChainedLoss:
    """Sequentially staged loss — the segmentable form the overlap path
    streams buckets out of.

    ``stages`` is a sequence of ``stage(stage_params, carry, batch)``
    functions: stage 0 receives ``carry=None`` and builds the first
    activation from ``batch``; every later stage maps its predecessor's
    carry (a batch-leading array or pytree of them) to its own; the
    LAST stage returns the scalar per-replica loss.  ``params`` passed
    to the step must be a sequence with one entry (an arbitrary pytree)
    per stage.

    Calling the object evaluates the chain monolithically — exactly
    what the ``HVD_TPU_OVERLAP=off`` step differentiates — with each
    stage wrapped in ``jax.checkpoint``.  The checkpointing is
    load-bearing for the bitwise contract, not just a memory policy:
    the segmented backward programs rematerialize their stage's forward
    from the boundary carry (that is what makes per-stage backward
    programs possible), and XLA:CPU contracts a *saved* activation
    against a cotangent with different fusion decisions than a
    *recomputed* one — observed as 1-ULP drift in ``wo``/``w_out``-style
    gradients.  Checkpointing the monolithic evaluation gives both
    schedules the identical per-stage backward jaxpr, so
    ``HVD_TPU_OVERLAP=on`` ≡ ``off`` holds bitwise.
    """

    def __init__(self, stages: Sequence[Callable]):
        self.stages = list(stages)
        if not self.stages:
            raise ValueError("ChainedLoss needs at least one stage")

    def _check_params(self, params) -> list:
        if not isinstance(params, (list, tuple)) \
                or len(params) != len(self.stages):
            raise ValueError(
                f"ChainedLoss expects params as a sequence with one "
                f"entry per stage ({len(self.stages)}); got "
                f"{type(params).__name__} of length "
                f"{len(params) if isinstance(params, (list, tuple)) else 'n/a'}")
        return list(params)

    def __call__(self, params, batch):
        params = self._check_params(params)
        carry = None
        for i, (f, p) in enumerate(zip(self.stages, params)):
            if i:
                carry = stage_boundary(carry)
            # The params boundary materializes each stage's GRADIENTS
            # at the stage cut (its transpose barriers the param
            # cotangents) — in the segmented schedule they are program
            # outputs, i.e. materialized buffers, and the monolithic
            # backward must pin the same layout to stay bitwise.
            carry = jax.checkpoint(f)(stage_boundary(p), carry, batch)
        return carry


# ---------------------------------------------------------------------------
# Bucket plan
# ---------------------------------------------------------------------------

@dataclass
class _Bucket:
    gi: int                 # global bucket index (stable wire names)
    local_pos: List[int]    # positions within the segment's leaf list
    global_idx: List[int]   # positions within the full flattened tree
    nbytes: int


@dataclass
class _Segment:
    buckets: List[_Bucket] = field(default_factory=list)


@dataclass
class _Plan:
    threshold: int
    segments: List[_Segment]
    n_leaves: int

    @property
    def n_buckets(self) -> int:
        return sum(len(s.buckets) for s in self.segments)


def _build_plan(seg_leaf_avals: List[List[Any]], threshold: int) -> _Plan:
    """Partition each segment's (wire-dtype) leaf avals into dispatch
    buckets with the shared fusion rule.  Buckets never span segments —
    a bucket dispatches the moment its segment's cotangents exist."""
    segments: List[_Segment] = []
    gi = 0
    offset = 0
    for avals in seg_leaf_avals:
        seg = _Segment()
        for local in partition_fusion_buckets(avals, threshold):
            nbytes = sum(
                (int(np.prod(avals[p].shape, dtype=np.int64))
                 if avals[p].shape else 1)
                * jnp.dtype(avals[p].dtype).itemsize for p in local)
            seg.buckets.append(_Bucket(
                gi=gi, local_pos=list(local),
                global_idx=[offset + p for p in local], nbytes=nbytes))
            gi += 1
        segments.append(seg)
        offset += len(avals)
    return _Plan(threshold=threshold, segments=segments, n_leaves=offset)


# ---------------------------------------------------------------------------
# CPU in-flight window (intra-step analogue of training._ThrottledStep)
# ---------------------------------------------------------------------------

def _max_inflight() -> int:
    try:
        return max(1, int(os.environ.get("HVD_TPU_MAX_INFLIGHT", "2")))
    except ValueError:
        return 2


class _InflightWindow:
    """Bound the overlapped step's in-flight sub-programs on CPU meshes
    (same rendezvous-starvation rationale as ``_throttle_on_cpu``:
    the host-platform backend runs every replica's collective on one
    shared pool; stacking unbounded dispatches starves the rendezvous).
    Real TPU meshes never construct one — their pipelining is the
    performance model."""

    def __init__(self, depth: int):
        self._depth = depth
        self._q: collections.deque = collections.deque()
        from ..tuning import actuation as _actuation

        _actuation.register_inflight_window(self)

    def resize(self, depth: int) -> None:
        """hvd-tune live retune: a shrink drains down to the new depth
        on the next ``admit`` — no flush here (the drain tick must never
        block on device results)."""
        self._depth = max(1, int(depth))

    def admit(self, tree) -> None:
        self._q.append(tree)
        while len(self._q) > self._depth:
            popped = self._q.popleft()
            t0 = time.perf_counter()
            for leaf in jax.tree_util.tree_leaves(popped):
                # A leaf donated into a later dispatch is deleted; that
                # dispatch is ordered behind this one on every device,
                # so blocking on the surviving leaves suffices.
                deleted = getattr(leaf, "is_deleted", None)
                if deleted is not None and deleted():
                    continue
                jax.block_until_ready(leaf)
            _M_HOST_STALL.observe(time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# Partial-cycle dispatch (shared with parallel/pipeline.py)
# ---------------------------------------------------------------------------

def dispatch_bucket_segment(prefix: str, seg: _Segment, seg_leaves: List,
                            handles: List[Optional[int]], tl,
                            mp: bool = False) -> None:
    """Hand one gradient segment's buckets to the dynamic path.
    Submission is atomic against the background drain tick, and the
    explicit drain right after dispatches each bucket's megakernel
    immediately — before the next (earlier) backward segment.  The
    1F1B pipeline schedule (parallel/pipeline.py) streams each stage's
    buckets through this same choreography the moment that stage's
    last microbatch backward is dispatched.

    Multi-process: each leaf's contribution is this process's LOCAL
    row of the per-replica gradient (``addressable_data(0)`` — a
    zero-copy view of the shard this process computed; the
    ``mp-local-replicas`` guard pinned one replica per process).  The
    bucket's requests buffer under the drain lock and the drain
    flushes them as ONE coalesced control frame — the partial cycle
    the coordinator negotiates (and, on the steady state, the
    response cache replays) independently of the other buckets still
    inside the backward.  Inputs are not declared donated in mp: the
    local rows share their buffers with the live global gradient
    arrays, and the mp executor's local pack copies them into the
    fusion buffer anyway."""
    for b in seg.buckets:
        tensors = [seg_leaves[p] for p in b.local_pos]
        base = f"{prefix}.g{b.gi}"
        if mp:
            tensors = [t.addressable_data(0) for t in tensors]
        with C._drain_lock:
            hs = C.grouped_allreduce_async(
                tensors, op=ReduceOp.SUM, name=base,
                donate_inputs=not mp)
        C._drain()
        for idx, h in zip(b.global_idx, hs):
            handles[idx] = h
        _M_BUCKETS.inc()
        if mp:
            _M_MP_BUCKETS.inc()
        if tl is not None:
            tl.instant(base, "BUCKET_DISPATCH",
                       args={"bucket": b.gi, "tensors": len(hs),
                             "bytes": b.nbytes})


# ---------------------------------------------------------------------------
# The overlapped step
# ---------------------------------------------------------------------------

_prefix_lock = threading.Lock()
_prefix_counter = 0


def _next_prefix() -> str:
    """Stable per-builder wire-name prefix.  Collective names must be
    identical across steps (the response-cache key) and unique across
    step builders in one process; construction order is part of the
    SPMD program and — like every compiled-program knob — must match
    across ranks: a multi-process build's bucket names negotiate over
    the control plane, so every rank must construct its overlapped
    steps in the same order (user training scripts are SPMD, so they
    do; a divergence is caught by the coordinator's name/shape
    mismatch diagnostics on the first step)."""
    global _prefix_counter
    with _prefix_lock:
        _prefix_counter += 1
        return f"overlap.p{_prefix_counter}"


def _is_cpu_mesh(mesh) -> bool:
    try:
        return mesh.devices.flat[0].platform == "cpu"
    except Exception:  # noqa: BLE001 — exotic mesh: no throttle
        return False


class _OverlapStep:
    """The bucketed-backward train step: a host-driven sequence of
    compiled sub-programs (forward / per-segment backward / per-bucket
    megakernel reduction / optimizer apply) replacing the single jitted
    program of the monolithic path.  Drop-in call signature; builds its
    programs and bucket plan lazily on the first call (the fallback
    checks need concrete trees) and re-plans when the fusion threshold
    changes (the same event that flushes the coordinator's fusion-plan
    memo and the megakernel cache)."""

    def __init__(self, loss_fn, optimizer, mesh, red_op: ReduceOp,
                 fusion_threshold: Optional[int], has_aux: bool,
                 donate: bool, has_state: bool, compression,
                 stream: bool, fallback_builder: Callable[[], Callable]):
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._mesh = mesh
        self._red_op = red_op
        self._fusion_threshold = fusion_threshold
        self._has_aux = has_aux
        self._donate = donate
        self._has_state = has_state
        self._compression = compression
        self._stream = stream
        self._fallback_builder = fallback_builder
        self._prefix = _next_prefix()
        self._cpu_mesh = _is_cpu_mesh(mesh)
        self._built = False
        self._fallback_step: Optional[Callable] = None
        self._fallback_reason: Optional[str] = None
        self._plan: Optional[_Plan] = None
        self._segmented = False
        self._mp = False
        self._treedef = None
        self._ctxs: Optional[list] = None  # per-leaf decompress contexts

    # -- introspection (tests / bench) ------------------------------------
    @property
    def overlap_active(self) -> bool:
        return self._fallback_step is None

    @property
    def schedule(self) -> str:
        return "stream" if self._stream else "serial"

    @property
    def bucket_count(self) -> Optional[int]:
        return None if self._plan is None else self._plan.n_buckets

    @property
    def segment_count(self) -> Optional[int]:
        return None if self._plan is None else len(self._plan.segments)

    # -- fallback ----------------------------------------------------------
    def _fall_back(self, reason: str, detail: str):
        """Build the monolithic step instead, leaving the standard
        triple-entry record — one warn line, one ``overlap.fallbacks``
        counter tick and one ``overlap_fallback`` flight event, all
        carrying the NAMED reason (tests assert the lockstep)."""
        print(f"[hvd-overlap] falling back to the monolithic step "
              f"[{reason}]: {detail}", file=sys.stderr)
        _M_FALLBACKS.inc()
        _telemetry.overlap_fallback_event(reason, detail)
        self._fallback_reason = reason
        self._fallback_step = self._fallback_builder()
        return self._fallback_step

    # -- plan / program construction --------------------------------------
    def _effective_threshold(self) -> int:
        """The dispatch-boundary granularity: the step's explicit
        threshold clamped by the coordinator's live one — the
        coordinator's fusion planner packs replayed cycles with ITS
        threshold, so a bucket must never exceed it (it would split
        into two launches and, under quantized formats, re-partition
        the scaling blocks).  Multi-process builds use the state's
        threshold instead: the live coordinator value is rank-0-only
        knowledge, while ``st.fusion_threshold_bytes`` starts from the
        (env-fingerprinted) HOROVOD_FUSION_THRESHOLD and is updated by
        the same fleet-wide hook that retunes the coordinators — the
        bucket partition must be identical on every rank (it is the
        collective program)."""
        st = _state.global_state()
        if st.multiprocess:
            coord = int(st.fusion_threshold_bytes)
        else:
            try:
                coord = int(st.coordinator.fusion_threshold)
            except Exception:  # noqa: BLE001 — no coordinator (size checks)
                coord = _fusion_threshold_bytes()
        if self._fusion_threshold is None:
            return coord
        return min(int(self._fusion_threshold), coord)

    def _wire_aval(self, leaf) -> SimpleNamespace:
        """(shape, WIRE dtype) of one gradient leaf — buckets group by
        the compressed dtype, like the static path's narrow-end-to-end
        packing.  Also records the per-leaf decompress context."""
        dtype = jnp.dtype(leaf.dtype)
        if self._compression is None:
            self._ctxs.append(None)
            return SimpleNamespace(shape=tuple(leaf.shape), dtype=dtype)
        wire, ctx = self._compression.compress(jnp.zeros((1,), dtype))
        if isinstance(ctx, jax.Array):
            raise _NonStaticContext()
        self._ctxs.append(ctx)
        return SimpleNamespace(shape=tuple(leaf.shape),
                               dtype=jnp.dtype(wire.dtype))

    def _compress_tree(self, grads):
        comp = self._compression
        if comp is None:
            return grads
        return jax.tree_util.tree_map(lambda g: comp.compress(g)[0], grads)

    def _build(self, args) -> None:
        self._built = True
        st = _state.global_state()
        if self._red_op == ReduceOp.ADASUM:
            # Adasum never overlaps: its scale-insensitive combination
            # is defined on the WHOLE gradient vector — there is no
            # per-bucket decomposition to stream.
            self._fall_back(
                "adasum",
                "op=Adasum combines the whole gradient vector; no "
                "per-bucket decomposition exists")
            return
        if tuple(self._mesh.devices.flat) != tuple(st.devices):
            self._fall_back(
                "sub-mesh",
                "step mesh is not the global replica mesh; a subset "
                "mesh keeps its in-program reduction")
            return
        self._mp = bool(st.multiprocess)
        if self._mp:
            if st.size != st.process_count:
                # The mp data plane carries exactly ONE contribution
                # per process (ops/collective._mp_global); a process
                # holding several local replicas would need a local
                # pre-reduction the bitwise contract cannot absorb.
                self._fall_back(
                    "mp-local-replicas",
                    f"{st.size} replicas over {st.process_count} "
                    f"processes; the mp data plane reduces one "
                    f"contribution per process")
                return
            mp_mesh = C._mp_kernels()[0]
            if tuple(mp_mesh.devices.flat) != tuple(
                    self._mesh.devices.flat):
                # The reduced buckets come back committed to the
                # process mesh; the apply program runs over the global
                # mesh — they must agree on device order or XLA
                # rejects the mixed device assignment.
                self._fall_back(
                    "mp-mesh-order",
                    "process-mesh device order differs from the "
                    "global replica mesh")
                return
        if self._has_state:
            params, model_state, _opt_state, batch = args
        else:
            (params, _opt_state, batch), model_state = args, None

        self._ctxs = []
        try:
            if (isinstance(self._loss_fn, ChainedLoss)
                    and len(self._loss_fn.stages) >= 2
                    and not self._has_aux and not self._has_state):
                self._build_segmented(params, batch)
            else:
                self._build_unsegmented(params, model_state, batch)
        except _Unbucketable as e:
            self._fall_back(e.reason, str(e))
            return
        except _NonStaticContext:
            self._fall_back(
                "nonstatic-compression",
                "compression context is value-dependent; the decompress "
                "cannot move to a separate apply program")
            return
        self._apply = self._build_apply()

    def _build_unsegmented(self, params, model_state, batch) -> None:
        has_aux, has_state = self._has_aux, self._has_state
        grad_fn = jax.value_and_grad(self._loss_fn,
                                     has_aux=has_aux or has_state)
        self._detect_sparse(grad_fn, params, model_state, batch)
        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        avals = [self._wire_aval(leaf) for leaf in leaves]
        self._leaf_avals = avals
        self._seg_sizes = [len(avals)]
        self._plan = _build_plan([avals], self._effective_threshold())
        self._segmented = False

        def per_replica(params, model_state, batch):
            a = (params, model_state, batch) if has_state \
                else (params, batch)
            out, grads = grad_fn(*a)
            loss = out[0] if (has_aux or has_state) else out
            extra = out[1] if (has_aux or has_state) else None
            grads = self._compress_tree(grads)
            # Report the global mean loss (and pmean aux/state), exactly
            # like the monolithic per_replica.
            loss = jax.lax.pmean(loss, REPLICA_AXIS)
            extra = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, REPLICA_AXIS), extra)
            grads = jax.tree_util.tree_map(lambda g: g[None], grads)
            return loss, grads, extra

        self._grads_program = jax.jit(_compat.shard_map(
            per_replica, mesh=self._mesh,
            in_specs=(P(), P(), P(REPLICA_AXIS)),
            out_specs=(P(), P(REPLICA_AXIS), P()), check_vma=False))

    def _detect_sparse(self, grad_fn, params, model_state, batch) -> None:
        """Best-effort trace-time structure probe: IndexedSlices leaves
        (or a grads tree that is not the params tree) cannot bucket —
        their wire payload is negotiated per step.  A loss that cannot
        be abstractly evaluated outside the replica context is assumed
        dense (standard AD cotangents are)."""
        from ..ops.sparse import IndexedSlices

        try:
            a = (params, model_state, batch) if self._has_state \
                else (params, batch)
            out = jax.eval_shape(grad_fn, *a)
        except Exception:  # noqa: BLE001 — collectives in the loss etc.
            return
        grads = out[1]
        flat, tdef = jax.tree_util.tree_flatten(
            grads, is_leaf=lambda g: isinstance(g, IndexedSlices))
        if any(isinstance(g, IndexedSlices) for g in flat):
            raise _Unbucketable(
                "sparse",
                "sparse (IndexedSlices) gradient leaves ship a "
                "negotiated-size payload the bucket planner cannot size")
        if tdef != jax.tree_util.tree_structure(params):
            raise _Unbucketable(
                "grad-tree",
                "gradient tree structure differs from the params tree")

    def _build_segmented(self, params, batch) -> None:
        chain: ChainedLoss = self._loss_fn
        params = chain._check_params(params)
        stages = chain.stages
        S = len(stages)
        self._segmented = True
        leaves, self._treedef = jax.tree_util.tree_flatten(list(params))
        seg_avals: List[List[Any]] = []
        for p in params:
            seg_avals.append([self._wire_aval(leaf)
                              for leaf in jax.tree_util.tree_leaves(p)])
        self._leaf_avals = [a for avals in seg_avals for a in avals]
        self._seg_sizes = [len(a) for a in seg_avals]
        self._plan = _build_plan(seg_avals, self._effective_threshold())

        def fwd(params, batch):
            carries = []
            carry = None
            for f, p in zip(stages[:-1], params[:-1]):
                carry = f(p, carry, batch)
                carries.append(carry)
            loss = stages[-1](params[-1], carry, batch)
            return jax.lax.pmean(loss, REPLICA_AXIS), tuple(carries)

        self._fwd_program = jax.jit(_compat.shard_map(
            fwd, mesh=self._mesh, in_specs=(P(), P(REPLICA_AXIS)),
            out_specs=(P(), P(REPLICA_AXIS)), check_vma=False))

        def pr(tree):
            return jax.tree_util.tree_map(lambda x: x[None], tree)

        def make_last(k):
            def bwd(p, carry, batch):
                def f(p, c):
                    return stages[k](p, c, batch)
                out, vjp = jax.vjp(f, p, carry)
                g, ct = vjp(jnp.ones_like(out))
                return pr(self._compress_tree(g)), ct
            return bwd

        def make_mid(k):
            def bwd(p, carry, batch, ct_in):
                def f(p, c):
                    return stages[k](p, c, batch)
                _, vjp = jax.vjp(f, p, carry)
                g, ct = vjp(ct_in)
                return pr(self._compress_tree(g)), ct
            return bwd

        def make_first():
            def bwd(p, batch, ct_in):
                def f(p):
                    return stages[0](p, None, batch)
                _, vjp = jax.vjp(f, p)
                (g,) = vjp(ct_in)
                return pr(self._compress_tree(g))
            return bwd

        sm = _compat.shard_map
        R = P(REPLICA_AXIS)
        self._bwd_programs: List[Callable] = [None] * S
        # Stage-boundary carries and cotangents are step-internal
        # single-consumer buffers: donate them so the backward chain
        # runs in-place on real accelerators.
        self._bwd_programs[S - 1] = jax.jit(
            sm(make_last(S - 1), mesh=self._mesh,
               in_specs=(P(), R, R), out_specs=(R, R),
               check_vma=False),
            donate_argnums=(1,))
        for k in range(1, S - 1):
            self._bwd_programs[k] = jax.jit(
                sm(make_mid(k), mesh=self._mesh,
                   in_specs=(P(), R, R, R), out_specs=(R, R),
                   check_vma=False),
                donate_argnums=(1, 3))
        self._bwd_programs[0] = jax.jit(
            sm(make_first(), mesh=self._mesh, in_specs=(P(), R, R),
               out_specs=R, check_vma=False),
            donate_argnums=(2,))

    def _build_apply(self) -> Callable:
        optimizer = self._optimizer
        comp = self._compression
        ctxs = list(self._ctxs or [])
        divide = self._red_op == ReduceOp.AVERAGE

        def apply_body(grads_pr, opt_state, params):
            g = jax.tree_util.tree_map(
                lambda x: jnp.squeeze(x, 0), grads_pr)
            leaves, tdef = jax.tree_util.tree_flatten(g)
            if comp is not None:
                leaves = [comp.decompress(x, ctx)
                          for x, ctx in zip(leaves, ctxs)]
            if divide:
                # The static path's `finish`: divide AFTER decompress in
                # the restored dtype by the f32 replica count — the
                # reductions themselves always ride as SUM.
                denom = jax.lax.psum(jnp.ones((), jnp.float32),
                                     REPLICA_AXIS)
                leaves = [x / denom.astype(x.dtype) for x in leaves]
            g = jax.tree_util.tree_unflatten(tdef, leaves)
            updates, opt_state = optimizer.update(g, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        donate = (0, 1, 2) if self._donate else (0,)
        # Single-process: reduced buckets are per-replica [size, ...]
        # arrays — each replica squeezes its own row.  Multi-process:
        # the mp megakernel returns REPLICATED [1, ...] tensors (the
        # negotiated local-row shape), so the grads ride in replicated
        # and every replica squeezes the same row; the psum(ones)
        # denominator still counts the world replicas (== processes —
        # the mp-local-replicas guard pinned size == process_count),
        # which is exactly the mp AVERAGE denominator.
        grads_spec = P() if self._mp else P(REPLICA_AXIS)
        return jax.jit(_compat.shard_map(
            apply_body, mesh=self._mesh,
            in_specs=(grads_spec, P(), P()), out_specs=(P(), P()),
            check_vma=False), donate_argnums=donate)

    # -- execution ---------------------------------------------------------
    def _submit_segment(self, seg: _Segment, seg_leaves: List,
                        handles: List[Optional[int]], tl) -> None:
        dispatch_bucket_segment(self._prefix, seg, seg_leaves, handles,
                                tl, mp=self._mp)

    def __call__(self, *args):
        if self._fallback_step is not None:
            return self._fallback_step(*args)
        if not self._built:
            self._build(args)
            if self._fallback_step is not None:
                return self._fallback_step(*args)
        thr = self._effective_threshold()
        if thr != self._plan.threshold:
            # Fusion-threshold change (autotune / set_fusion_threshold):
            # the coordinator flushed its plan memo and the megakernel
            # cache; re-partition the dispatch boundaries to match.  The
            # re-used wire names carry new signatures, which the
            # response cache resolves as a program change (flush +
            # renegotiate once).
            self._replan(thr)
        return self._run(args)

    def _replan(self, threshold: int) -> None:
        seg_avals: List[List[Any]] = []
        pos = 0
        for n in self._seg_sizes:
            seg_avals.append(self._leaf_avals[pos:pos + n])
            pos += n
        self._plan = _build_plan(seg_avals, threshold)

    def _run(self, args):
        st = _state.global_state()
        tl = st.timeline
        stream = self._stream
        if self._has_state:
            params, model_state, opt_state, batch = args
        else:
            (params, opt_state, batch), model_state = args, None
        handles: List[Optional[int]] = [None] * self._plan.n_leaves
        window = _InflightWindow(_max_inflight()) if self._cpu_mesh \
            else None
        extra = None

        if self._segmented:
            chain_params = list(params)
            loss, carries = self._fwd_program(chain_params, batch)
            segs = self._plan.segments
            S = len(segs)
            staged = []  # serial schedule: submit only after the fence
            ct = None
            for k in range(S - 1, -1, -1):
                if k == S - 1:
                    g, ct = self._bwd_programs[k](
                        chain_params[k], carries[k - 1], batch)
                elif k == 0:
                    g = self._bwd_programs[k](chain_params[k], batch, ct)
                    ct = None
                else:
                    g, ct = self._bwd_programs[k](
                        chain_params[k], carries[k - 1], batch, ct)
                if window is not None:
                    window.admit((g, ct))
                seg_leaves = jax.tree_util.tree_leaves(g)
                if stream:
                    self._submit_segment(segs[k], seg_leaves, handles, tl)
                else:
                    staged.append((segs[k], seg_leaves))
            if not stream:
                # "Reduction serialized after backward": the exact
                # symptom docs/performance.md names — fence the whole
                # backward, then dispatch the same buckets.
                for _, seg_leaves in staged:
                    jax.block_until_ready(seg_leaves)
                for seg, seg_leaves in staged:
                    self._submit_segment(seg, seg_leaves, handles, tl)
        else:
            loss, grads_pr, extra = self._grads_program(
                params, model_state, batch)
            if window is not None:
                window.admit(grads_pr)
            seg_leaves = jax.tree_util.tree_leaves(grads_pr)
            if not stream:
                jax.block_until_ready(seg_leaves)
            self._submit_segment(self._plan.segments[0], seg_leaves,
                                 handles, tl)

        t0 = time.perf_counter()
        reduced = [C.take_async(h) for h in handles]
        if not stream:
            jax.block_until_ready(reduced)
        if _telemetry.enabled():
            _M_EXPOSED.observe(time.perf_counter() - t0)
        red_tree = jax.tree_util.tree_unflatten(self._treedef, reduced)
        new_params, opt_state = self._apply(red_tree, opt_state, params)
        if self._has_state:
            return new_params, extra, opt_state, loss
        if self._has_aux:
            return new_params, opt_state, loss, extra
        return new_params, opt_state, loss


class _Unbucketable(Exception):
    """Raised during plan building when the gradient tree cannot take
    the bucketed path; the step falls back to the monolithic program.
    ``reason`` is the short fallback name the telemetry/flight record
    carries (``sparse``, ``grad-tree``)."""

    def __init__(self, reason: str, detail: str):
        super().__init__(detail)
        self.reason = reason


class _NonStaticContext(Exception):
    pass


def make_overlapped_step(loss_fn, optimizer, mesh, red_op: ReduceOp,
                         fusion_threshold: Optional[int], has_aux: bool,
                         donate: bool, has_state: bool, compression,
                         stream: bool,
                         fallback_builder: Callable[[], Callable]):
    """Build the bucketed-backward step (``parallel/training._make_step``
    calls this when the overlap mode resolves on).  ``fallback_builder``
    constructs the monolithic static step for the unbucketable cases
    (Adasum, sparse leaves, subset meshes)."""
    if optax is None:
        return fallback_builder()
    return _OverlapStep(loss_fn, optimizer, mesh, red_op,
                        fusion_threshold, has_aux, donate, has_state,
                        compression, stream, fallback_builder)
