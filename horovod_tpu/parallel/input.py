"""Double-buffered device prefetch — the input half of host-stall
elimination (hvd-pipeline).

PR 2 deleted the per-step control-plane cost and PR 3 the data-plane
dispatch cost, which leaves the *host* as the steady-state bound: a
train loop that calls ``shard_batch(next(loader))`` serializes three
things that could overlap — the loader producing batch N+1, the
host→device transfer of batch N+1, and the device computing step N.
That is exactly the input-pipeline stall the original Horovod paper's
throughput methodology assumes away with synthetic data
(arXiv:1802.05799 §5) and that production input pipelines hide with
prefetch queues.

:func:`prefetch_to_device` wraps any host batch iterator in a
background stager: while step N computes, the stager pulls batch N+1
from the loader and places it on the mesh with ONE batched
``jax.device_put`` over the whole pytree (correct ``NamedSharding`` per
leaf), parking the device-resident batch in a bounded queue.  The
consuming loop's ``next()`` then returns arrays that are already on
device — combined with the async-dispatch loop (deferred metric
fetches, ``hvd.barrier_fence()`` for explicit completion points) the
TPU never waits for the host in steady state.

Contract:

* **Ordering** — batches come out in exactly the loader's order.
* **Bounded** — at most ``depth`` staged batches exist at once (plus
  the one the loader is currently producing); depth 2 is classic
  double buffering.
* **Exceptions** — a loader exception is captured on the stager thread
  and re-raised at the consuming step WITH the original traceback; the
  flight recorder logs it (``prefetch_error``) so a crashed input
  pipeline is forensically visible.
* **Clean shutdown** — ``close()`` (also via context manager / ``for``
  loop exhaustion / garbage collection) stops the stager, closes a
  generator loader, and joins the thread, even mid-epoch with a full
  queue.

Telemetry (docs/metrics.md): ``host.stall_seconds`` (histogram — time
the consumer blocked waiting on the queue, i.e. the stall the prefetch
failed to hide), ``input.batches_staged`` (counter) and
``input.prefetch_queue_depth`` (gauge).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterable, Iterator, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import chaos as _chaos
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..analysis import threads as _analysis_threads
from ..core import state as _state
from ..core.state import REPLICA_AXIS
from ..memory import ledger as _mem
from ..telemetry import flight as _flight

_M_STALL = _telemetry.histogram(
    "host.stall_seconds", "seconds",
    "time the training loop blocked waiting on the input queue")
_M_STAGED = _telemetry.counter(
    "input.batches_staged", "batches staged host->device by prefetchers")
_M_DEPTH = _telemetry.gauge(
    "input.prefetch_queue_depth", "device-resident batches currently staged")

# Queue sentinels (identity-compared).
_END = object()


class _Staged:
    """One staged device batch plus its ledger charge.  A wrapper
    class, not a tuple — user batches may themselves be tuples, and the
    consumer must distinguish them from the bookkeeping by type."""

    __slots__ = ("batch", "nbytes")

    def __init__(self, batch, nbytes: int) -> None:
        self.batch = batch
        self.nbytes = nbytes


def _shardings_for(batch: Any, mesh, sharding) -> Any:
    """Resolve the per-leaf shardings for one batch pytree.

    ``sharding`` may be None (split the leading axis over the replica
    axis — the data-parallel default), a single ``NamedSharding`` /
    ``PartitionSpec`` applied to every leaf, or a pytree of either
    matching the batch structure."""
    if sharding is None:
        sharding = NamedSharding(mesh, P(REPLICA_AXIS))
    def to_sharding(s):
        return NamedSharding(mesh, s) if isinstance(s, P) else s
    if isinstance(sharding, (NamedSharding, P)):
        s = to_sharding(sharding)
        return jax.tree_util.tree_map(lambda _: s, batch)
    return jax.tree_util.tree_map(lambda _x, s: to_sharding(s),
                                  batch, sharding)


def device_put_batch(batch: Any, mesh=None, sharding=None) -> Any:
    """Place one host batch onto the mesh with a single batched
    ``jax.device_put`` call over the whole pytree (one transfer program,
    not one dispatch per leaf — the satellite fix PR 5 applies to
    ``shard_batch``/``replicate``/``shard_parallel_batch`` too)."""
    mesh = mesh or _state.mesh()
    return jax.device_put(batch, _shardings_for(batch, mesh, sharding))


class PrefetchIterator:
    """Iterator returned by :func:`prefetch_to_device`.

    Iterates device-resident batches; supports ``len()`` pass-through
    is intentionally absent (the loader's length is unknowable in
    general).  Use as a context manager — or just break/close — for
    deterministic mid-epoch shutdown."""

    def __init__(self, iterable: Iterable, mesh, depth: int,
                 sharding) -> None:
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._mesh = mesh
        self._depth = depth
        self._sharding = sharding
        self._source = iterable
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._stage_loop, name="hvd-prefetch", daemon=True)
        self._thread.start()

    # -- stager thread -----------------------------------------------------
    def _stage_loop(self) -> None:  # thread: stager
        _analysis_threads.set_role("stager")
        it = iter(self._source)
        try:
            while not self._stop.is_set():
                try:
                    host_batch = next(it)
                except StopIteration:
                    self._put(_END)
                    return
                # hvd-chaos input.stall: a loader/filesystem hiccup on
                # the stager thread.  The contract under injection: the
                # consumer sees added latency (host.stall_seconds), the
                # batch ORDER and VALUES never change — training stays
                # bitwise-identical to the fault-free run.
                if _chaos.active():
                    _chaos.sleep_site("input.stall")
                staged = device_put_batch(host_batch, self._mesh,
                                          self._sharding)
                _M_STAGED.inc()
                # hvd-mem: a staged batch is framework-held HBM until
                # the consumer takes it — charge the ledger for its
                # queue residency (released at __next__/close).
                nb = _mem.tree_nbytes(staged) if _mem.enabled() else 0
                if nb:
                    _mem.ledger.alloc("input.prefetch", nb)
                if not self._put(_Staged(staged, nb)):
                    _mem.ledger.free("input.prefetch", nb)
                    return
        except BaseException as e:  # noqa: BLE001 — carried to consumer
            _telemetry.prefetch_error_event(
                f"{type(e).__name__}: {e}")
            self._put(e)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass

    def _put(self, item) -> bool:
        """Bounded put that stays responsive to close(); returns False
        when the iterator shut down before the item was accepted."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                _M_DEPTH.set(self._q.qsize())
                return True
            except queue.Full:
                continue
        return False

    # -- consumer side -----------------------------------------------------
    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        try:
            item = self._q.get_nowait()
        except queue.Empty:
            # The stall the prefetch could not hide: the loader (or the
            # transfer) is slower than the step.  One perf_counter pair,
            # blocked path only.  Timed gets so a close() from another
            # thread (which enqueues nothing) wakes this consumer too.
            t0 = time.perf_counter()
            mt0 = time.monotonic() if _trace.enabled() else 0.0
            while True:
                try:
                    item = self._q.get(timeout=0.05)
                    break
                except queue.Empty:
                    if self._stop.is_set():
                        _M_STALL.observe(time.perf_counter() - t0)
                        raise StopIteration from None
            _M_STALL.observe(time.perf_counter() - t0)
            if _trace.enabled():
                # hvd-trace host span: the analyzer's "this rank was
                # input-bound" signal — the blame category a seeded
                # slow loader must surface under (docs/tracing.md).
                _trace.span("prefetch.wait", "host", mt0,
                            time.monotonic())
        _M_DEPTH.set(self._q.qsize())
        if item is _END:
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            # Re-raise ON the consumer thread with the stager-side
            # traceback intact (the exception object carries it).
            raise item
        if item.nbytes:
            _mem.ledger.free("input.prefetch", item.nbytes)
        return item.batch

    def close(self) -> None:
        """Stop the stager and join it.  Safe mid-epoch with a full
        queue (the stager's bounded put polls the stop flag), safe to
        call twice, safe from ``__del__``."""
        self._stop.set()
        # Unblock a stager parked in put() by draining; it re-checks the
        # stop flag within its put timeout either way.  Drained staged
        # batches release their ledger charge — a mid-epoch shutdown
        # must not read as a prefetch leak.
        try:
            while True:
                item = self._q.get_nowait()
                if isinstance(item, _Staged) and item.nbytes:
                    _mem.ledger.free("input.prefetch", item.nbytes)
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        # Drain AGAIN after the join: a stager parked inside its bounded
        # put() can land one final charged batch in the window between
        # the drain above emptying the queue and the stop-flag re-check
        # — the put succeeds, the stager exits without freeing, and the
        # charge would leak into whichever test asserts the
        # "input.prefetch" category drains to zero.
        try:
            while True:
                item = self._q.get_nowait()
                if isinstance(item, _Staged) and item.nbytes:
                    _mem.ledger.free("input.prefetch", item.nbytes)
        except queue.Empty:
            pass
        _M_DEPTH.set(0)

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def prefetch_to_device(iterable: Iterable, mesh=None, depth: int = 2,
                       sharding=None) -> PrefetchIterator:
    """Stage host batches onto the mesh ahead of consumption.

    Args:
      iterable: host batch source — any iterable/iterator/generator
        yielding pytrees of arrays (one GLOBAL batch per item, leading
        axis divisible by the replica count under the default
        sharding).
      mesh: target mesh; defaults to the global replica mesh.
      depth: bound on staged batches (2 = double buffering: batch N+1
        transfers while step N computes).
      sharding: per-leaf placement — None for the data-parallel default
        (leading axis split over ``"hvd"``), or a ``PartitionSpec`` /
        ``NamedSharding`` / pytree of either (the multi-axis
        ``shard_parallel_batch`` layouts).

    Returns a :class:`PrefetchIterator` yielding device-resident
    batches in loader order.  Loader exceptions re-raise at the
    consuming ``next()`` with the original traceback.
    """
    mesh = mesh or _state.mesh()
    return PrefetchIterator(iterable, mesh, depth, sharding)


__all__ = [
    "PrefetchIterator",
    "device_put_batch",
    "prefetch_to_device",
]
