"""Expert parallelism: Mixture-of-Experts with all_to_all token routing.

Beyond-parity extension (SURVEY.md §2.3 "Expert parallelism: NO").  The
design is the standard Switch/GShard formulation mapped onto a mesh axis:

* every device holds ``num_experts / axis_size`` expert MLPs,
* a router picks top-k experts per token with a capacity limit,
* tokens are dispatched to their experts with ONE ``all_to_all`` (the
  ICI-native equivalent of the reference's point-to-point sends — there
  are none in the reference; MPI_Alltoall would be the analogue),
* expert outputs return with a second ``all_to_all`` and are combined by
  router weight.

Everything is dense einsums over static shapes (dispatch/combine one-hot
tensors), so XLA tiles it onto the MXU and overlaps the two collectives —
no scalar gather/scatter loops.

**Fused hot path** (hvd-fuse, arXiv:2305.06942; ops/fused.py): the
dispatch all_to_all → expert FFN GEMMs → combine all_to_all pipeline is
chunked along the CAPACITY axis — each chunk runs the full round trip,
so chunk *i*'s all_to_all legs fly while chunk *i+1*'s FFN computes,
inside ONE XLA program.  Routing (router GEMM, top-k dispatch, aux
loss) stays whole: it is the producer every chunk depends on.  The
chunked output is BITWISE-identical to the unfused reference
(tests/test_fused.py): capacity rows are reduction-free, each chunk's
einsums keep the unfused contraction order, and the combine all_to_all
inverts the dispatch all_to_all's tiled row permutation chunk-by-chunk
so the concatenation restores the exact unfused layout.
``HVD_TPU_FUSE=off`` (or ``fuse=False``) pins the unfused reference
program; ``HVD_TPU_FUSE_CHUNKS`` bounds the chunk count (both knobs
ride the HELLO env fingerprint).

Conventionally EP rides the *data* axis (expert groups = DP groups):
pass ``axis_name="data"``; a dedicated ``expert`` axis works identically.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax

from ..core import compat as _compat
import jax.numpy as jnp

from ..ops import fused as _fused


class MoEOutput(NamedTuple):
    out: jnp.ndarray          # [tokens, d_model]
    aux_loss: jnp.ndarray     # scalar load-balancing loss
    dropped_fraction: jnp.ndarray  # scalar, tokens beyond capacity


def init_moe_params(key, num_experts: int, d_model: int, d_hidden: int,
                    dtype=jnp.float32) -> dict:
    """Full (unsharded) expert stack + router; shard the leading expert
    axis over the EP mesh axis before use (or index with
    :func:`local_experts`)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = d_model ** -0.5
    scale_out = d_hidden ** -0.5
    return {
        "router": jax.random.normal(k1, (d_model, num_experts),
                                    dtype) * scale_in,
        "w_in": jax.random.normal(k2, (num_experts, d_model, d_hidden),
                                  dtype) * scale_in,
        "w_out": jax.random.normal(k3, (num_experts, d_hidden, d_model),
                                   dtype) * scale_out,
    }


def local_experts(params: dict, *, axis_name: str) -> dict:
    """Slice this device's expert shard (inside shard_map) from replicated
    full params; the router stays replicated."""
    n = _compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)

    def shard(leaf):
        size = leaf.shape[0] // n
        return jax.lax.dynamic_slice_in_dim(leaf, idx * size, size, axis=0)

    return {"router": params["router"],
            "w_in": shard(params["w_in"]),
            "w_out": shard(params["w_out"])}


def _top_k_dispatch(probs, k: int, capacity: int):
    """Greedy top-k routing with per-expert capacity.

    Returns dispatch ``[t, E, C]`` (0/1) and combine ``[t, E, C]``
    (gate-weighted) tensors, plus the dropped-token fraction.
    """
    tokens, num_experts = probs.shape
    remaining = probs
    dispatch = jnp.zeros((tokens, num_experts, capacity), probs.dtype)
    combine = jnp.zeros((tokens, num_experts, capacity), probs.dtype)
    # Tokens already admitted per expert (running fill count).
    fill = jnp.zeros((num_experts,), jnp.int32)
    routed = jnp.zeros((tokens,), probs.dtype)
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)              # [t]
        gate = jnp.take_along_axis(remaining, choice[:, None],
                                   axis=-1)[:, 0]            # [t]
        onehot = jax.nn.one_hot(choice, num_experts,
                                dtype=probs.dtype)           # [t, E]
        # Position of each token within its chosen expert's buffer:
        # earlier tokens first (cumsum order), offset by the current fill.
        pos = (jnp.cumsum(onehot, axis=0) - 1.0
               + fill[None, :].astype(probs.dtype))          # [t, E]
        pos_tok = jnp.sum(pos * onehot, axis=-1)             # [t]
        keep = pos_tok < capacity
        pos_oh = jax.nn.one_hot(
            jnp.clip(pos_tok, 0, capacity - 1).astype(jnp.int32),
            capacity, dtype=probs.dtype)                     # [t, C]
        d = (onehot * keep[:, None].astype(probs.dtype))[:, :, None] \
            * pos_oh[:, None, :]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        fill = fill + jnp.sum(
            onehot * keep[:, None].astype(probs.dtype),
            axis=0).astype(jnp.int32)
        routed = routed + keep.astype(probs.dtype)
        # Exclude the chosen expert from the next round.
        remaining = remaining * (1.0 - onehot)
    dropped = 1.0 - jnp.mean(routed) / k
    return dispatch, combine, dropped


def moe_layer(x, params: dict, *, axis_name: str, num_experts: int,
              top_k: int = 2, capacity_factor: float = 1.25,
              activation=jax.nn.gelu,
              aux_loss_weight: float = 1e-2,
              fuse: Optional[bool] = None,
              fuse_chunks: Optional[int] = None) -> MoEOutput:
    """Sharded mixture-of-experts FFN (inside shard_map over
    ``axis_name``).

    Args:
      x: ``[tokens_local, d_model]`` — this shard's tokens.
      params: ``router [d, E]`` (replicated), ``w_in [E_local, d, h]``,
        ``w_out [E_local, h, d]`` — expert leading axes already sharded
        (e.g. via :func:`local_experts`).
      num_experts: global expert count E (must divide by the axis size).
      fuse: override the ``HVD_TPU_FUSE`` knob for this layer —
        ``False`` pins the unfused reference program (bitwise-identical
        output either way; see the module docstring).
      fuse_chunks: override ``HVD_TPU_FUSE_CHUNKS`` — capacity-axis
        chunks of the fused dispatch→FFN→combine round trip.
    """
    n = _compat.axis_size(axis_name)
    tokens, d_model = x.shape
    e_local = num_experts // n
    if e_local * n != num_experts:
        raise ValueError(f"num_experts ({num_experts}) must divide by the "
                         f"'{axis_name}' axis size ({n})")
    if params["w_in"].shape[0] != e_local:
        raise ValueError(
            f"params carry {params['w_in'].shape[0]} local experts but "
            f"num_experts/axis_size = {e_local}; shard them with "
            f"local_experts() first")
    capacity = max(1, int(tokens * capacity_factor * top_k / num_experts))

    logits = jnp.dot(x, params["router"],
                     preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, dropped = _top_k_dispatch(probs, top_k, capacity)

    # Load-balancing auxiliary loss (Switch Transformer eq. 4): fraction
    # of tokens per expert × mean router probability per expert.
    token_frac = jnp.mean(jnp.sum(dispatch, axis=-1), axis=0)
    prob_frac = jnp.mean(probs, axis=0)
    aux = aux_loss_weight * num_experts * jnp.sum(
        token_frac * prob_frac)

    # Dispatch: [t, d] x [t, E, C] -> [E, C, d]; ship each device its
    # experts' buffers from every peer.
    expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                           dispatch.astype(jnp.float32))
    w_in = params["w_in"].astype(jnp.float32)
    w_out = params["w_out"].astype(jnp.float32)

    def roundtrip(buf):
        # One capacity chunk's full trip: route out, compute, route
        # back.  [E, c, d] -> [E_local, n*c, d] -> ... -> [E, c, d].
        buf = jax.lax.all_to_all(buf, axis_name, split_axis=0,
                                 concat_axis=1, tiled=True)
        # Run the local experts on everyone's tokens.
        h = jnp.einsum("ecd,edh->ech", buf, w_in)
        h = activation(h)
        o = jnp.einsum("ech,ehd->ecd", h, w_out)
        # Return trip: the inverse all_to_all undoes the dispatch
        # leg's tiled row permutation within the chunk.
        return jax.lax.all_to_all(o, axis_name, split_axis=1,
                                  concat_axis=0, tiled=True)

    # hvd-fuse: emit the round trip per capacity chunk inside this one
    # program — chunk i's all_to_all legs overlap chunk i+1's FFN.
    # One chunk (or fuse=False) IS the unfused reference program.
    expert_out = _fused.chunked_map(roundtrip, expert_in, axis=1,
                                    chunks=fuse_chunks, fuse=fuse)
    out = jnp.einsum("ecd,tec->td", expert_out,
                     combine.astype(jnp.float32))
    return MoEOutput(out.astype(x.dtype), aux.astype(jnp.float32),
                     dropped.astype(jnp.float32))
