"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Beyond-parity long-context support (the reference has none — SURVEY.md §5
"Long-context / sequence parallelism: absent").  Two standard schemes, both
expressed over a named mesh axis (:data:`..core.topology.SEQ_AXIS`) inside
``shard_map``:

* **Ring attention** (:func:`ring_attention`) — q/k/v arrive sharded along
  the sequence axis; K/V chunks rotate around the ring with
  ``lax.ppermute`` while every device runs the Pallas flash-attention
  kernel on its resident q shard, merging partial results with the online
  log-sum-exp rule.  Peak memory is one sequence shard per device and the
  per-hop transfer overlaps with the chunk compute, so context length
  scales linearly with the ring size.  The backward pass rotates gradient
  accumulators with their chunks (one full ring pass) using the saved
  global LSE — the standard blockwise-parallel formulation.
* **Ulysses** (:func:`ulysses_attention`) — ``all_to_all`` re-shards from
  sequence-parallel to head-parallel, runs dense local flash attention on
  the full sequence for a head subset, and re-shards back.  Cheaper at
  moderate context (two all-to-alls total), but requires
  ``heads % axis_size == 0``.

Causal masking never wastes a full ring step: chunks entirely in the
future are skipped via ``lax.switch`` (only the selected branch executes),
the diagonal chunk runs the causal kernel, past chunks run unmasked.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from ..core import compat as _compat
import jax.numpy as jnp

from ..core.topology import SEQ_AXIS
from ..ops.flash_attention import (_flash_backward, flash_attention,
                                   flash_attention_with_lse)


def _rot_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _merge_partial(o_acc, lse_acc, o_p, lse_p):
    """Online-softmax merge of two partial attentions over the same rows.

    ``o`` accumulates in float32; ``lse`` values of -inf (no visible keys)
    contribute zero weight without producing NaNs.
    """
    lse_new = jnp.logaddexp(lse_acc, lse_p)
    safe = jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)
    w_acc = jnp.where(jnp.isneginf(lse_acc), 0.0, jnp.exp(lse_acc - safe))
    w_p = jnp.where(jnp.isneginf(lse_p), 0.0, jnp.exp(lse_p - safe))
    o_new = o_acc * w_acc[..., None] + o_p * w_p[..., None]
    return o_new, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
          interpret):
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_q,
                          block_k, interpret)
    return o


def _attend_chunk(q, k_c, v_c, src, my, causal, sm_scale, block_q, block_k,
                  interpret):
    """Partial attention of the local q shard against one K/V chunk.

    ``src`` is the traced global index of the chunk currently resident;
    relative to the local shard index ``my`` it selects diagonal (causal
    mask), past (dense), or future (skip) handling.
    """
    kw = dict(sm_scale=sm_scale, block_q=block_q, block_k=block_k,
              interpret=interpret)
    if not causal:
        return flash_attention_with_lse(q, k_c, v_c, causal=False, **kw)

    def diag(_):
        return flash_attention_with_lse(q, k_c, v_c, causal=True, **kw)

    def full(_):
        return flash_attention_with_lse(q, k_c, v_c, causal=False, **kw)

    def skip(_):
        b, h, s, _d = q.shape
        return (jnp.zeros(q.shape, q.dtype),
                jnp.full((b, h, s), -jnp.inf, jnp.float32))

    branch = jnp.where(src == my, 0, jnp.where(src < my, 1, 2))
    return jax.lax.switch(branch, [diag, full, skip], None)


def _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
                   interpret):
    n = _compat.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = _rot_perm(n)

    b, h, s, d = q.shape
    o = jnp.zeros((b, h, s, d), jnp.float32)
    lse = jnp.full((b, h, s), -jnp.inf, jnp.float32)
    k_c, v_c = k, v
    for t in range(n):
        src = (my - t) % n
        o_p, lse_p = _attend_chunk(q, k_c, v_c, src, my, causal, sm_scale,
                                   block_q, block_k, interpret)
        o, lse = _merge_partial(o, lse, o_p.astype(jnp.float32), lse_p)
        if t != n - 1:
            k_c = jax.lax.ppermute(k_c, axis_name, perm)
            v_c = jax.lax.ppermute(v_c, axis_name, perm)
    return o.astype(q.dtype), lse


def _ring_fwd(q, k, v, axis_name, causal, sm_scale, block_q, block_k,
              interpret):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_q,
                            block_k, interpret)
    return o, (q, k, v, o, lse)


def _chunk_grads(q, k_c, v_c, o, lse, g, src, my, causal, sm_scale,
                 block_q, block_k, interpret):
    """(dq_partial, dk_chunk, dv_chunk) for one resident chunk.

    Uses the *global* LSE and final output, under which every chunk's
    softmax probabilities are exact — partial gradients then sum to the
    true gradient without any per-chunk renormalization.
    """
    def run(causal_flag):
        return _flash_backward((q, k_c, v_c, o, lse), g, sm_scale=sm_scale,
                               causal=causal_flag, block_q=block_q,
                               block_k=block_k, q_block_offset=0,
                               interpret=interpret)

    if not causal:
        return run(False)

    def diag(_):
        return run(True)

    def full(_):
        return run(False)

    def skip(_):
        return (jnp.zeros_like(q), jnp.zeros_like(k_c),
                jnp.zeros_like(v_c))

    branch = jnp.where(src == my, 0, jnp.where(src < my, 1, 2))
    return jax.lax.switch(branch, [diag, full, skip], None)


def _ring_bwd(axis_name, causal, sm_scale, block_q, block_k, interpret,
              res, g):
    q, k, v, o, lse = res
    n = _compat.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = _rot_perm(n)

    dq = jnp.zeros(q.shape, jnp.float32)
    k_c, v_c = k, v
    dk_c = jnp.zeros(k.shape, jnp.float32)
    dv_c = jnp.zeros(v.shape, jnp.float32)
    for t in range(n):
        src = (my - t) % n
        dq_p, dk_p, dv_p = _chunk_grads(q, k_c, v_c, o, lse, g, src, my,
                                        causal, sm_scale, block_q, block_k,
                                        interpret)
        dq = dq + dq_p.astype(jnp.float32)
        dk_c = dk_c + dk_p.astype(jnp.float32)
        dv_c = dv_c + dv_p.astype(jnp.float32)
        # Gradient accumulators travel with their chunk; after the final
        # rotation each chunk's dK/dV lands back on its home device.
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        dk_c = jax.lax.ppermute(dk_c, axis_name, perm)
        dv_c = jax.lax.ppermute(dv_c, axis_name, perm)
    return (dq.astype(q.dtype), dk_c.astype(k.dtype),
            dv_c.astype(v.dtype))


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                   causal: bool = False, sm_scale: Optional[float] = None,
                   block_q: int = 128, block_k: int = 128,
                   interpret: Optional[bool] = None):
    """Sequence-parallel attention over a ring of devices.

    Call inside ``shard_map`` with ``q, k, v : [batch, heads, seq_local,
    head_dim]`` sharded along ``axis_name``; sequence position is shard
    -major (shard i holds rows ``[i*seq_local, (i+1)*seq_local)``).
    Differentiable; numerically matches dense attention over the gathered
    sequence.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _ring(q, k, v, axis_name, bool(causal), float(sm_scale),
                 int(block_q), int(block_k), interpret)


def ulysses_attention(q, k, v, *, axis_name: str = SEQ_AXIS,
                      causal: bool = False,
                      sm_scale: Optional[float] = None,
                      block_q: int = 128, block_k: int = 128,
                      interpret: Optional[bool] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses scheme).

    Re-shards seq-parallel q/k/v to head-parallel over ``axis_name`` (one
    ``all_to_all``), runs local flash attention on the full sequence for
    ``heads / axis_size`` heads, and re-shards back.  Differentiable
    through the native transpose of ``all_to_all``.  Requires the head
    count to divide evenly.
    """
    n = _compat.axis_size(axis_name)
    h = q.shape[1]
    if h % n != 0:
        raise ValueError(f"ulysses_attention needs heads ({h}) divisible "
                         f"by the '{axis_name}' axis size ({n})")

    def to_heads(x):  # [B, H, S/n, D] -> [B, H/n, S, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def to_seq(x):  # [B, H/n, S, D] -> [B, H, S/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    o = flash_attention(to_heads(q), to_heads(k), to_heads(v),
                        causal=causal, sm_scale=sm_scale, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    return to_seq(o)
