"""Pipeline parallelism: GPipe scan + host-scheduled 1F1B MPMD schedule.

Two generations of the same axis:

* :func:`gpipe` — the original beyond-parity extension (SURVEY.md §2.3
  "Pipeline parallelism: NO"): layer blocks shard over
  :data:`..core.topology.PIPE_AXIS`; a batch is cut into microbatches
  that flow stage-to-stage over ICI via ``lax.ppermute`` inside a
  ``lax.scan`` — the whole schedule is ONE compiled XLA program, so the
  backward pass (reverse scan, reversed permutes) is derived by JAX AD
  and is itself pipelined.  Bubble fraction is the usual
  ``(n_stages - 1) / (m + n_stages - 1)``, and — the cost this module's
  second half deletes — every gradient collective fires only after the
  whole scan, so the bubble ticks sit idle while the reduction waits.

* :func:`make_pipeline_train_step` — the MPMD rebuild (arXiv:2412.14374
  direction; ROADMAP open item 4): instead of one monolithic scan, each
  stage's forward and backward microbatch is its OWN compiled
  executable, dispatched by a host-side scheduler in 1F1B order
  (optionally with interleaved virtual stages).  The per-stage backward
  programs are the segmented-backward substrate the
  backward/communication-overlap step introduced
  (``parallel/overlap.py``: stage-boundary activations, one backward
  program per stage, ``jax.vjp`` with in-segment rematerialization) —
  and each stage's bucketed gradient dispatch rides the SAME
  partial-cycle choreography (:func:`..parallel.overlap.
  dispatch_bucket_segment`): the moment a stage's last microbatch
  backward is dispatched, its fusion groups negotiate/replay through
  the response cache and stream their megakernels into the remaining
  schedule ticks — communication hides in the pipeline bubbles instead
  of serializing after the flush.

Why 1F1B: at equal microbatch count the flush bubble is the same as
GPipe's, but (a) in-flight activation memory is bounded by the stage
depth instead of the microbatch count (``PipelinePlan.peak_activations``
— the property the dryrun tests gate), and (b) each stage finishes its
backwards EARLY (stage ``S-1`` first), so streamed gradient reduction
overlaps the other stages' cooldown — ``bench.py --mode pipeline``
gates the exposed-bubble seconds strictly below the GPipe-ordered leg
at equal device work.

Env contract (validated at ``hvd.init``; rides the control-plane HELLO
env fingerprint — the schedule selects which compiled programs a rank
dispatches in which order, so it must be uniform fleet-wide):

  HVD_TPU_PIPELINE_SCHEDULE=1f1b|gpipe
      default 1f1b.  ``gpipe`` runs the SAME per-stage executables in
      all-forwards-then-all-backwards order with the gradient dispatch
      serialized after a full flush fence — the measurement comparator
      and the bitwise-identity reference (same programs, same
      microbatch accumulation order, different interleaving).
  HVD_TPU_PIPELINE_INTERLEAVE=<v>
      default 1.  Interleaved virtual stages: ``v`` must divide the
      stage count; the ``n_stages/v`` executors each own ``v``
      round-robin model chunks, shortening the per-chunk ramp so the
      flush bubble shrinks (gated structurally by the dryrun plan).

**Bitwise contract** (tests/test_pipeline_parallel.py, gated by
``bench.py --mode pipeline``): the 1F1B step's loss and parameters are
bitwise identical to the GPipe-ordered dispatch of the same per-stage
programs — backwards execute in microbatch order at every stage under
both schedules, so the gradient accumulation chains are the same
arithmetic; only the interleaving and the reduction dispatch points
differ.  Against the monolithic reference (``jax.grad`` of the
microbatch-mean loss) the parity is allclose, not bitwise — XLA
compiles per-stage programs with different fusion decisions than one
whole-graph backward (the same ULP story as
``parallel/overlap.ChainedLoss``).

**Sub-mesh placement (mp × pipeline; hvd-fuse)**: pass
``stage_meshes=[mesh_0, ..., mesh_{S-1}]`` (e.g. from
:func:`stage_submeshes`) and each stage's executables compile over its
OWN sub-mesh instead of sharing the global replica mesh — real MPMD
placement: stage *k*'s forward/backward/apply only ever touch stage
*k*'s devices, and the host loop moves boundary carries/cotangents
between sub-meshes with ``device_put``.  A sub-mesh may carry extra
axes beyond :data:`~..core.state.REPLICA_AXIS` (e.g.
:data:`~..core.topology.MODEL_AXIS`), so a stage body can run
tensor-parallel fused closers (``parallel/tensor.py``) inside its own
sub-mesh — the mp × pipeline composition.  Under placement the
per-stage gradient reduction leaves the dynamic bucket path: each
stage gets ONE fused reduce+apply program (in-program ``psum`` over
the stage's replica axis + optimizer update, an
:class:`~..ops.fused.FusedProgram`) dispatched the moment the stage's
last backward is in flight (1F1B) or after the flush fence (the GPipe
comparator) — 1f1b ≡ gpipe stays bitwise under placement because the
programs and accumulation chains are identical, only dispatch points
move.  ``opt_state`` must then be a per-stage sequence (mirroring
``params``), and ``donate`` applies to the backward programs only.
"""

from __future__ import annotations

import collections
import math
import os
import time
from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .. import telemetry as _telemetry
from ..analysis import donation as _donation
from ..core import compat as _compat
from ..core import state as _state
from ..core.state import REPLICA_AXIS
from ..core.topology import MODEL_AXIS, PIPE_AXIS
from ..memory import ledger as _mem
from ..memory import oom as _oom
from ..memory import planner as _mem_planner
from ..ops import fused as _fused

try:
    import optax
except Exception:  # pragma: no cover - optax is baked into the image
    optax = None

SCHEDULE_ENV = "HVD_TPU_PIPELINE_SCHEDULE"
INTERLEAVE_ENV = "HVD_TPU_PIPELINE_INTERLEAVE"
_VALID_SCHEDULES = ("1f1b", "gpipe")

# hvd-telemetry (docs/metrics.md "Pipeline schedule").
_M_MICROBATCHES = _telemetry.counter(
    "pipeline.microbatches",
    "microbatches executed through the MPMD pipeline schedule")
_M_BUBBLE = _telemetry.histogram(
    "pipeline.bubble_seconds", "seconds",
    "host seconds waiting on gradient reductions after the last "
    "schedule tick was dispatched — bubble/communication time NOT "
    "hidden inside the schedule")
_M_INFLIGHT = _telemetry.gauge(
    "pipeline.inflight_activations",
    "peak stage-boundary activations held live by the last schedule")
# hvd-mem: the figure that actually bounds a launch — BYTES, not tensor
# count (a carry count of 9 says nothing about whether 9 carries fit).
_M_INFLIGHT_BYTES = _telemetry.gauge(
    "pipeline.inflight_activation_bytes",
    "peak stage-boundary activation bytes held live by the last "
    "schedule (the 1F1B-vs-GPipe memory bound, in bytes)")


def _nearest_divisors(n: int, m: int) -> Tuple[int, int]:
    """The divisors of ``n`` nearest to ``m`` from below and above —
    the suggestion surface for schedule-shape errors."""
    lo = next((k for k in range(min(m, n), 0, -1) if n % k == 0), 1)
    hi = next((k for k in range(max(m, 1), n + 1) if n % k == 0), n)
    return lo, hi


def _indivisible_message(what: str, axis: int, m: int) -> str:
    lo, hi = _nearest_divisors(axis, m)
    suggest = f"{lo}" if lo == hi else f"{lo} or {hi}"
    return (f"{what} axis of size {axis} is not divisible by "
            f"num_microbatches={m}; nearest valid counts: {suggest}")


def schedule_env() -> str:
    return (os.environ.get(SCHEDULE_ENV, "1f1b").strip().lower()
            or "1f1b")


def interleave_env() -> int:
    v = os.environ.get(INTERLEAVE_ENV, "1").strip() or "1"
    try:
        return int(v)
    except ValueError:
        # Same named-knob contract as validate_env — the public dryrun
        # path (hvd.schedule_plan with no init) reads the env directly.
        raise ValueError(
            f"{INTERLEAVE_ENV}={v!r}: expected a positive integer "
            f"(virtual stages per pipeline executor)") from None


def validate_env() -> None:
    """Fail ``hvd.init()`` — not the first pipeline step — on a
    malformed schedule knob (same contract as the overlap/compression
    knobs; cross-rank uniformity is checked by the HELLO env
    fingerprint, ops/transport.py)."""
    v = os.environ.get(SCHEDULE_ENV)
    if v and schedule_env() not in _VALID_SCHEDULES:
        raise ValueError(
            f"{SCHEDULE_ENV}={v!r}: expected one of "
            f"{'|'.join(_VALID_SCHEDULES)}")
    iv = os.environ.get(INTERLEAVE_ENV)
    if iv:
        try:
            ok = int(iv) >= 1
        except ValueError:
            ok = False
        if not ok:
            raise ValueError(
                f"{INTERLEAVE_ENV}={iv!r}: expected a positive integer "
                f"(virtual stages per pipeline executor)")


# ---------------------------------------------------------------------------
# Schedule plan: the dryrun surface (shape gated without hardware)
# ---------------------------------------------------------------------------

class Action(NamedTuple):
    """One schedule slot: dispatch ``phase`` (``"F"``/``"B"``) of
    microbatch ``mb`` at pipeline stage ``stage``."""

    phase: str
    stage: int
    mb: int


@dataclass
class PipelinePlan:
    """A fully resolved dispatch schedule.

    ``ticks`` is the deterministic host dispatch order: at tick ``t``
    every listed action is handed to the device stream (one executable
    dispatch each); data dependencies always point to earlier ticks.
    ``bubble_ticks``/``bubble_fraction`` count executor-idle slots
    (an executor with remaining work but no ready action), and
    ``peak_activations`` the maximum number of stage-boundary carries
    live at once — the memory bound 1F1B holds at the stage depth
    while GPipe grows it with the microbatch count.
    """

    n_stages: int
    num_microbatches: int
    schedule: str
    interleave: int
    ticks: List[List[Action]] = field(default_factory=list)
    bubble_ticks: int = 0
    peak_activations: int = 0

    @property
    def n_executors(self) -> int:
        return self.n_stages // self.interleave

    @property
    def total_ticks(self) -> int:
        return len(self.ticks)

    @property
    def bubble_fraction(self) -> float:
        slots = self.n_executors * max(self.total_ticks, 1)
        return self.bubble_ticks / slots


def _resolve_schedule(schedule: Optional[str], interleave: Optional[int],
                      n_stages: int) -> Tuple[str, int]:
    sched = (schedule or schedule_env()).strip().lower()
    if sched not in _VALID_SCHEDULES:
        raise ValueError(
            f"pipeline schedule {sched!r}: expected one of "
            f"{'|'.join(_VALID_SCHEDULES)} ({SCHEDULE_ENV})")
    v = interleave if interleave is not None else interleave_env()
    v = int(v)
    if v < 1:
        raise ValueError(f"interleave={v}: must be >= 1")
    if n_stages % v != 0:
        lo, hi = _nearest_divisors(n_stages, v)
        suggest = f"{lo}" if lo == hi else f"{lo} or {hi}"
        raise ValueError(
            f"interleave={v} does not divide n_stages={n_stages}; "
            f"nearest valid interleave depths: {suggest}")
    return sched, v


def _stage_action_list(schedule: str, S: int, m: int, s: int) -> list:
    """Stage ``s``'s action order.  GPipe: all forwards, then all
    backwards.  1F1B: ``min(m, S-1-s)`` warmup forwards, a steady
    one-forward-one-backward phase, then the backward cooldown.
    Backwards run in microbatch order under BOTH schedules — the
    bitwise gradient-accumulation contract."""
    if schedule == "gpipe":
        return ([Action("F", s, i) for i in range(m)]
                + [Action("B", s, i) for i in range(m)])
    w = min(m, S - 1 - s)
    acts = [Action("F", s, i) for i in range(w)]
    for k in range(m - w):
        acts.append(Action("F", s, w + k))
        acts.append(Action("B", s, k))
    acts += [Action("B", s, i) for i in range(m - w, m)]
    return acts


def schedule_plan(n_stages: int, num_microbatches: int,
                  schedule: Optional[str] = None,
                  interleave: Optional[int] = None) -> PipelinePlan:
    """Resolve the dispatch schedule for ``n_stages`` × ``m``
    microbatches — the ``HVD_TPU_VIRTUAL_SLICES``-style dryrun surface:
    tests and operators gate the schedule SHAPE (tick order, bubble
    slots, peak activation memory) with no hardware and no jax
    dispatch.

    The plan is built by a deterministic event simulation: each of the
    ``n_stages/interleave`` executors owns its round-robin virtual
    stages and, every tick, fires the first owned stage whose next
    queued action (the per-stage 1F1B/GPipe order) has its
    dependencies satisfied by earlier ticks.  Forward of ``(s, i)``
    needs forward ``(s-1, i)``; backward needs the stage's own forward
    plus backward ``(s+1, i)``.
    """
    S, m = int(n_stages), int(num_microbatches)
    if S < 1 or m < 1:
        raise ValueError(f"n_stages={S} and num_microbatches={m} must "
                         f"be >= 1")
    sched, v = _resolve_schedule(schedule, interleave, S)
    D = S // v
    owners = {d: [d + j * D for j in range(v)] for d in range(D)}
    queues = {s: collections.deque(_stage_action_list(sched, S, m, s))
              for s in range(S)}
    fwd_done, bwd_done = set(), set()
    plan = PipelinePlan(n_stages=S, num_microbatches=m, schedule=sched,
                        interleave=v)

    def ready(a: Action) -> bool:
        if a.phase == "F":
            return a.stage == 0 or (a.stage - 1, a.mb) in fwd_done
        return ((a.stage, a.mb) in fwd_done
                and (a.stage == S - 1
                     or (a.stage + 1, a.mb) in bwd_done))

    live = 0
    while any(queues.values()):
        fired: List[Action] = []
        for d in range(D):
            for s in owners[d]:
                q = queues[s]
                if q and ready(q[0]):
                    fired.append(q.popleft())
                    break
            else:
                if any(queues[s] for s in owners[d]):
                    plan.bubble_ticks += 1
        if not fired:
            raise RuntimeError(
                f"pipeline schedule wedged: no ready action with "
                f"{sum(map(len, queues.values()))} pending "
                f"(schedule={sched}, S={S}, m={m}, v={v})")
        for a in fired:
            if a.phase == "F":
                fwd_done.add((a.stage, a.mb))
                if a.stage < S - 1:
                    live += 1  # carry born (consumed by B of stage+1)
            else:
                bwd_done.add((a.stage, a.mb))
                if a.stage > 0:
                    live -= 1  # carry (stage-1, mb) consumed
            plan.peak_activations = max(plan.peak_activations, live)
        plan.ticks.append(fired)
    return plan


# ---------------------------------------------------------------------------
# Sub-mesh placement (mp × pipeline)
# ---------------------------------------------------------------------------

def stage_submeshes(n_stages: int, *, mesh=None, model: int = 1
                    ) -> Tuple[jax.sharding.Mesh, ...]:
    """Split a replica mesh's devices into ``n_stages`` contiguous
    sub-meshes — the standard placement for
    ``make_pipeline_train_step(..., stage_meshes=...)``.

    Each sub-mesh gets ``devices/n_stages`` devices shaped
    ``(replica, model)``: axis :data:`~..core.state.REPLICA_AXIS` plus,
    when ``model > 1``, :data:`~..core.topology.MODEL_AXIS` — so a
    stage body can run tensor-parallel fused closers on its own
    devices (the mp × pipeline composition).  Contiguous splits keep
    each stage inside one ICI neighborhood on real slice topologies.
    """
    mesh = mesh or _state.mesh()
    devs = list(mesh.devices.flat)
    S, v = int(n_stages), int(model)
    if S < 1 or v < 1:
        raise ValueError(f"n_stages={S} and model={v} must be >= 1")
    if len(devs) % S != 0:
        raise ValueError(
            f"{len(devs)} devices do not split into {S} equal stage "
            f"sub-meshes")
    per = len(devs) // S
    if per % v != 0:
        raise ValueError(
            f"stage sub-mesh of {per} devices is not divisible by "
            f"model={v}")
    out = []
    for s in range(S):
        block = np.asarray(devs[s * per:(s + 1) * per])
        if v == 1:
            out.append(jax.sharding.Mesh(block, (REPLICA_AXIS,)))
        else:
            out.append(jax.sharding.Mesh(
                block.reshape(per // v, v), (REPLICA_AXIS, MODEL_AXIS)))
    return tuple(out)


def _validate_stage_meshes(stage_meshes, n_stages: int) -> tuple:
    meshes = tuple(stage_meshes)
    if len(meshes) != n_stages:
        raise ValueError(
            f"stage_meshes has {len(meshes)} meshes for {n_stages} "
            f"stages — one sub-mesh per stage")
    sizes = set()
    for k, mk in enumerate(meshes):
        if REPLICA_AXIS not in mk.axis_names:
            raise ValueError(
                f"stage_meshes[{k}] has axes {mk.axis_names!r}; every "
                f"stage sub-mesh needs the {REPLICA_AXIS!r} replica "
                f"axis (extra axes like {MODEL_AXIS!r} are fine)")
        sizes.add(int(mk.shape[REPLICA_AXIS]))
    if len(sizes) > 1:
        raise ValueError(
            f"stage sub-meshes disagree on replica count "
            f"({sorted(sizes)}): boundary carries are sharded over the "
            f"replica axis, so every stage needs the same count")
    return meshes


def _to_mesh(tree, mesh, spec):
    """Move a pytree onto ``mesh`` with ``spec`` on every leaf — the
    host-side boundary transfer between stage sub-meshes."""
    s = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)


# ---------------------------------------------------------------------------
# The MPMD pipeline train step
# ---------------------------------------------------------------------------

class _AotProgram:
    """AOT-compile-on-first-dispatch wrapper around one jitted stage
    program (hvd-mem): the first call lowers + compiles with the
    concrete arguments — the SAME executable ``jit`` would have built,
    one compile total — then harvests ``compiled.memory_analysis()``
    into the planner's per-mesh table (where the backend implements the
    query), and every dispatch runs inside the OOM guard naming this
    executable, so a pipeline-stage RESOURCE_EXHAUSTED dumps forensics
    instead of a bare traceback.  A shape change (or any non-OOM
    compiled-call failure) falls back to the jit wrapper, which
    recompiles transparently — semantics identical to plain jit."""

    __slots__ = ("name", "_fn", "_compiled", "_donate")

    def __init__(self, name: str, fn, donate: Tuple[int, ...] = ()) -> None:
        self.name = name
        self._fn = fn
        self._compiled = None
        # hvd-race donation sanitizer: the stage's donated positions —
        # every dispatch routes through the registry so a stale
        # re-dispatch of a consumed activation/state buffer raises a
        # DonationError naming this stage program (the bug class the
        # jit-fallback-after-consumed fix below closed by hand).
        self._donate = tuple(donate)

    def __call__(self, *args):
        with _oom.guard(self.name):
            if self._compiled is None:
                try:
                    compiled = self._fn.lower(*args).compile()
                    _mem_planner.record_compiled(self.name, compiled)
                    self._compiled = compiled
                except Exception:  # noqa: BLE001 — AOT lowering is an
                    self._compiled = False  # optimization, jit is the
                    # semantic baseline
            if self._compiled:
                try:
                    return _donation.guard_dispatch(
                        self.name, self._compiled, args, self._donate)
                except Exception as e:  # noqa: BLE001 — see below
                    if _oom.is_resource_exhausted(e):
                        raise
                    if isinstance(e, _donation.DonationError):
                        # The sanitizer caught a stale donated input
                        # BEFORE dispatch; the jit fallback would read
                        # the same dead buffers and mask the named
                        # diagnostic with XLA's deletion error.
                        raise
                    # A RUNTIME failure after XLA consumed the donated
                    # inputs must surface, not retry: the jit fallback
                    # would read deleted buffers and mask the original
                    # error (the ops/collective.py consumed-check
                    # convention).  Shape mismatches fail BEFORE
                    # dispatch — inputs intact — and hand over to jit
                    # PERMANENTLY: jit's own cache then serves every
                    # recurring shape, where re-arming the AOT path
                    # would pay a fresh XLA compile per A/B shape
                    # alternation (e.g. an epoch-end partial
                    # microbatch) that plain jit never pays.
                    if any(isinstance(a, jax.Array) and a.is_deleted()
                           for a in jax.tree_util.tree_leaves(args)):
                        raise
                    self._compiled = False
            return self._fn(*args)


class _PipelineStep:
    """Host-scheduled MPMD pipeline train step: per-stage compiled
    forward/backward microbatch executables dispatched in
    ``PipelinePlan`` order, per-stage gradient accumulation folded into
    the backward programs, and each stage's bucketed reduction streamed
    as partial cycles the moment its last backward is dispatched
    (``schedule="1f1b"``) or serialized after a flush fence
    (``schedule="gpipe"`` — the comparator leg).  Programs build
    lazily on the first call (microbatch shapes need a concrete
    batch)."""

    def __init__(self, chain, optimizer, mesh, num_microbatches: int,
                 schedule: str, interleave: int, average: bool,
                 fusion_threshold: Optional[int], donate: bool,
                 stage_meshes=None):
        from .overlap import ChainedLoss, _next_prefix

        if optax is None:  # pragma: no cover - optax baked into image
            raise RuntimeError("make_pipeline_train_step needs optax")
        if not isinstance(chain, ChainedLoss):
            chain = ChainedLoss(list(chain))
        if len(chain.stages) < 2:
            raise ValueError(
                "make_pipeline_train_step needs at least 2 stages; a "
                "single-stage loss trains faster through "
                "make_train_step")
        self._chain = chain
        self._optimizer = optimizer
        self._S = len(chain.stages)
        self._stage_meshes = None if stage_meshes is None else \
            _validate_stage_meshes(stage_meshes, self._S)
        if self._stage_meshes is not None:
            # Placed mode never touches the global replica mesh; keep a
            # reference mesh for sizing (batch divisibility = the
            # per-stage replica count).
            self._mesh = self._stage_meshes[0]
        else:
            self._mesh = mesh or _state.mesh()
        self._m = int(num_microbatches)
        self._average = average
        self._fusion_threshold = fusion_threshold
        self._donate = donate
        from .overlap import _is_cpu_mesh

        # Data-parallel width: the replica axis alone (a placed
        # sub-mesh may carry a model axis on top).
        self._replicas = int(self._mesh.shape[REPLICA_AXIS]) \
            if REPLICA_AXIS in self._mesh.axis_names \
            else int(self._mesh.devices.size)
        self._plan = schedule_plan(self._S, self._m, schedule, interleave)
        self._prefix = _next_prefix()
        self._built = False
        self._bucket_plan = None
        self._cpu_mesh = _is_cpu_mesh(self._mesh)

    # -- introspection (tests / bench) ------------------------------------
    @property
    def plan(self) -> PipelinePlan:
        return self._plan

    @property
    def schedule(self) -> str:
        return self._plan.schedule

    @property
    def bucket_count(self) -> Optional[int]:
        return None if self._bucket_plan is None \
            else self._bucket_plan.n_buckets

    @property
    def stage_meshes(self) -> Optional[tuple]:
        """The per-stage placement, or ``None`` when every stage shares
        the global replica mesh."""
        return self._stage_meshes

    @property
    def placed(self) -> bool:
        return self._stage_meshes is not None

    # -- build -------------------------------------------------------------
    def _check_batch(self, batch) -> None:
        n = self._replicas
        for leaf in jax.tree_util.tree_leaves(batch):
            axis = int(leaf.shape[0])
            if axis % self._m != 0:
                raise ValueError(_indivisible_message("batch", axis,
                                                      self._m))
            if axis % n != 0:
                raise ValueError(
                    f"batch axis of size {axis} is not divisible by "
                    f"the replica count {n} (the data-parallel shard)")
            if (axis // n) % self._m != 0:
                raise ValueError(
                    f"batch axis {axis} shards to {axis // n} rows per "
                    f"replica; " + _indivisible_message(
                        "per-replica batch", axis // n, self._m))

    def _build(self, params, batch) -> None:
        from .data import _fusion_threshold_bytes
        from .overlap import _build_plan

        self._built = True
        st = _state.global_state()
        if st.multiprocess:
            raise ValueError(
                "make_pipeline_train_step is single-process "
                "(single-controller SPMD) in this build; multi-process "
                "pipeline scheduling composes with the mp overlap path "
                "in a later round (docs/performance.md).")
        params = self._chain._check_params(params)
        self._check_batch(batch)
        leaves, self._treedef = jax.tree_util.tree_flatten(list(params))
        if self._stage_meshes is None:
            seg_avals = [[SimpleNamespace(shape=tuple(x.shape),
                                          dtype=jnp.dtype(x.dtype))
                          for x in jax.tree_util.tree_leaves(p)]
                         for p in params]
            thr = self._fusion_threshold
            if thr is None:
                try:
                    thr = int(st.coordinator.fusion_threshold)
                except Exception:  # noqa: BLE001 — size-check contexts
                    thr = _fusion_threshold_bytes()
            self._bucket_plan = _build_plan(seg_avals, int(thr))
        self._preflight(params, batch)
        self._build_programs()
        self._apply = self._build_apply(params)

    def _preflight(self, params, batch) -> None:
        """hvd-mem pre-flight (docs/memory.md): size the schedule's
        peak carries via ``jax.eval_shape`` over the stage chain — no
        compute, no compile — and WARN before the first dispatch when
        activations + stage params + gradient accumulators exceed the
        advertised per-rank HBM capacity.  Best-effort: a stage whose
        body resists shape abstraction skips the check, never the
        build."""
        if _oom.advertised_capacity() is None:
            return
        try:
            m = self._m

            def sds_nbytes(tree) -> int:
                total = 0
                for leaf in jax.tree_util.tree_leaves(tree):
                    total += int(jnp.dtype(leaf.dtype).itemsize) * int(
                        math.prod(leaf.shape) or 1)
                return total

            def mb(x):
                return jax.ShapeDtypeStruct(
                    (int(x.shape[0]) // m,) + tuple(x.shape[1:]),
                    x.dtype)

            mb_batch = jax.tree_util.tree_map(mb, batch)
            stages = self._chain.stages
            carry = jax.eval_shape(
                lambda p, b: stages[0](p, None, b), params[0], mb_batch)
            max_carry = sds_nbytes(carry)
            for k in range(1, self._S - 1):
                carry = jax.eval_shape(
                    lambda p, c, b, k=k: stages[k](p, c, b),
                    params[k], carry, mb_batch)
                max_carry = max(max_carry, sds_nbytes(carry))
            world = int(self._mesh.devices.size)
            pbytes = sum(_mem.tree_nbytes(p) for p in params)
            # Per-DEVICE figure vs the per-device capacity: carries
            # and gradient accumulators shard over the replica axis
            # (global/world per device); params are replicated (full
            # copy per device).
            predicted = (self._plan.peak_activations * max_carry
                         // max(1, world) + 2 * pbytes)
            _oom.preflight_warn(
                predicted, "make_pipeline_train_step",
                f"{self._plan.peak_activations} peak carries x "
                f"{max_carry} B / {world} devices + stage params + "
                f"accumulators ({self._plan.schedule}, m={m})")
        except Exception:  # noqa: BLE001 — pre-flight must never
            pass           # break a build eval_shape cannot model

    def _build_programs(self) -> None:
        stages = self._chain.stages
        S, m = self._S, self._m
        sm = _compat.shard_map
        R = P(REPLICA_AXIS)

        def mesh_of(k: int):
            # Placed: stage k's executables live on stage k's sub-mesh.
            if self._stage_meshes is not None:
                return self._stage_meshes[k]
            return self._mesh

        def mb_slice(batch, i):
            def sl(x):
                xs = x.reshape((m, x.shape[0] // m) + x.shape[1:])
                return jax.lax.dynamic_index_in_dim(xs, i, keepdims=False)
            return jax.tree_util.tree_map(sl, batch)

        def pr(tree):
            return jax.tree_util.tree_map(lambda x: x[None], tree)

        def acc_add(acc, g):
            return jax.tree_util.tree_map(jnp.add, acc, g)

        # Forward programs: one per stage, microbatch index traced so
        # every microbatch reuses ONE executable per stage.
        def make_fwd(k):
            def fwd(p, carry, batch, i):
                return stages[k](p, carry, mb_slice(batch, i))
            return fwd

        def fwd0(p, batch, i):
            return stages[0](p, None, mb_slice(batch, i))

        def fwd_last(p, carry, batch, i):
            loss = stages[S - 1](p, carry, mb_slice(batch, i))
            return jax.lax.pmean(loss, REPLICA_AXIS)

        self._fwd: List[Callable] = [None] * S
        self._fwd[0] = _AotProgram("pipeline/F0", jax.jit(
            sm(fwd0, mesh=mesh_of(0), in_specs=(P(), R, P()),
               out_specs=R, check_vma=False)))
        for k in range(1, S - 1):
            self._fwd[k] = _AotProgram(f"pipeline/F{k}", jax.jit(
                sm(make_fwd(k), mesh=mesh_of(k),
                   in_specs=(P(), R, R, P()), out_specs=R,
                   check_vma=False)))
        self._fwd[S - 1] = _AotProgram(f"pipeline/F{S - 1}", jax.jit(
            sm(fwd_last, mesh=mesh_of(S - 1), in_specs=(P(), R, R, P()),
               out_specs=P(), check_vma=False)))

        # Backward programs: jax.vjp with in-segment rematerialization
        # (the overlap substrate), gradient ACCUMULATION folded in (the
        # `acc` variants donate and replace the running sum — one
        # dispatch per action, no separate eager adds).  Backwards run
        # in microbatch order, so `acc` chains are the same arithmetic
        # under every schedule.
        def make_bwd_last(with_acc):
            def bwd(p, carry, batch, i, *acc):
                def f(p, c):
                    return stages[S - 1](p, c, mb_slice(batch, i))
                out, vjp = jax.vjp(f, p, carry)
                g, ct = vjp(jnp.ones_like(out))
                g = pr(g)
                if with_acc:
                    g = acc_add(acc[0], g)
                return g, ct
            return bwd

        def make_bwd_mid(k, with_acc):
            def bwd(p, carry, batch, i, ct_in, *acc):
                def f(p, c):
                    return stages[k](p, c, mb_slice(batch, i))
                _, vjp = jax.vjp(f, p, carry)
                g, ct = vjp(ct_in)
                g = pr(g)
                if with_acc:
                    g = acc_add(acc[0], g)
                return g, ct
            return bwd

        def make_bwd_first(with_acc):
            def bwd(p, batch, i, ct_in, *acc):
                def f(p):
                    return stages[0](p, None, mb_slice(batch, i))
                _, vjp = jax.vjp(f, p)
                (g,) = vjp(ct_in)
                g = pr(g)
                if with_acc:
                    g = acc_add(acc[0], g)
                return g
            return bwd

        def jit_b(name, k, fn, in_specs, out_specs, donate):
            return _AotProgram(name, jax.jit(
                sm(fn, mesh=mesh_of(k), in_specs=in_specs,
                   out_specs=out_specs, check_vma=False),
                donate_argnums=donate), donate=donate)

        self._bwd: List[Callable] = [None] * S
        self._bwd_acc: List[Callable] = [None] * S
        self._bwd[S - 1] = jit_b(f"pipeline/B{S - 1}", S - 1,
                                 make_bwd_last(False),
                                 (P(), R, R, P()), (R, R), (1,))
        self._bwd_acc[S - 1] = jit_b(f"pipeline/B{S - 1}acc", S - 1,
                                     make_bwd_last(True),
                                     (P(), R, R, P(), R), (R, R), (1, 4))
        for k in range(1, S - 1):
            self._bwd[k] = jit_b(f"pipeline/B{k}", k,
                                 make_bwd_mid(k, False),
                                 (P(), R, R, P(), R), (R, R), (1, 4))
            self._bwd_acc[k] = jit_b(f"pipeline/B{k}acc", k,
                                     make_bwd_mid(k, True),
                                     (P(), R, R, P(), R, R), (R, R),
                                     (1, 4, 5))
        self._bwd[0] = jit_b("pipeline/B0", 0, make_bwd_first(False),
                             (P(), R, P(), R), R, (3,))
        self._bwd_acc[0] = jit_b("pipeline/B0acc", 0,
                                 make_bwd_first(True),
                                 (P(), R, P(), R, R), R, (3, 4))

        self._loss_mean = jax.jit(lambda xs: jnp.mean(jnp.stack(xs)))
        # Per-microbatch index constants, built once: the tick loop is
        # the dispatch critical path, and S*m fresh host→device
        # transfers per step would sit right on it.
        self._mb_idx = [jnp.asarray(i, jnp.int32) for i in range(m)]

    def _build_apply(self, params) -> Optional[Callable]:
        optimizer = self._optimizer
        average = self._average
        m = self._m

        def scale(g, opt_state, stage_params):
            leaves, tdef = jax.tree_util.tree_flatten(g)
            # Accumulated as RAW per-microbatch per-replica sums; the
            # mean-loss gradient divides by microbatches × replicas
            # (exactly the monolithic mean-loss denominator).
            denom = jnp.float32(m)
            if average:
                denom = denom * jax.lax.psum(jnp.ones((), jnp.float32),
                                             REPLICA_AXIS)
            leaves = [x / denom.astype(x.dtype) for x in leaves]
            g = jax.tree_util.tree_unflatten(tdef, leaves)
            updates, opt_state = optimizer.update(g, opt_state,
                                                  stage_params)
            return optax.apply_updates(stage_params, updates), opt_state

        if self._stage_meshes is None:
            def apply_body(grads_pr, opt_state, prm):
                g = jax.tree_util.tree_map(
                    lambda x: jnp.squeeze(x, 0), grads_pr)
                return scale(g, opt_state, prm)

            donate = (0, 1, 2) if self._donate else (0,)
            return jax.jit(_compat.shard_map(
                apply_body, mesh=self._mesh,
                in_specs=(P(REPLICA_AXIS), P(), P()),
                out_specs=(P(), P()), check_vma=False),
                donate_argnums=donate)

        # Placed: one fused reduce+apply program PER STAGE on the
        # stage's own sub-mesh — the cross-replica psum happens inside
        # the same executable as the optimizer update (hvd-fuse), so
        # the 1F1B scheduler can dispatch it the moment the stage's
        # last backward is in flight and the reduction streams into
        # the other stages' remaining ticks.
        def apply_body(grads_pr, opt_state, prm):
            g = jax.tree_util.tree_map(
                lambda x: jnp.squeeze(x, 0), grads_pr)
            g = jax.lax.psum(g, REPLICA_AXIS)
            return scale(g, opt_state, prm)

        self._apply_s = []
        for k, mk in enumerate(self._stage_meshes):
            jitted = jax.jit(_compat.shard_map(
                apply_body, mesh=mk,
                in_specs=(P(REPLICA_AXIS), P(), P()),
                out_specs=(P(), P()), check_vma=False))
            launch_bytes = sum(
                _mem_planner.fused_group_bytes(tuple(leaf.shape), 1,
                                               dtype=leaf.dtype)
                for leaf in jax.tree_util.tree_leaves(params[k]))
            self._apply_s.append(_fused.FusedProgram(
                f"pipeline/apply{k}", jitted, mesh=mk, chunks=1,
                launch_bytes=launch_bytes))
        return None

    # -- execution ---------------------------------------------------------
    def __call__(self, params, opt_state, batch):
        if not self._built:
            self._build(params, batch)
        if self._stage_meshes is not None:
            if (not isinstance(opt_state, (list, tuple))
                    or len(opt_state) != self._S):
                raise ValueError(
                    "stage_meshes placement needs a PER-STAGE opt_state "
                    "sequence (e.g. [optimizer.init(p) for p in "
                    f"params]); got {type(opt_state).__name__} for "
                    f"{self._S} stages")
            params = [_to_mesh(p, mk, P())
                      for p, mk in zip(params, self._stage_meshes)]
            opt_state = [_to_mesh(o, mk, P())
                         for o, mk in zip(opt_state, self._stage_meshes)]
        return self._run(list(params), opt_state, batch)

    def _run(self, params, opt_state, batch):
        from .overlap import (_InflightWindow, _max_inflight,
                              dispatch_bucket_segment)

        st = _state.global_state()
        tl = st.timeline
        S, m = self._S, self._m
        plan = self._plan
        stream = plan.schedule == "1f1b"
        meshes = self._stage_meshes
        R = P(REPLICA_AXIS)
        if meshes is not None:
            # Each stage reads microbatches from its own sub-mesh copy
            # of the batch (one transfer per stage per step, off the
            # per-tick critical path).
            batches = [_to_mesh(batch, mk, R) for mk in meshes]
            applied: List = [None] * S
        window = _InflightWindow(_max_inflight()) if self._cpu_mesh \
            else None
        carries = {}          # (stage, mb) -> boundary activation
        carry_nb = {}         # (stage, mb) -> ledger bytes (hvd-mem)
        cts = {}              # (stage, mb) -> cotangent from stage's B
        accs: List = [None] * S
        losses: List = [None] * m
        handles: List[Optional[int]] = [None] * (
            0 if self._bucket_plan is None else self._bucket_plan.n_leaves)
        live = peak = 0
        live_b = peak_b = 0
        mem_on = _mem.enabled()

        def born(key, out):
            # A carry was born: count it AND charge its bytes to the
            # ledger (pipeline.activations) — the figure that actually
            # bounds the schedule (peak carries x carry size).
            nonlocal live_b, peak_b
            carries[key] = out
            if mem_on:
                nb = _mem.tree_nbytes(out)
                carry_nb[key] = nb
                live_b += nb
                peak_b = max(peak_b, live_b)
                _mem.ledger.alloc("pipeline.activations", nb)

        def consumed(key):
            nonlocal live_b
            out = carries.pop(key)
            nb = carry_nb.pop(key, 0)
            if nb:
                live_b -= nb
                _mem.ledger.free("pipeline.activations", nb)
            return out

        def stage_batch(s):
            return batch if meshes is None else batches[s]

        def carry_in(s, mb):
            # Stage s's input carry; placed mode moves it onto stage
            # s's sub-mesh ONCE (the stored copy serves s's backward
            # too).
            c = carries[(s - 1, mb)]
            if meshes is not None:
                c = _to_mesh(c, meshes[s], R)
                carries[(s - 1, mb)] = c
            return c

        def ct_in(s, mb):
            ct = cts.pop((s + 1, mb))
            if meshes is not None:
                ct = _to_mesh(ct, meshes[s], R)
            return ct

        for tick in plan.ticks:
            for a in tick:
                i = self._mb_idx[a.mb]
                s = a.stage
                if a.phase == "F":
                    if s == 0:
                        out = self._fwd[0](params[0], stage_batch(0), i)
                        born((0, a.mb), out)
                        live += 1
                    elif s == S - 1:
                        out = losses[a.mb] = self._fwd[s](
                            params[s], carry_in(s, a.mb),
                            stage_batch(s), i)
                    else:
                        out = self._fwd[s](
                            params[s], carry_in(s, a.mb),
                            stage_batch(s), i)
                        born((s, a.mb), out)
                        live += 1
                    peak = max(peak, live)
                else:
                    prog = self._bwd_acc[s] if accs[s] is not None \
                        else self._bwd[s]
                    extra = (accs[s],) if accs[s] is not None else ()
                    if s == S - 1:
                        out = prog(params[s], consumed((s - 1, a.mb)),
                                   stage_batch(s), i, *extra)
                        accs[s], cts[(s, a.mb)] = out
                        live -= 1
                    elif s == 0:
                        out = accs[0] = prog(params[0], stage_batch(0),
                                             i, ct_in(0, a.mb), *extra)
                    else:
                        out = prog(params[s], consumed((s - 1, a.mb)),
                                   stage_batch(s), i, ct_in(s, a.mb),
                                   *extra)
                        accs[s], cts[(s, a.mb)] = out
                        live -= 1
                    if stream and a.mb == m - 1:
                        if meshes is not None:
                            # Placed: the stage's fused reduce+apply
                            # (in-program psum + optimizer update on
                            # the stage sub-mesh) dispatches NOW —
                            # the reduction streams into the other
                            # stages' remaining ticks.
                            applied[s] = self._apply_s[s](
                                accs[s], opt_state[s], params[s])
                        else:
                            # This stage's LAST backward: its buckets
                            # negotiate/replay NOW, as partial cycles —
                            # the reduction streams into the remaining
                            # schedule ticks (the bubble).
                            dispatch_bucket_segment(
                                self._prefix,
                                self._bucket_plan.segments[s],
                                jax.tree_util.tree_leaves(accs[s]),
                                handles, tl)
                if window is not None:
                    window.admit(out)

        # Exposed-bubble window: everything the host pays between the
        # LAST schedule tick's dispatch and the reduced gradients being
        # ready.  The GPipe-ordered leg pays its flush fence, the
        # serialized bucket dispatch AND the whole reduction inside
        # this window; the 1F1B leg's reductions were dispatched inside
        # the schedule, so only the residual drain shows up —
        # `bench.py --mode pipeline` gates 1f1b strictly below gpipe.
        t0 = time.perf_counter()
        if not stream:
            # GPipe-ordered comparator: reduction serialized after the
            # full flush — fence every accumulated gradient, then
            # dispatch the same reductions (buckets, or the per-stage
            # fused reduce+apply programs under placement).
            jax.block_until_ready([jax.tree_util.tree_leaves(acc)
                                   for acc in accs])
            for s in range(S):
                if meshes is not None:
                    applied[s] = self._apply_s[s](
                        accs[s], opt_state[s], params[s])
                else:
                    dispatch_bucket_segment(
                        self._prefix, self._bucket_plan.segments[s],
                        jax.tree_util.tree_leaves(accs[s]), handles, tl)

        if meshes is not None:
            new_params = [a[0] for a in applied]
            new_opt = [a[1] for a in applied]
            jax.block_until_ready(
                jax.tree_util.tree_leaves(new_params))
        else:
            from ..ops import collective as C

            reduced = [C.take_async(h) for h in handles]
            jax.block_until_ready(reduced)
        if _telemetry.enabled():
            _M_BUBBLE.observe(time.perf_counter() - t0)
            _M_MICROBATCHES.inc(m)
            _M_INFLIGHT.set(peak)
            _M_INFLIGHT_BYTES.set(peak_b)
        if mem_on:
            _mem.ledger.note_step()
        loss = self._loss_mean(losses)
        if meshes is not None:
            return new_params, new_opt, loss
        red_tree = jax.tree_util.tree_unflatten(self._treedef, reduced)
        new_params, opt_state = self._apply(red_tree, opt_state, params)
        return new_params, opt_state, loss


def make_pipeline_train_step(
    stages,
    optimizer,
    *,
    num_microbatches: int,
    schedule: Optional[str] = None,
    interleave: Optional[int] = None,
    mesh=None,
    average: bool = True,
    fusion_threshold: Optional[int] = None,
    donate: bool = False,
    stage_meshes: Optional[Sequence] = None,
):
    """Build the host-scheduled MPMD pipeline train step.

    Args:
      stages: a :class:`~horovod_tpu.parallel.overlap.ChainedLoss` (or
        a sequence of ``stage(stage_params, carry, microbatch)``
        callables — stage 0 receives ``carry=None``, the last stage
        returns the scalar per-replica microbatch loss).
      optimizer: an optax ``GradientTransformation``.
      num_microbatches: pipeline depth-filling factor; every batch
        leaf's leading axis must divide by it (and the microbatch by
        the replica count) — violations raise naming the axis size and
        the nearest valid counts.
      schedule: ``1f1b`` (default; ``HVD_TPU_PIPELINE_SCHEDULE``) or
        ``gpipe`` — the all-forwards-then-all-backwards dispatch of
        the SAME executables with the reduction serialized after a
        flush fence (the comparator; bitwise-identical results).
      interleave: virtual stages per executor
        (``HVD_TPU_PIPELINE_INTERLEAVE``, default 1); must divide the
        stage count.
      mesh: replica mesh (data-parallel axis); defaults to the global
        one.  The batch is sharded over it; gradients reduce through
        the dynamic partial-cycle path per stage.
      average: divide the accumulated gradients by
        ``num_microbatches × replicas`` (the mean-loss gradient);
        ``False`` divides by ``num_microbatches`` only.
      fusion_threshold: per-stage bucket granularity in bytes
        (defaults to the coordinator's live threshold).
      donate: donate params/opt_state into the apply program.
      stage_meshes: optional per-stage sub-mesh placement (one mesh
        per stage, e.g. from :func:`stage_submeshes`) — the mp ×
        pipeline composition.  Each stage's executables compile over
        its own sub-mesh (which may carry a model axis on top of the
        replica axis), boundary carries/cotangents move between
        sub-meshes on the host, and each stage's gradients reduce
        through its own fused reduce+apply program instead of the
        dynamic bucket path.  Requires ``opt_state`` to be a
        per-stage sequence (``[optimizer.init(p) for p in params]``);
        ``donate`` then covers the backward programs only.

    Returns:
      ``step(params, opt_state, batch) -> (params, opt_state, loss)``
      with ``params`` a per-stage sequence; ``loss`` is the mean over
      microbatches of the pmean'd per-microbatch loss.  ``step.plan``
      exposes the resolved :class:`PipelinePlan` (the dryrun surface).
    """
    return _PipelineStep(stages, optimizer, mesh, num_microbatches,
                         schedule, interleave, average, fusion_threshold,
                         donate, stage_meshes)


# ---------------------------------------------------------------------------
# The original GPipe scan (one compiled program over the pipe axis)
# ---------------------------------------------------------------------------

def gpipe(stage_fn: Callable, stage_params, x, *, num_microbatches: int,
          axis_name: str = PIPE_AXIS):
    """Run ``x`` through ``n_stages`` pipelined applications of
    ``stage_fn``.

    Args:
      stage_fn: ``stage_fn(stage_params, x_mb) -> y_mb`` (shape-
        preserving).  Called by every device on its own stage's params.
      stage_params: this device's stage parameters (from shard_map over
        the pipe axis).
      x: the full per-pipeline batch ``[batch, ...]`` (replicated across
        the pipe axis); ``batch`` must divide by ``num_microbatches``.
      num_microbatches: pipeline depth-filling factor.

    Returns:
      ``y`` with the same shape as ``x``, valid on every stage (the last
      stage's results are summed across the axis, other stages contribute
      zeros — one psum at the end).
    """
    n = _compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = num_microbatches
    if x.shape[0] % m != 0:
        raise ValueError(_indivisible_message("batch", x.shape[0], m))
    mb = x.shape[0] // m
    xs = x.reshape((m, mb) + x.shape[1:])
    # send i -> i+1 (last stage's send is dropped into stage 0, ignored)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        recv, outs = carry
        # Stage 0 draws the next microbatch from the batch; later stages
        # consume what arrived from the left neighbor.
        mb_idx = jnp.clip(t, 0, m - 1)
        first_in = jax.lax.dynamic_index_in_dim(xs, mb_idx, keepdims=False)
        x_in = jnp.where(idx == 0, first_in, recv)
        y = stage_fn(stage_params, x_in)
        # The last stage finished microbatch t - (n - 1) this tick.
        out_idx = t - (n - 1)
        valid = jnp.logical_and(idx == n - 1, out_idx >= 0)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(valid, y,
                      jax.lax.dynamic_index_in_dim(
                          outs, jnp.clip(out_idx, 0, m - 1),
                          keepdims=False)),
            jnp.clip(out_idx, 0, m - 1), axis=0)
        recv = jax.lax.ppermute(y, axis_name, perm)
        return (recv, outs), None

    ticks = jnp.arange(m + n - 1)
    recv0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    outs0 = jnp.zeros_like(xs)
    (_, outs), _ = _compat.scan(tick, (recv0, outs0), ticks)
    # Only the last stage holds real outputs; share them with one psum.
    outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    outs = jax.lax.psum(outs, axis_name)
    return outs.reshape(x.shape)


def stage_index(axis_name: str = PIPE_AXIS):
    """This device's pipeline stage id (inside shard_map)."""
    return jax.lax.axis_index(axis_name)


def select_stage_params(params_per_stage, *, axis_name: str = PIPE_AXIS):
    """Slice one stage's parameters out of a stacked
    ``[n_stages, ...]``-leading pytree (inside shard_map, replicated
    input)."""
    idx = jax.lax.axis_index(axis_name)
    return jax.tree_util.tree_map(
        lambda leaf: jax.lax.dynamic_index_in_dim(leaf, idx,
                                                  keepdims=False),
        params_per_stage)
