"""Pipeline parallelism: GPipe-style microbatching over a mesh axis.

Beyond-parity extension (SURVEY.md §2.3 "Pipeline parallelism: NO").
Layer blocks shard over :data:`..core.topology.PIPE_AXIS`; a batch is cut
into microbatches that flow stage-to-stage over ICI via ``lax.ppermute``
inside a ``lax.scan`` — the whole schedule is one compiled XLA program, so
the backward pass (reverse scan, reversed permutes) is derived by JAX AD
and is itself pipelined.  Bubble fraction is the usual
``(n_stages - 1) / (n_microbatches + n_stages - 1)``.

Use inside ``shard_map``: every device holds *its stage's* parameters
(same pytree structure, different values) and calls :func:`gpipe` on the
(replicated) batch.  Stage functions must preserve the activation
shape — the natural fit is a stack of identical transformer blocks.
"""

from __future__ import annotations

from typing import Callable

import jax

from ..core import compat as _compat
import jax.numpy as jnp

from ..core.topology import PIPE_AXIS


def gpipe(stage_fn: Callable, stage_params, x, *, num_microbatches: int,
          axis_name: str = PIPE_AXIS):
    """Run ``x`` through ``n_stages`` pipelined applications of
    ``stage_fn``.

    Args:
      stage_fn: ``stage_fn(stage_params, x_mb) -> y_mb`` (shape-
        preserving).  Called by every device on its own stage's params.
      stage_params: this device's stage parameters (from shard_map over
        the pipe axis).
      x: the full per-pipeline batch ``[batch, ...]`` (replicated across
        the pipe axis); ``batch`` must divide by ``num_microbatches``.
      num_microbatches: pipeline depth-filling factor.

    Returns:
      ``y`` with the same shape as ``x``, valid on every stage (the last
      stage's results are summed across the axis, other stages contribute
      zeros — one psum at the end).
    """
    n = _compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = num_microbatches
    if x.shape[0] % m != 0:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"num_microbatches {m}")
    mb = x.shape[0] // m
    xs = x.reshape((m, mb) + x.shape[1:])
    # send i -> i+1 (last stage's send is dropped into stage 0, ignored)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        recv, outs = carry
        # Stage 0 draws the next microbatch from the batch; later stages
        # consume what arrived from the left neighbor.
        mb_idx = jnp.clip(t, 0, m - 1)
        first_in = jax.lax.dynamic_index_in_dim(xs, mb_idx, keepdims=False)
        x_in = jnp.where(idx == 0, first_in, recv)
        y = stage_fn(stage_params, x_in)
        # The last stage finished microbatch t - (n - 1) this tick.
        out_idx = t - (n - 1)
        valid = jnp.logical_and(idx == n - 1, out_idx >= 0)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(valid, y,
                      jax.lax.dynamic_index_in_dim(
                          outs, jnp.clip(out_idx, 0, m - 1),
                          keepdims=False)),
            jnp.clip(out_idx, 0, m - 1), axis=0)
        recv = jax.lax.ppermute(y, axis_name, perm)
        return (recv, outs), None

    ticks = jnp.arange(m + n - 1)
    recv0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    outs0 = jnp.zeros_like(xs)
    (_, outs), _ = _compat.scan(tick, (recv0, outs0), ticks)
    # Only the last stage holds real outputs; share them with one psum.
    outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
    outs = jax.lax.psum(outs, axis_name)
    return outs.reshape(x.shape)


def stage_index(axis_name: str = PIPE_AXIS):
    """This device's pipeline stage id (inside shard_map)."""
    return jax.lax.axis_index(axis_name)


def select_stage_params(params_per_stage, *, axis_name: str = PIPE_AXIS):
    """Slice one stage's parameters out of a stacked
    ``[n_stages, ...]``-leading pytree (inside shard_map, replicated
    input)."""
    idx = jax.lax.axis_index(axis_name)
    return jax.tree_util.tree_map(
        lambda leaf: jax.lax.dynamic_index_in_dim(leaf, idx,
                                                  keepdims=False),
        params_per_stage)
