"""ZeRO-1 data parallelism: optimizer state sharded across replicas.

Beyond-parity extension (the reference — and Horovod generally — keeps
the full optimizer state on every worker; state sharding arrived in the
ecosystem later as ZeRO/FSDP).  On TPU the idiomatic construction is a
direct transcription of the allreduce decomposition: an allreduce IS a
reduce_scatter followed by an all_gather, so instead of

    psum(grads) -> full optimizer update on every replica   (plain DP)

each replica reduces only its 1/N contiguous slice of the flattened
gradient, applies the optimizer to that slice (holding only 1/N of the
optimizer state — for Adam that is 2/N of the model size instead of 2x),
and the updated parameter slices are all_gathered back into the full
replicated parameters:

    g_shard = psum_scatter(flat_grads)        # same bytes as psum
    p_shard, opt_shard = opt.update(g_shard)  # 1/N state, 1/N compute
    params = unravel(all_gather(p_shard))

Wire cost is identical to the fused allreduce (reduce_scatter +
all_gather move the same bytes over ICI); optimizer memory and update
FLOPs drop by the replica count.

Caveat (inherent to ZeRO-1, documented by every implementation): the
optimizer transformation must be *elementwise* (sgd, momentum, adam,
adamw, rmsprop, ... — anything that treats each parameter independently).
Transforms that aggregate across the whole tree (``clip_by_global_norm``)
would see only the local shard and silently train wrong.
:func:`make_zero_train_step` therefore probes the optimizer at build
time — it applies one update to a small vector and to its two halves
independently and requires identical results — and raises for
aggregating chains, naming the alternatives (clip per-element with
``optax.clip``, clip-then-ZeRO is not recoverable per-shard, or pass
``validate_elementwise=False`` to accept shard-local semantics).

Usage::

    zstep = make_zero_train_step(loss_fn, optax.adam(1e-3))
    opt_state = zstep.init(params)              # sharded state
    params, opt_state, loss = zstep.step(params, opt_state, batch)
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P

from ..core import compat as _compat
from ..core import state as _state
from ..core.state import REPLICA_AXIS
from .data import DistributedOptimizer
from .training import _throttle_on_cpu

try:
    import optax
except Exception:  # pragma: no cover - optax is baked into the image
    optax = None


class ZeroTrainStep(NamedTuple):
    """``init(params) -> opt_state`` (sharded) and
    ``step(params, opt_state, batch) -> (params, opt_state, loss)`` —
    or, from :func:`make_zero_train_step_with_state`,
    ``step(params, model_state, opt_state, batch) ->
    (params, model_state, opt_state, loss)``."""

    init: Callable[[Any], Any]
    step: Callable[..., Any]


def _replica_count(mesh) -> int:
    return mesh.shape[REPLICA_AXIS]


def _pad_flat(tree, n: int):
    """Flatten a pytree to one vector zero-padded to a multiple of n.
    Returns (flat, unravel, true_size).  The SINGLE place the layout is
    defined — gradient shards and parameter shards must slice the same
    way or replicas would update the wrong slices."""
    flat, unravel = ravel_pytree(tree)
    true_size = flat.size
    pad = (-flat.size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat, unravel, true_size


def _flat_shard(tree, n: int):
    """This replica's contiguous (1/n) slice of the padded flat vector
    plus the unravel closure and true size.  Must run inside the
    replica-axis trace."""
    flat, unravel, true_size = _pad_flat(tree, n)
    chunk = flat.size // n
    idx = jax.lax.axis_index(REPLICA_AXIS)
    shard = jax.lax.dynamic_slice(flat, (idx * chunk,), (chunk,))
    return shard, unravel, true_size


def _sharded_state_specs(opt_state):
    """Per-leaf PartitionSpecs for a flat-sharded optimizer state:
    vector leaves (momentum/variance slices) shard over the replica
    axis, scalar leaves (e.g. Adam's step count) replicate.  Shared by
    the ZeRO-1 and FSDP builders."""
    return jax.tree_util.tree_map(
        lambda leaf: P(REPLICA_AXIS) if getattr(leaf, "ndim", 0)
        else P(), opt_state)


def _abstract_state_or_raise(optimizer, chunk: int, dtype,
                             feature: str = "ZeRO-1",
                             api_name: str = "make_zero_train_step"):
    """Abstract optimizer state for a (chunk,)-sized slice, refusing
    states whose non-scalar leaves are not per-parameter slices.

    :func:`_sharded_state_specs` shards every ndim>=1 state leaf over
    the replica axis, which is only correct for chunk-sized
    per-parameter vectors (momentum/variance slices).  A leaf of any
    other shape (an array hyperparameter from ``inject_hyperparams``, a
    non-elementwise transform's aggregate) would get silently wrong
    sharding — refuse at build time.  Shared by the ZeRO-1 and FSDP
    builders."""
    abstract = jax.eval_shape(
        optimizer.init, jax.ShapeDtypeStruct((chunk,), dtype))
    bad = [tuple(leaf.shape)
           for leaf in jax.tree_util.tree_leaves(abstract)
           if getattr(leaf, "ndim", 0) >= 1
           and tuple(leaf.shape) != (chunk,)]
    if bad:
        raise ValueError(
            f"{feature} shards every non-scalar optimizer-state "
            "leaf over the replica axis, so each such leaf must "
            f"be one ({chunk},)-shaped per-parameter slice; the "
            f"given optimizer's state has leaves of shape {bad}. "
            "This usually means a non-elementwise transform or "
            "an array-valued hyperparameter "
            "(optax.inject_hyperparams) — keep those outside "
            f"{api_name} (see parallel/zero.py docstring).")
    return abstract


def _check_elementwise(optimizer, feature: str = "ZeRO-1",
                       api_name: str = "make_zero_train_step") -> None:
    """Build-time probe for the elementwise-optimizer precondition.

    An elementwise transform updates a concatenated vector exactly as it
    updates the parts with independent states — which is precisely how
    the sharded builders run it (each replica updates its shard with its
    shard of state).  A transform that aggregates across the tree
    (``clip_by_global_norm``: the norm of a half differs from the norm
    of the whole) fails the probe and would silently train wrong.

    Probe values are large (~1e4) so norm-dependent transforms with any
    realistic threshold take their data-dependent branch.  Transforms
    whose ``update`` needs extra arguments (GradientTransformationExtraArgs)
    cannot be probed and are skipped with a warning.
    """
    import warnings

    import numpy as np

    probe = jnp.asarray(np.linspace(1.0e4, -3.0e4, 16, dtype=np.float32))
    try:
        full, _ = optimizer.update(probe, optimizer.init(probe), probe)
        parts = []
        for part in (probe[:8], probe[8:]):
            up, _ = optimizer.update(part, optimizer.init(part), part)
            parts.append(np.asarray(up))
        full = np.asarray(full)
    except TypeError as e:
        warnings.warn(
            f"{api_name} could not probe the optimizer for the "
            f"elementwise precondition ({e}); proceeding unchecked — "
            "ensure no transform aggregates across parameters "
            "(see horovod_tpu/parallel/zero.py docstring)")
        return
    if not np.allclose(full, np.concatenate(parts), rtol=1e-5, atol=1e-5):
        raise ValueError(
            f"{feature} requires an ELEMENTWISE optimizer: updating a "
            "vector must equal updating its parts independently, because "
            "each replica will only ever see its 1/N shard of the "
            "gradients and optimizer state.  The given optax chain "
            "failed that probe — it aggregates across parameters (e.g. "
            "optax.clip_by_global_norm computes the GLOBAL gradient "
            f"norm, but under {feature} each replica would clip by its "
            "shard's norm, silently training wrong).  Alternatives: "
            "clip per-element with optax.clip(delta); clip by global "
            "norm OUTSIDE the optimizer on the full gradient before "
            f"{feature} sees it; or pass validate_elementwise=False to "
            "accept shard-local semantics.")


def make_zero_train_step(
    loss_fn,
    optimizer,
    mesh=None,
    average: bool = True,
    compression=None,
    donate: bool = True,
    has_state: bool = False,
    validate_elementwise: bool = True,
) -> ZeroTrainStep:
    """Build a ZeRO-1 data-parallel train step over the replica mesh.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` on the local shard —
        or, with ``has_state=True``, ``loss_fn(params, model_state,
        batch) -> (scalar, new_model_state)`` (BatchNorm-style models;
        the returned state is pmean-synchronized like
        :func:`~horovod_tpu.parallel.training.make_train_step_with_state`).
      optimizer: an elementwise optax ``GradientTransformation`` (or a
        :class:`DistributedOptimizer` wrapping one — its averaging flag
        and compression are honored; the reduction here is the
        reduce_scatter, so its ``fusion_threshold`` does not apply: the
        flattened gradient IS one maximal fusion bucket).
      mesh: replica mesh; defaults to the global one from ``init()``.
      average: average (True) or sum (False) gradients across replicas.
      compression: ``hvd.Compression.{bf16,fp16}`` casts the gradient
        down for the reduce_scatter wire (the parameter all_gather stays
        uncompressed — it carries the master weights).

    Returns:
      :class:`ZeroTrainStep` with sharded ``init`` and jitted ``step``.
      The optimizer state returned by ``init``/``step`` is laid out as
      flat vectors sharded over the replica axis — treat it as opaque
      (checkpoint it like any pytree; its sharding round-trips).
    """
    mesh = mesh or _state.mesh()
    n = _replica_count(mesh)

    if isinstance(optimizer, DistributedOptimizer):
        average = optimizer._average
        if optimizer._compression is not None:
            compression = optimizer._compression
        optimizer = optimizer._inner

    if validate_elementwise:
        _check_elementwise(optimizer)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=has_state)

    def per_replica_init(params):
        p_shard, _, _ = _flat_shard(params, n)
        return optimizer.init(p_shard)

    def per_replica_step(params, model_state, opt_state, batch):
        if has_state:
            (loss, model_state), grads = grad_fn(params, model_state,
                                                 batch)
            # Synchronized BatchNorm: stats average over replicas on the
            # same compiled collective schedule as the gradients.
            model_state = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x, REPLICA_AXIS), model_state)
        else:
            loss, grads = grad_fn(params, batch)
        flat_g, _, _ = _pad_flat(grads, n)
        ctx = None
        if compression is not None:
            flat_g, ctx = compression.compress(flat_g)
        # reduce_scatter: this replica reduces only its slice — same ICI
        # bytes as the psum in plain DP, 1/N of the optimizer work.
        g_shard = jax.lax.psum_scatter(
            flat_g.reshape(n, flat_g.size // n), REPLICA_AXIS,
            scatter_dimension=0)
        if compression is not None:
            g_shard = compression.decompress(g_shard, ctx)
        if average:
            g_shard = g_shard / n
        p_shard, unravel_p, true_size = _flat_shard(params, n)
        updates, opt_state = optimizer.update(g_shard, opt_state, p_shard)
        p_shard = optax.apply_updates(p_shard, updates)
        # all_gather the updated slices back into the full parameters.
        flat_p = jax.lax.all_gather(p_shard, REPLICA_AXIS, axis=0,
                                    tiled=True)
        params = unravel_p(flat_p[:true_size])
        loss = jax.lax.pmean(loss, REPLICA_AXIS)
        if has_state:
            return params, model_state, opt_state, loss
        return params, opt_state, loss

    # The per-leaf state specs (_sharded_state_specs) depend on the
    # state's structure, which optax only reveals given the
    # (chunk-sized) param slice, so the jitted programs are built
    # lazily and cached by state structure.
    _state_specs = _sharded_state_specs

    init_cache: dict = {}

    def init(params):
        leaves = jax.tree_util.tree_leaves(params)
        total = sum(l.size for l in leaves)
        chunk = -(-total // n)
        dtype = jnp.result_type(*[l.dtype for l in leaves])
        key = (chunk, str(dtype))
        if key not in init_cache:
            abstract = _abstract_state_or_raise(optimizer, chunk, dtype)
            init_cache[key] = jax.jit(_compat.shard_map(
                per_replica_init, mesh=mesh,
                in_specs=(P(),), out_specs=_state_specs(abstract),
                check_vma=False))
        return init_cache[key](params)

    step_cache: dict = {}

    def _compiled(opt_state):
        specs = _state_specs(opt_state)
        key = jax.tree_util.tree_structure(specs), tuple(
            str(s) for s in jax.tree_util.tree_leaves(
                specs, is_leaf=lambda x: isinstance(x, P)))
        if key not in step_cache:
            if has_state:
                fn = per_replica_step
                in_specs = (P(), P(), specs, P(REPLICA_AXIS))
                out_specs = (P(), P(), specs, P())
                donate_argnums = (0, 1, 2) if donate else ()
            else:
                def fn(params, opt_state, batch):
                    return per_replica_step(params, None, opt_state,
                                            batch)
                in_specs = (P(), specs, P(REPLICA_AXIS))
                out_specs = (P(), specs, P())
                donate_argnums = (0, 1) if donate else ()
            jitted = jax.jit(
                _compat.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=False),
                donate_argnums=donate_argnums)
            step_cache[key] = _throttle_on_cpu(jitted, mesh)
        return step_cache[key]

    if has_state:
        def step(params, model_state, opt_state, batch):
            return _compiled(opt_state)(params, model_state, opt_state,
                                        batch)
    else:
        def step(params, opt_state, batch):
            return _compiled(opt_state)(params, opt_state, batch)

    return ZeroTrainStep(init=init, step=step)


def make_zero_train_step_with_state(loss_fn, optimizer, mesh=None,
                                    average: bool = True,
                                    compression=None,
                                    donate: bool = True,
                                    validate_elementwise: bool = True,
                                    ) -> ZeroTrainStep:
    """Stateful-model spelling (BatchNorm etc.) of
    :func:`make_zero_train_step` — ``loss_fn(params, state, batch) ->
    (loss, state)``; ``step(params, model_state, opt_state, batch) ->
    (params, model_state, opt_state, loss)`` — mirroring
    :func:`~horovod_tpu.parallel.training.make_train_step_with_state`."""
    return make_zero_train_step(loss_fn, optimizer, mesh=mesh,
                                average=average, compression=compression,
                                donate=donate, has_state=True,
                                validate_elementwise=validate_elementwise)
