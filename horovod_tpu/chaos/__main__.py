"""CLI for hvd-chaos (docs/chaos.md).

  python -m horovod_tpu.chaos --matrix            run the full no-hang
                                                  scenario matrix (CI
                                                  job ``chaos``)
  python -m horovod_tpu.chaos --matrix --only A B run a subset
  python -m horovod_tpu.chaos --list              print the matrix
  python -m horovod_tpu.chaos --scenario NAME     (child) one local
                                                  scenario in THIS
                                                  process
  python -m horovod_tpu.chaos --node R --np N \\
      --port P --scenario NAME                    (child) one rank of a
                                                  control-plane fleet
"""

from __future__ import annotations

import argparse
import sys

from . import matrix as _matrix


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m horovod_tpu.chaos")
    ap.add_argument("--matrix", action="store_true",
                    help="run the no-hang scenario matrix")
    ap.add_argument("--only", nargs="*", default=None,
                    help="scenario subset for --matrix")
    ap.add_argument("--list", action="store_true",
                    help="list the matrix scenarios")
    ap.add_argument("--scenario", default=None,
                    help="(child) scenario name")
    ap.add_argument("--node", type=int, default=None,
                    help="(child) control-plane fleet rank")
    ap.add_argument("--np", type=int, default=2,
                    help="(child) control-plane fleet size")
    ap.add_argument("--port", type=int, default=0,
                    help="(child) controller port")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    if args.list:
        for s in _matrix.SCENARIOS:
            print(f"{s.name:26s} {s.kind:5s} expect={s.expect:10s} "
                  f"cap={s.cap:.0f}s spec={s.spec!r}")
        return 0
    if args.matrix:
        return _matrix.run_matrix(only=args.only, verbose=args.verbose)
    if args.node is not None:
        if args.node == 0:
            _matrix.run_cp_controller(args.np, args.port)
        else:
            _matrix.run_cp_worker(args.node, args.port, args.np)
        return 0
    if args.scenario:
        fn = _matrix.LOCAL_SCENARIOS.get(args.scenario)
        if fn is None:
            print(f"unknown local scenario {args.scenario!r}",
                  file=sys.stderr)
            return 2
        fn()
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
