"""hvd-chaos: deterministic fault injection at the runtime's real
failure boundaries (docs/chaos.md).

``HVD_TPU_FAULTS="<spec>@seed"`` arms a seeded
:class:`~horovod_tpu.chaos.spec.FaultSchedule`; every hardened layer
asks :func:`fire` at its failure boundary — the transport's frame
send path, the coordinator drain tick, the background checkpoint
writer's tmp-file write, the prefetch stager, the serving front door —
and the schedule answers deterministically (same spec + seed ⇒ the
identical fault sequence, the replay contract).

The no-hang contract this enables (enforced by ``python -m
horovod_tpu.chaos --matrix``, CI job ``chaos``): under every schedule
in the scenario matrix the fleet either fully recovers — results
bitwise-identical to the fault-free run — or fails within a bounded
time with a diagnostic naming the injected fault.  A hang is a test
failure.

Hot-path cost when unarmed: one module-global ``None`` check per
injection point (the schedule loads lazily from the env on first use;
:func:`reload` re-reads it for tests).
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional

from .. import telemetry as _telemetry
from ..telemetry import flight as _flight
from .spec import Fault, FaultSchedule, VALID_SITES, parse  # noqa: F401

_M_INJECTED = _telemetry.counter(
    "chaos.injected", "faults fired by the hvd-chaos schedule")

# None = unarmed (the overwhelmingly common case); loaded lazily.
_schedule: Optional[FaultSchedule] = None
_loaded = False
_rank: Optional[int] = None


def validate_env() -> None:
    """Fail-at-init validation of HVD_TPU_FAULTS (core/state.init):
    a typo'd site/key must abort with the valid list, not surface as a
    silent no-op chaos run."""
    spec = os.environ.get("HVD_TPU_FAULTS")
    if spec:
        parse(spec)


def reload() -> Optional[FaultSchedule]:
    """(Re-)load the schedule from the env — tests repoint
    HVD_TPU_FAULTS mid-process."""
    global _schedule, _loaded, _rank
    spec = os.environ.get("HVD_TPU_FAULTS")
    _schedule = parse(spec) if spec else None
    _loaded = True
    _rank = None
    if _schedule is not None and _schedule.sites():
        print(f"[hvd-chaos] armed: {_schedule.describe()}",
              file=sys.stderr)
    return _schedule


def schedule() -> Optional[FaultSchedule]:
    if not _loaded:
        reload()
    return _schedule


def active() -> bool:
    return schedule() is not None


def _rank_of() -> int:
    """Best-effort rank for rank-filtered clauses (cached; same lazy
    resolution as the flight recorder's)."""
    global _rank
    if _rank is None:
        _rank = _flight._rank_of()
    return _rank


def fire(site: str) -> Optional[Fault]:
    """Account one opportunity at ``site``; returns the
    :class:`Fault` when this opportunity fires.  Every firing is
    logged with its clause + opportunity index — the exact line a
    replay needs — flight-recorded, and counted
    (``chaos.injected``)."""
    sched = _schedule if _loaded else schedule()
    if sched is None:
        return None
    f = sched.fire(site, rank=_rank_of())
    if f is not None:
        _M_INJECTED.inc()
        _flight.record("chaos", f.site, f.n, f.clause)
        print(f"[hvd-chaos] rank {_rank_of()}: fired {f.site}#{f.n} "
              f"(clause {f.clause!r}, seed {sched.seed})",
              file=sys.stderr)
    return f


def sleep_site(site: str) -> bool:
    """Convenience for the pure-delay sites (coord.tick_delay,
    input.stall, transport.delay): sleep the clause's delay when the
    opportunity fires.  Returns whether it fired."""
    f = fire(site)
    if f is None:
        return False
    time.sleep(f.delay)
    return True


def maybe_reorder(site: str, items: List) -> List:
    """coord.reorder: deterministically permute ``items`` (reverse —
    pure in the firing decision) when the opportunity fires.  The
    caller scopes this to a reorder-legal span (freshly negotiated
    responses within one tick; never across a CACHE_FLUSH marker)."""
    if len(items) > 1 and fire(site) is not None:
        return list(reversed(items))
    return items
