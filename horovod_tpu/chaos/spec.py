"""Fault-spec grammar + the deterministic seeded schedule (hvd-chaos).

One env var drives every injection point in the runtime::

    HVD_TPU_FAULTS = "<clause>(;<clause>)*[@<seed>]"
    clause         = <site>(:<key>=<value>)*

Sites are the runtime's REAL failure boundaries (docs/chaos.md):

  transport.drop      frame silently not sent          (lost packet)
  transport.dup       frame sent twice                 (retransmit ghost)
  transport.delay     sleep before the frame goes out  (congestion)
  transport.trunc     partial frame, then connection
                      close                            (reset mid-frame)
  transport.reset     connection closed before the
                      frame                            (peer reset)
  transport.stall     header sent, long pause, then
                      the body                         (slow peer)
  tree.relay_reset    an interior tree node's child
                      link is hard-closed before a
                      downward relay (ops/tree.py)     (interior death)
  coord.tick_delay    sleep before a drain tick        (starved thread)
  coord.reorder       permute a tick's freshly
                      negotiated responses             (jittery fusion)
  ckpt.oserror        transient OSError inside the
                      checkpoint tmp-file write        (flaky disk/ENOSPC)
  input.stall         sleep in the prefetch stager
                      before staging a batch           (slow loader)
  serving.disconnect  report the /generate client as
                      gone mid-generation              (dropped client)

Keys (all optional):

  p=<float>       fire probability per opportunity (default: fire
                  deterministically on the first ``count`` opportunities
                  after ``after``)
  count=<int>     max firings for this clause (default 1 without ``p``,
                  unlimited with it)
  after=<int>     opportunities skipped before the clause arms
                  (default 0)
  delay=<float>   seconds, for the delaying sites (default 0.05)
  rank=<int>      only fire on this global rank (default: every rank)

Determinism (the replay contract, docs/chaos.md): each site keeps an
opportunity counter; the decision for opportunity ``n`` is a pure
function of ``(seed, site, n)`` — probabilistic clauses draw their
uniform from ``sha256(f"{seed}:{site}:{n}")``, never from wall clock or
a shared PRNG stream.  Opportunities at one site occur in a
deterministic order (frames on a socket are sequential, ticks are
sequential, checkpoint writes are FIFO), so the same spec + seed yields
the same fault sequence bit-for-bit — any chaos failure reproduces from
the spec line the firing logged.
"""

from __future__ import annotations

import hashlib
import struct
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

VALID_SITES = (
    "transport.drop",
    "transport.dup",
    "transport.delay",
    "transport.trunc",
    "transport.reset",
    "transport.stall",
    "tree.relay_reset",
    "coord.tick_delay",
    "coord.reorder",
    "ckpt.oserror",
    "input.stall",
    "serving.disconnect",
    "router.replica_kill",
    "router.kill",
)

_DEFAULT_DELAY = 0.05


@dataclass
class Clause:
    """One parsed fault clause."""

    site: str
    p: Optional[float] = None
    count: Optional[int] = None
    after: int = 0
    delay: float = _DEFAULT_DELAY
    rank: Optional[int] = None
    fired: int = 0  # guarded by the schedule's per-site lock

    def describe(self) -> str:
        parts = [self.site]
        if self.p is not None:
            parts.append(f"p={self.p:g}")
        if self.count is not None:
            parts.append(f"count={self.count}")
        if self.after:
            parts.append(f"after={self.after}")
        if self.delay != _DEFAULT_DELAY:
            parts.append(f"delay={self.delay:g}")
        if self.rank is not None:
            parts.append(f"rank={self.rank}")
        return ":".join(parts)


@dataclass
class Fault:
    """One firing decision handed back to an injection point."""

    site: str
    n: int                # the site opportunity index that fired
    delay: float = _DEFAULT_DELAY
    clause: str = ""      # the clause's spec line, for the firing log


def _uniform(seed: int, site: str, n: int) -> float:
    """The pure decision draw: uniform in [0, 1) from
    ``sha256(seed:site:n)`` — no shared stream, no wall clock, so
    concurrent sites can never perturb each other's sequences."""
    h = hashlib.sha256(f"{seed}:{site}:{n}".encode()).digest()
    (v,) = struct.unpack_from("<Q", h)
    return v / 2.0 ** 64


def parse(spec: str) -> "FaultSchedule":
    """Parse ``HVD_TPU_FAULTS``.  Raises ``ValueError`` naming the
    offending clause and the valid sites/keys — same fail-at-init
    policy as every other SPMD env knob (core/state.init)."""
    text = spec.strip()
    seed = 0
    if "@" in text:
        text, _, seed_s = text.rpartition("@")
        try:
            seed = int(seed_s)
        except ValueError:
            raise ValueError(
                f"HVD_TPU_FAULTS: seed {seed_s!r} is not an integer "
                f"(grammar: '<clause>(;<clause>)*[@<seed>]')") from None
    clauses: List[Clause] = []
    for raw in text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        site = parts[0].strip()
        if site not in VALID_SITES:
            raise ValueError(
                f"HVD_TPU_FAULTS: unknown fault site {site!r}; valid "
                f"sites: {', '.join(VALID_SITES)}")
        c = Clause(site=site)
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(
                    f"HVD_TPU_FAULTS: malformed key {kv!r} in clause "
                    f"{raw!r} (expected key=value)")
            k, _, v = kv.partition("=")
            k = k.strip()
            try:
                if k == "p":
                    c.p = float(v)
                    if not 0.0 <= c.p <= 1.0:
                        raise ValueError
                elif k == "count":
                    c.count = int(v)
                elif k == "after":
                    c.after = int(v)
                elif k == "delay":
                    c.delay = float(v)
                elif k == "rank":
                    c.rank = int(v)
                else:
                    raise ValueError(
                        f"HVD_TPU_FAULTS: unknown key {k!r} in clause "
                        f"{raw!r}; valid keys: p, count, after, delay, "
                        f"rank")
            except ValueError as e:
                if str(e).startswith("HVD_TPU_FAULTS"):
                    raise
                raise ValueError(
                    f"HVD_TPU_FAULTS: bad value {v!r} for key {k!r} in "
                    f"clause {raw!r}") from None
        if c.p is None and c.count is None:
            c.count = 1  # a bare clause fires exactly once
        clauses.append(c)
    return FaultSchedule(clauses, seed, spec.strip())


class FaultSchedule:
    """The armed fault schedule: per-site opportunity counters + the
    pure decision function.  ``fire(site)`` is called by every
    injection point; it returns a :class:`Fault` when this opportunity
    fires, else None."""

    def __init__(self, clauses: List[Clause], seed: int,
                 text: str = "") -> None:
        self.seed = seed
        self.text = text
        self._by_site: Dict[str, List[Clause]] = {}
        for c in clauses:
            self._by_site.setdefault(c.site, []).append(c)
        # One lock + counter per site: opportunities at one site are
        # sequential (socket frames, drain ticks, FIFO writes), and a
        # per-site lock keeps unrelated sites from contending.
        self._counts: Dict[str, int] = {s: 0 for s in self._by_site}
        self._locks: Dict[str, threading.Lock] = {
            s: threading.Lock() for s in self._by_site}

    def sites(self) -> List[str]:
        return sorted(self._by_site)

    def describe(self) -> str:
        cs = ";".join(c.describe() for cl in self._by_site.values()
                      for c in cl)
        return f"{cs}@{self.seed}"

    def fire(self, site: str, rank: Optional[int] = None
             ) -> Optional[Fault]:
        """Account one opportunity at ``site``; return the firing
        decision.  Pure in ``(seed, site, opportunity index)`` — see
        the module docstring's determinism contract."""
        clauses = self._by_site.get(site)
        if not clauses:
            return None
        with self._locks[site]:
            n = self._counts[site]
            self._counts[site] = n + 1
            for c in clauses:
                if c.rank is not None and rank is not None \
                        and c.rank != rank:
                    continue
                if n < c.after:
                    continue
                if c.count is not None and c.fired >= c.count:
                    continue
                if c.p is None:
                    fired = True
                else:
                    fired = _uniform(self.seed, site, n) < c.p
                if fired:
                    c.fired += 1
                    return Fault(site=site, n=n, delay=c.delay,
                                 clause=c.describe())
        return None

    def opportunities(self, site: str) -> int:
        lock = self._locks.get(site)
        if lock is None:
            return 0
        with lock:
            return self._counts[site]
