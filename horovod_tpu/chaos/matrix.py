"""The hvd-chaos scenario matrix: the fleet-wide no-hang contract.

``python -m horovod_tpu.chaos --matrix`` runs every scenario below
under a hard per-scenario wall-clock cap and enforces, for each:

* **recover** — the faulted run exits 0 and its ``CHAOS_RESULT``
  digests are IDENTICAL to a fault-free run of the same scenario
  (full recovery, bitwise);
* **diagnostic** — the faulted run ends (within the cap) with a
  nonzero exit AND its output names the injected fault
  (``needle``) — a bounded, diagnosable failure;
* **complete** — a single pass that must simply finish cleanly under
  load (no fault spec; e.g. the request storm).

A run that is still alive at the cap is killed and reported as HANG —
the contract violation this matrix exists to catch.  Every scenario's
fault sequence is deterministic (chaos/spec.py), so a failure
reproduces from the scenario's spec line alone.

Scenario kinds:

* ``cp`` — an np=2/np=3 REAL-process control-plane fleet: one
  controller + workers driving the actual ControllerTransport /
  WorkerTransport / Coordinator / ResponseCache over TCP loopback
  with a drain loop mirroring ops/collective._drain's transport
  sequencing.  This exercises the reconnect protocol, replay rings,
  grace windows, frame deadlines and cache-replica alignment with
  real sockets and real processes — no XLA, so it runs in any
  container (np>1 CPU data-plane collectives need a current jax; the
  CI-gated ``scenario_chaos`` mp leg covers the full-stack training
  variant).  The digest covers every completed negotiation
  ``(step, tensor, response type)`` per rank.
* ``local`` — a single-process scenario with the real jax stack
  (checkpoint writer, prefetch training loop, serving front door);
  digests cover real bytes (checkpoint content, trained parameters,
  generated tokens).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# ---------------------------------------------------------------------------
# The matrix
# ---------------------------------------------------------------------------


@dataclass
class Scenario:
    name: str
    kind: str                  # "cp" | "local"
    expect: str                # "recover" | "diagnostic" | "complete"
    spec: str = ""             # HVD_TPU_FAULTS for the faulted pass
    needle: str = ""           # substring the faulted output must show
    np: int = 2                # cp: process count
    cap: float = 90.0          # wall-clock cap per pass (seconds)
    env: Dict[str, str] = field(default_factory=dict)
    doc: str = ""


SCENARIOS: List[Scenario] = [
    # -- transport (ops/transport.py): the reconnect protocol ------------
    Scenario(
        "transport_reset_worker", "cp", "recover",
        spec="transport.reset:count=1:after=25:rank=1@11",
        needle="session resumed",
        doc="worker's control-plane connection reset mid-run; "
            "reconnect + ring replay; results identical"),
    Scenario(
        "transport_reset_np3", "cp", "recover", np=3,
        spec="transport.reset:count=1:after=25:rank=2@12",
        needle="session resumed", cap=120.0,
        doc="np=3: one of two workers resets; the other is "
            "undisturbed; results identical on all three"),
    Scenario(
        "transport_reset_controller", "cp", "recover",
        spec="transport.reset:count=1:after=25:rank=0@13",
        needle="session resumed",
        doc="controller-side reset of a worker's socket (send path); "
            "grace + reconnect; results identical"),
    Scenario(
        "transport_trunc", "cp", "recover",
        spec="transport.trunc:count=1:after=20:rank=1@14",
        needle="session resumed",
        doc="frame truncated mid-wire then connection reset; the "
            "replay ring re-sends the full frame"),
    Scenario(
        "transport_dup_delay", "cp", "recover",
        spec="transport.dup:count=3:after=10:rank=1;"
             "transport.delay:count=5:after=12:delay=0.05:rank=1@15",
        doc="duplicated + delayed frames; the stream survives "
            "(duplicate REQUEST_BATCH submits are idempotent)"),
    Scenario(
        "transport_drop", "cp", "diagnostic",
        spec="transport.drop:count=1:after=20:rank=1@16",
        needle="was abandoned",
        doc="a silently dropped frame (no reset, so no reconnect): "
            "bounded failure via the withdraw path, naming the op"),
    Scenario(
        "transport_stall", "cp", "recover",
        spec="transport.stall:count=1:after=20:delay=3:rank=1@17",
        needle="frame deadline exceeded",
        env={"HVD_TPU_FRAME_TIMEOUT": "1"},
        doc="slow peer stalls mid-frame past HVD_TPU_FRAME_TIMEOUT: "
            "the deadline names peer+frame, then reconnect recovers"),
    Scenario(
        "grace_expiry", "cp", "diagnostic",
        needle="no reconnect within",
        env={"HVD_TPU_CHAOS_KILL_STEP": "12",
             "HVD_TPU_RECONNECT_GRACE": "1.5"},
        doc="worker dies hard (no reconnect ever comes): the grace "
            "window expires into a diagnostic naming the fault"),
    # -- tree overlay (ops/tree.py): interior-node death + re-parent -----
    Scenario(
        "tree_interior_down", "cp", "recover", np=3, cap=150.0,
        spec="tree.relay_reset:count=1:after=40:rank=1;"
             "transport.reset:count=1:after=30:rank=1@31",
        needle="re-parent",
        env={"HVD_TPU_TREE": "on", "HVD_TPU_TREE_FANOUT": "1",
             "HVD_TPU_RECONNECT_GRACE": "15",
             "HVD_TPU_RECONNECT_DEADLINE": "15"},
        doc="np=3 chain 0<-1<-2: BOTH of the interior's links die "
            "(uplink reset + child-link relay reset); rank 1 resumes "
            "its uplink, rank 2 re-parents to the root via the "
            "session-resume listener; results (and the mid-run fleet "
            "metrics pull) identical to the fault-free tree run"),
    Scenario(
        "tree_leaf_reset", "cp", "recover", np=3, cap=150.0,
        spec="transport.reset:count=1:after=25:rank=2@32",
        needle="session resumed",
        env={"HVD_TPU_TREE": "on", "HVD_TPU_TREE_FANOUT": "1",
             "HVD_TPU_RECONNECT_GRACE": "15",
             "HVD_TPU_RECONNECT_DEADLINE": "15"},
        doc="np=3 chain: the LEAF's uplink to its interior parent is "
            "reset; it re-parents to the root and the stream replay "
            "keeps every cache replica aligned"),
    # -- hvd-tune actuation (tuning/actuation.py) ------------------------
    Scenario(
        "retune_midfault", "cp", "recover",
        spec="transport.reset:count=1:after=26:rank=1@33",
        needle="session resumed",
        env={"HVD_TPU_CHAOS_RETUNE_STEPS": "10,25"},
        doc="hvd-tune RETUNE markers ride the response stream at steps "
            "10 and 25; the worker's connection resets in the window "
            "between a marker's broadcast and its apply boundary — the "
            "session-resume replay must deliver the marker exactly "
            "once (records identical to the fault-free pass: never "
            "lost, never double-applied, fleet-coherent)"),
    # -- coordinator drain loop (ops/collective.py) ----------------------
    Scenario(
        "coord_tick_delay", "cp", "recover", cap=120.0,
        spec="coord.tick_delay:p=0.4:count=20:delay=0.03@18",
        doc="randomly starved drain ticks; slower, never different"),
    Scenario(
        "coord_reorder", "cp", "recover",
        spec="coord.reorder:p=0.5:count=50@19",
        doc="freshly negotiated responses permuted within their tick; "
            "completion set identical"),
    # -- checkpoint writer (utils/checkpoint.py) -------------------------
    Scenario(
        "ckpt_flaky", "local", "recover", cap=240.0,
        spec="ckpt.oserror:count=2@20",
        needle="retrying",
        doc="two transient ENOSPC during the tmp write; the retry "
            "loop lands the identical bytes"),
    Scenario(
        "ckpt_exhaustion", "local", "diagnostic", cap=240.0,
        spec="ckpt.oserror:count=9@21",
        needle="ckpt.oserror",
        env={"HVD_TPU_CKPT_RETRIES": "3"},
        doc="persistent write failure exhausts the retries: "
            "CheckpointError at wait() names the injected fault"),
    # -- prefetch stager (parallel/input.py) -----------------------------
    Scenario(
        "input_stall", "local", "recover", cap=240.0,
        spec="input.stall:count=3:after=2:delay=0.2@22",
        doc="loader stalls on the stager thread; training result "
            "bitwise-identical (prefetch hides latency, never "
            "reorders)"),
    # -- serving front door (serving/server.py) --------------------------
    Scenario(
        "serving_disconnect", "local", "recover", cap=300.0,
        spec="serving.disconnect:count=1@23",
        needle="disconnected mid-generation",
        doc="client vanishes mid-generate: slot released via the "
            "abort path; the NEXT request's completion is identical "
            "to the fault-free run's"),
    Scenario(
        "serving_storm", "local", "complete", cap=300.0,
        doc="a burst of concurrent /generate requests: every one "
            "completes or fails explicitly — the front door never "
            "hangs"),
    Scenario(
        "serving_spec_disconnect", "local", "recover", cap=300.0,
        spec="serving.disconnect:count=1@27",
        needle="disconnected mid-generation",
        doc="client vanishes mid-SPECULATION (draft model + prefix "
            "cache live): the iteration-boundary abort releases the "
            "target AND draft KV slots and decrements the prefix "
            "refcounts; the follow-up request (a prefix-cache hit) "
            "completes identically to the fault-free run"),
    # -- hvd-route fleet router (routing/router.py) ----------------------
    Scenario(
        "router_replica_death", "local", "recover", cap=300.0,
        spec="router.replica_kill:count=1@40",
        needle="failed over",
        doc="two real replicas behind the real Router over real HTTP; "
            "the one that served the first request is drained and "
            "then killed hard — dispatch fails over to the survivor "
            "and every completion is identical to the fault-free "
            "fleet's"),
    Scenario(
        "router_restart", "local", "recover", cap=300.0,
        spec="router.kill:count=1@41",
        needle="severed router connection",
        doc="the real RouterServer runs as a separate PROCESS and is "
            "SIGKILLed mid-generation; the replicas abort the severed "
            "sockets via the client probe (no slot leak), a fresh "
            "router over the same fleet serves the resubmitted "
            "request, and completions are identical to the never-"
            "killed run (the bitwise contract makes the retry the "
            "same answer)"),
]


def find(name: str) -> Scenario:
    for s in SCENARIOS:
        if s.name == name:
            return s
    raise SystemExit(f"unknown chaos scenario {name!r}; "
                     f"--list shows the matrix")


# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------

def _digest(records) -> str:
    """Order-insensitive digest of a run's completion records; the
    recover contract compares it between the faulted and fault-free
    passes."""
    blob = json.dumps(sorted(map(list, records))).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


def _result(rank: int, records) -> None:
    print(f"CHAOS_RESULT rank={rank} n={len(records)} "
          f"digest={_digest(records)}", flush=True)


def _diag(rank: int, message: str) -> None:
    print(f"CHAOS_DIAG rank={rank}: {message}", file=sys.stderr,
          flush=True)
    sys.stdout.flush()
    raise SystemExit(1)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# cp nodes: a real-process control-plane fleet (no XLA)
# ---------------------------------------------------------------------------

CP_TENSORS = 4
CP_STEP_DEADLINE = 8.0
_THRESHOLD = 1 << 20


def _cp_steps() -> int:
    """Steps per cp pass (env-overridable so the tier-1 tree leg can
    run a short fleet; the matrix default stays 40)."""
    return int(os.environ.get("HVD_TPU_CHAOS_CP_STEPS", "40"))


def _cp_layout(np_: int):
    """The tree layout the cp fleet runs under, or None for the flat
    star — the SAME decision rule production init applies
    (ops/tree.tree_active), so HVD_TPU_TREE=on in a scenario's env
    turns the whole fleet into tree mode."""
    from ..ops import tree as _tree

    return _tree.build_layout(np_) if _tree.tree_active(np_) else None


def _cp_req(rank: int, name: str):
    from ..ops import wire
    from ..ops.wire import Request

    return Request(rank, wire.RequestType.ALLREDUCE,
                   wire.DataType.FLOAT32, name, -1, -1, (8,),
                   wire.ReduceOp.SUM, 0, ())


def _cp_names() -> List[str]:
    return [f"t{k}" for k in range(CP_TENSORS)]


# hvd-mem: every cp rank seeds a rank-keyed ledger entry at fleet
# start, so the mid-run FRAME_METRICS / FRAME_METRICS_TREE pull can
# assert the memory gauge family aggregates EXACTLY — per-rank values
# from every rank, fleet min/max/mean bit-for-bit (tests/test_tree.py
# extends its metrics-pull leg over this).
MEM_PROBE_GAUGE = "memory.bytes.chaos.probe"


def _seed_mem_probe(rank: int) -> None:
    from ..memory import ledger as _mem

    _mem.ledger.set("chaos.probe", (rank + 1) << 20)


def _check_mem_gauges(snaps, np_: int) -> None:
    """Controller-side exactness assertion over one completed pull:
    the seeded probe gauge must arrive from EVERY rank with its exact
    per-rank value, and the fleet min/max/mean must be exact integers
    of the seeded arithmetic — any drop or mangling through the tree
    merge is a loud _diag, not a silent coverage gap."""
    from .. import telemetry as _telemetry

    agg = _telemetry.aggregate(snaps).get(MEM_PROBE_GAUGE)
    if agg is None:
        _diag(0, f"metrics pull carried no {MEM_PROBE_GAUGE} gauge "
                 f"(snapshot keys: "
                 f"{sorted(next(iter(snaps.values())))[:8]}...)")
    expect = {r: (r + 1) << 20 for r in range(np_)}
    got = {int(r): int(v) for r, v in agg.get("per_rank", {}).items()}
    if got != expect:
        _diag(0, f"{MEM_PROBE_GAUGE} per-rank mismatch: got {got}, "
                 f"expected {expect}")
    exact = {"min": 1 << 20, "max": np_ << 20,
             "mean": ((np_ + 1) << 20) / 2.0,
             "sum": (np_ * (np_ + 1) // 2) << 20}
    for key, want in exact.items():
        if agg.get(key) != want:
            _diag(0, f"{MEM_PROBE_GAUGE} {key} inexact: "
                     f"{agg.get(key)} != {want}")
    print(f"CHAOS_MEMGAUGES ranks={np_} ok", flush=True)


def run_cp_controller(np_: int, port: int) -> None:
    """Rank 0 of the cp fleet: the real ControllerTransport +
    Coordinator + ResponseCache, driven by a drain loop mirroring
    ops/collective._drain's transport sequencing (expire_grace →
    lost_ranks → flush_unrouted → marker/replay/negotiated →
    broadcast → observe)."""
    from .. import chaos as _chaos
    from ..ops import cache as _cache_mod
    from ..ops import transport as T
    from ..ops.coordinator import Coordinator
    from ..ops.wire import Response, ResponseType

    _seed_mem_probe(0)
    cache = (_cache_mod.ResponseCache(rank=0)
             if _cache_mod.cache_enabled() else None)
    coord = Coordinator(size=np_, fusion_threshold=_THRESHOLD,
                        cache=cache)
    ctrl = T.ControllerTransport(coord, np_, port, tree=_cp_layout(np_))
    ctrl.cache = cache
    records = []
    # hvd-tune: the retune_midfault scenario injects RETUNE markers at
    # fixed steps (HVD_TPU_CHAOS_RETUNE_STEPS); they ride the same
    # broadcast as production markers (ops/collective._coordinator_tick)
    # and every rank records (seq, token, RETUNE) on delivery — digest
    # equality with the fault-free pass proves exactly-once,
    # fleet-coherent application across the fault.
    retune_steps = {int(v) for v in
                    os.environ.get("HVD_TPU_CHAOS_RETUNE_STEPS",
                                   "").replace(";", ",").split(",")
                    if v.strip()}
    retune_pending: List = []
    retune_seq = 0

    def tick() -> List:
        if _chaos.active():
            _chaos.sleep_site("coord.tick_delay")
        ctrl.expire_grace()
        if ctrl.lost_ranks:
            lost = sorted(ctrl.lost_ranks)
            why = "; ".join(
                f"rank {r}: {ctrl.lost_reasons[r]}" for r in lost
                if r in ctrl.lost_reasons) or "terminated unexpectedly"
            ctrl.broadcast_responses([Response(
                ResponseType.SHUTDOWN,
                error_message=f"rank(s) {lost} lost: {why}")])
            _diag(0, f"rank(s) {lost} lost: {why}")
        ctrl.flush_unrouted()
        marker = cache.take_flush_marker() if cache is not None else None
        if cache is not None:
            replayed, groups, epoch, compact = cache.take_ready(
                lambda _psid: _THRESHOLD)
        else:
            replayed, groups, epoch, compact = [], [], 0, True
        retunes, retune_pending[:] = list(retune_pending), []
        negotiated = coord.poll_responses({})
        if _chaos.active():
            negotiated = _chaos.maybe_reorder("coord.reorder",
                                              negotiated)
        resps = (([marker] if marker is not None else [])
                 + retunes + replayed + negotiated)
        n_other = ((1 if marker is not None else 0) + len(retunes)
                   + len(negotiated))
        # Controller cache observation BEFORE the broadcast — same
        # ordering contract as the production drain loop: a worker's
        # hit bit for a freshly broadcast entry may arrive before the
        # send returns, and must find the entry already inserted.
        replay_ids = frozenset(id(r) for r in replayed)
        if cache is not None:
            for r in resps:
                cache.observe_response(r, replay=id(r) in replay_ids)
        if resps:
            if compact and groups and n_other == 0:
                ctrl.broadcast_replay(groups, epoch)
            else:
                ctrl.broadcast_responses(resps)
        return resps

    names = set(_cp_names())
    data_types = (ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
                  ResponseType.BROADCAST, ResponseType.REDUCESCATTER,
                  ResponseType.ALLTOALL)
    steps = _cp_steps()
    pull_step = (3 * steps) // 4
    for step in range(steps):
        if step in retune_steps:
            retune_pending.append(Response(
                ResponseType.RETUNE,
                tensor_names=[f"fusion_threshold={_THRESHOLD << 1}",
                              "cycle_time=0.004"],
                tensor_sizes=[retune_seq]))
            retune_seq += 1
        for n in sorted(names):
            ctrl.submit(_cp_req(0, n))
        done: set = set()
        deadline = time.monotonic() + CP_STEP_DEADLINE
        withdrew = False
        while done != names:
            for r in tick():
                if r.response_type in data_types:
                    for n in r.tensor_names:
                        done.add(n)
                        records.append((step, n, r.response_type.name))
                elif r.response_type == ResponseType.RETUNE:
                    for n in r.tensor_names:
                        records.append((int(r.tensor_sizes[0]), n,
                                        "RETUNE"))
                elif r.response_type == ResponseType.ERROR:
                    _diag(0, f"negotiation failed: {r.error_message}")
            if not withdrew and time.monotonic() > deadline:
                # The bounded end of a silently-lost frame: fail the
                # op group-wide (the runtime's synchronize-timeout →
                # withdraw path, mirrored here).
                withdrew = True
                for n in sorted(names - done):
                    coord.withdraw(n, 0)
            time.sleep(0.002)
        if step == pull_step:
            # One fleet-wide metrics pull mid-run: under the tree this
            # exercises the merged FRAME_METRICS_TREE aggregation (and
            # after an interior fault, the re-parented paths); every
            # live rank must answer.
            from .. import telemetry as _telemetry

            snaps = ctrl.collect_metrics(_telemetry.metrics(),
                                         timeout=10.0)
            if len(snaps) < np_:
                _diag(0, f"metrics pull covered only "
                         f"{sorted(snaps)} of {np_} ranks")
            _check_mem_gauges(snaps, np_)
    _result(0, records)
    ctrl.broadcast_responses([Response(ResponseType.SHUTDOWN)])
    time.sleep(0.3)  # let the workers drain the shutdown
    ctrl.close()


def run_cp_worker(rank: int, port: int, np_: int = 2) -> None:
    """Ranks 1..N-1 of the cp fleet: the real WorkerTransport (or its
    tree overlay when HVD_TPU_TREE arms it) + response-cache replica,
    mirroring the worker half of ops/collective._drain."""
    from ..ops import cache as _cache_mod
    from ..ops import transport as T
    from ..ops.wire import ResponseType

    _seed_mem_probe(rank)
    kill_step = int(os.environ.get("HVD_TPU_CHAOS_KILL_STEP", "-1"))
    layout = _cp_layout(np_)
    if layout is not None:
        from ..ops import tree as _tree

        w = _tree.TreeWorkerTransport("127.0.0.1", port, rank, layout)
    else:
        w = T.WorkerTransport("127.0.0.1", port, rank)
    if _cache_mod.cache_enabled() and w.controller_cache:
        w.cache = _cache_mod.ResponseCache(rank=rank)
    records = []
    names = set(_cp_names())
    data_types = (ResponseType.ALLREDUCE, ResponseType.ALLGATHER,
                  ResponseType.BROADCAST, ResponseType.REDUCESCATTER,
                  ResponseType.ALLTOALL)
    for step in range(_cp_steps()):
        if step == kill_step:
            sys.stderr.flush()
            os._exit(1)  # hard crash: no atexit handshake, no reconnect
        reqs = {}
        for n in sorted(names):
            req = _cp_req(rank, n)
            reqs[n] = req
            w.submit(req)
        w.flush_requests()
        done: set = set()
        deadline = time.monotonic() + CP_STEP_DEADLINE + 5.0
        while done != names:
            if time.monotonic() > deadline:
                _diag(rank, f"step {step} never completed "
                            f"({sorted(names - done)} missing)")
            resps = w.poll_responses()
            if resps is None:
                time.sleep(0.002)
                continue
            for r in resps:
                cache = w.cache  # may be dropped by a reconnect
                if cache is not None:
                    cache.observe_response(
                        r, own_requests={rank: reqs})
                if r.response_type in data_types:
                    for n in r.tensor_names:
                        done.add(n)
                        records.append((step, n, r.response_type.name))
                elif r.response_type == ResponseType.RETUNE:
                    # hvd-tune marker: record the apply exactly as the
                    # controller does — the recover digest proves
                    # exactly-once delivery across the fault.
                    for n in r.tensor_names:
                        records.append((int(r.tensor_sizes[0]), n,
                                        "RETUNE"))
                elif r.response_type == ResponseType.ERROR:
                    _diag(rank,
                          f"negotiation failed: {r.error_message}")
                elif r.response_type == ResponseType.SHUTDOWN:
                    if r.error_message:
                        _diag(rank, f"shutdown: {r.error_message}")
                    _diag(rank, "controller shut down mid-run")
    _result(rank, records)
    w.request_shutdown()
    w.close()


# ---------------------------------------------------------------------------
# local scenarios (real jax stack, single process)
# ---------------------------------------------------------------------------

def scenario_ckpt(exhaust: bool) -> None:
    """Background checkpoint write under an injected flaky filesystem
    (ckpt.oserror).  Recover: the published bytes are identical to the
    fault-free run.  Exhaustion: CheckpointError at wait() naming the
    injected fault."""
    import tempfile

    import numpy as np

    from ..utils import checkpoint as ckpt

    tree = {"w": np.arange(64, dtype=np.float32),
            "b": np.full((8,), 3.0, np.float32)}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.msgpack")
        handle = ckpt.write_tree_async(path, tree, step=7)
        try:
            handle.wait(timeout=60.0)
        except ckpt.CheckpointError as e:
            if exhaust:
                _diag(0, f"checkpoint failed after retries: {e}")
            raise
        if exhaust:
            print("CHAOS_NOTE: exhaustion scenario unexpectedly "
                  "succeeded", file=sys.stderr)
            raise SystemExit(1)
        with open(path, "rb") as f:
            blob = f.read()
        with open(path + ".step") as f:
            step = f.read()
        _result(0, [("ckpt", hashlib.sha256(blob).hexdigest(), step)])


def scenario_input_stall() -> None:
    """A tiny data-parallel training loop through prefetch_to_device
    with injected loader stalls: the trained parameters must be
    bitwise-identical to the fault-free run (prefetch adds latency,
    never reorders or drops batches)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd

    hvd.init(devices=jax.devices())
    try:
        nrep = hvd.size()
        rng = np.random.RandomState(7)
        batches = [rng.normal(size=(nrep * 4, 8)).astype("float32")
                   for _ in range(10)]

        w = jnp.zeros((8,), jnp.float32)

        @jax.jit
        def step(w, x):
            return w + jnp.tanh(x).mean(axis=0) * 0.1

        it = hvd.prefetch_to_device(iter(batches), depth=2)
        seen = 0
        for dev_batch in it:
            w = step(w, dev_batch)
            seen += 1
        host = np.asarray(w)
        _result(0, [("input", seen,
                     hashlib.sha256(host.tobytes()).hexdigest())])
    finally:
        hvd.shutdown()


def _build_server():
    import jax

    from ..models.transformer import TransformerConfig, init_transformer
    from ..serving.engine import InferenceEngine
    from ..serving.server import LMServer

    cfg = TransformerConfig(vocab_size=256, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64)
    params = init_transformer(jax.random.PRNGKey(5), cfg)
    engine = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                             capacity=64)
    return LMServer(engine, port=0).start()


def _post_generate(port: int, payload: dict, timeout: float = 60.0):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def scenario_serving_disconnect() -> None:
    """serving.disconnect fires inside the /generate client probe: the
    first request's slot is released through the abort path
    (serving.client_disconnects counts it) and the FOLLOW-UP request —
    the digested result — completes identically to the fault-free
    run."""
    from .. import chaos as _chaos
    from .. import telemetry as _telemetry

    srv = _build_server()
    try:
        faulted = _chaos.active()
        first: dict = {}
        try:
            first = _post_generate(
                srv.port, {"tokens": [5, 6, 7], "max_tokens": 24,
                           "timeout": 45.0})
        except Exception as e:  # noqa: BLE001 — 499 surfaces as an
            # HTTPError on the faulted pass; the follow-up is the test
            first = {"error": str(e)}
        follow = _post_generate(
            srv.port, {"tokens": [9, 10, 11], "max_tokens": 8,
                       "timeout": 45.0})
        if faulted:
            snap = _telemetry.metrics()
            got = snap.get("serving.client_disconnects",
                           {}).get("value", 0)
            if got < 1:
                _diag(0, f"client disconnect was injected but never "
                         f"counted (serving.client_disconnects={got}; "
                         f"first reply: {first})")
        _result(0, [("serve", tuple(follow["tokens"]),
                     follow["finish_reason"])])
    finally:
        srv.close()


def scenario_serving_spec_disconnect() -> None:
    """serving.disconnect fires mid-SPECULATION: the engine runs a
    draft model (speculative decoding) and the prefix cache, the first
    request dies at the client probe, and its iteration-boundary abort
    must release the target AND draft KV slots and decrement the
    prefix refcounts (a leak would show as diverging page accounting).
    The follow-up request shares the first one's prompt header — a
    prefix-cache hit — and must complete identically to the fault-free
    pass."""
    import jax

    from .. import chaos as _chaos
    from .. import telemetry as _telemetry
    from ..models.transformer import TransformerConfig, init_transformer
    from ..serving.engine import InferenceEngine
    from ..serving.server import LMServer

    cfg = TransformerConfig(vocab_size=256, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64)
    dcfg = TransformerConfig(vocab_size=256, d_model=32, n_heads=2,
                             n_layers=1, d_ff=32, max_seq_len=64)
    params = init_transformer(jax.random.PRNGKey(5), cfg)
    draft = init_transformer(jax.random.PRNGKey(6), dcfg)
    engine = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                             capacity=64, draft=(draft, dcfg),
                             spec_tokens=3, prefix_cache=True)
    srv = LMServer(engine, port=0).start()
    try:
        faulted = _chaos.active()
        header = list(range(40, 56))  # two full 8-token pages
        first: dict = {}
        try:
            first = _post_generate(
                srv.port, {"tokens": header + [5, 6, 7],
                           "max_tokens": 24, "timeout": 45.0})
        except Exception as e:  # noqa: BLE001 — 499 surfaces as an
            # HTTPError on the faulted pass; the follow-up is the test
            first = {"error": str(e)}
        follow = _post_generate(
            srv.port, {"tokens": header + [9, 10, 11],
                       "max_tokens": 8, "timeout": 45.0})
        if faulted:
            snap = _telemetry.metrics()
            got = snap.get("serving.client_disconnects",
                           {}).get("value", 0)
            if got < 1:
                _diag(0, f"client disconnect was injected but never "
                         f"counted (serving.client_disconnects={got}; "
                         f"first reply: {first})")
        # Page accounting after the abort: every slot idle, so free +
        # cached must cover every allocatable page on BOTH stores, and
        # no cached page may still hold a reference — a leak here is a
        # divergence between the passes (the digest covers it).
        stats = engine.cache.prefix_stats()
        target_ok = (engine.cache.free_pages()
                     == engine.cache.total_pages)
        draft_ok = (engine.draft_cache.free_pages()
                    == engine.draft_cache.total_pages)
        _result(0, [("serve", tuple(follow["tokens"]),
                     follow["finish_reason"]),
                    ("pages", target_ok, draft_ok,
                     stats["referenced_pages"],
                     stats["cached_pages"])])
    finally:
        srv.close()


def scenario_serving_storm() -> None:
    """A burst of concurrent /generate requests against two decode
    slots: every request must complete (or fail explicitly) — the
    front door never hangs under a storm."""
    import threading

    srv = _build_server()
    try:
        out: Dict[int, object] = {}

        def one(i: int) -> None:
            try:
                out[i] = tuple(_post_generate(
                    srv.port, {"tokens": [3 + i, 4, 5],
                               "max_tokens": 6,
                               "timeout": 90.0})["tokens"])
            except Exception as e:  # noqa: BLE001 — an explicit
                out[i] = f"error: {e}"  # failure is contract-legal

        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        alive = [i for i, t in enumerate(threads) if t.is_alive()]
        if alive:
            _diag(0, f"storm requests {alive} still hanging")
        _result(0, sorted(("storm", i, str(out.get(i)))
                          for i in range(12)))
    finally:
        srv.close()


def scenario_router_replica_death() -> None:
    """Two real replicas (identical params, so completions are
    bitwise-identical wherever they run) behind the REAL Router over
    real HTTP.  The faulted pass drains the replica that served the
    first request and then kills its front door hard: the remaining
    dispatches must fail over to the survivor, and the digested
    completions must match the fault-free fleet's exactly."""
    import jax

    from .. import chaos as _chaos
    from ..models.transformer import TransformerConfig, init_transformer
    from ..routing import Router, RouterConfig
    from ..routing.replica import HttpReplicaClient
    from ..serving.engine import InferenceEngine
    from ..serving.server import LMServer

    from ..telemetry import exporter as _exporter

    cfg = TransformerConfig(vocab_size=256, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64)
    params = init_transformer(jax.random.PRNGKey(5), cfg)

    def replica():
        engine = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                                 capacity=64, prefix_cache=True)
        # Private routes: two replicas in one process must not clobber
        # each other's /generate + /healthz (LMServer docstring).
        return LMServer(engine, port=0,
                        routes=_exporter.RouteRegistry()).start()

    servers = {"a": replica(), "b": replica()}
    router = Router(RouterConfig(probe_base=0.01))
    try:
        for name, srv in servers.items():
            router.add_replica(
                name, HttpReplicaClient("127.0.0.1", srv.port))
        router.poll()
        records = []
        header = list(range(40, 56))  # two full 8-token pages
        status, first = router.dispatch(
            {"tokens": header + [5, 6, 7], "max_tokens": 12})
        if status != 200:
            _diag(0, f"first dispatch failed: {status} {first}")
        records.append(("req0", tuple(first["tokens"]),
                        first["finish_reason"]))
        if _chaos.fire("router.replica_kill") is not None:
            victim = first["router"]["replica"]
            router.drain_replica(victim)  # real POST /drain
            servers[victim].close()       # then the hard death
            router.poll()                 # -> ReplicaUnreachable
        for i, prompt in enumerate((header + [9, 10, 11],
                                    [7, 8, 9, 10])):
            status, resp = router.dispatch({"tokens": prompt,
                                            "max_tokens": 8})
            if status != 200:
                _diag(0, f"dispatch {i + 1} failed after the replica "
                         f"death: {status} {resp}")
            records.append((f"req{i + 1}", tuple(resp["tokens"]),
                            resp["finish_reason"]))
        down = sorted(n for n, s in router.replica_status().items()
                      if s["status"] != "ready")
        if _chaos.active():
            if not down:
                _diag(0, "the kill was injected but every replica "
                         "still reads ready")
            print(f"[hvd-route] failed over from {down} to the "
                  f"surviving replica", flush=True)
        _result(0, records)
    finally:
        for srv in servers.values():
            try:
                srv.close()
            except Exception:  # noqa: BLE001 — the victim is already
                pass           # closed on the faulted pass


def scenario_router_restart() -> None:
    """The REAL RouterServer runs in a SEPARATE process over two real
    in-process replicas; the faulted pass SIGKILLs it mid-generation.
    The replicas must abort the severed connections via the client
    probe (no slot leak), a fresh router over the same fleet serves
    the resubmitted request, and the digested completions are
    identical to the never-killed run (the serving bitwise contract
    makes the retry the same answer)."""
    import signal
    import threading
    import urllib.request

    import jax

    from .. import chaos as _chaos
    from .. import telemetry as _telemetry
    from ..models.transformer import TransformerConfig, init_transformer
    from ..serving.engine import InferenceEngine
    from ..serving.server import LMServer

    from ..telemetry import exporter as _exporter

    # Wide enough that a 220-token generation takes whole seconds on
    # CPU — the SIGKILL below must land mid-generation.
    cfg = TransformerConfig(vocab_size=256, d_model=64, n_heads=2,
                            n_layers=2, d_ff=256, max_seq_len=256)
    params = init_transformer(jax.random.PRNGKey(5), cfg)

    def replica():
        engine = InferenceEngine(params, cfg, max_slots=2, page_size=8,
                                 capacity=256)
        return LMServer(engine, port=0,
                        routes=_exporter.RouteRegistry()).start()

    servers = [replica(), replica()]

    def boot_router():
        port = _free_port()
        env = dict(os.environ)
        env.pop("HVD_TPU_FAULTS", None)  # the router child is plain
        env["HVD_TPU_CHAOS_REPLICAS"] = ",".join(
            str(s.port) for s in servers)
        env["HVD_TPU_CHAOS_ROUTER_PORT"] = str(port)
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.chaos",
             "--scenario", "router_restart_node"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                _diag(0, f"router child exited {proc.returncode} "
                         f"before becoming healthy")
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=2.0) as resp:
                    if resp.status == 200:
                        return proc, port
            except Exception:  # noqa: BLE001 — still booting
                time.sleep(0.1)
        proc.kill()
        _diag(0, "router child never became healthy")

    proc, port = boot_router()
    try:
        records = []
        r0 = _post_generate(port, {"tokens": [5, 6, 7],
                                   "max_tokens": 8})
        records.append(("req0", tuple(r0["tokens"]),
                        r0["finish_reason"]))
        long_payload = {"tokens": [11, 12, 13, 14], "max_tokens": 220}
        if _chaos.fire("router.kill") is not None:
            severed: Dict[str, object] = {}

            def fire_and_forget() -> None:
                try:
                    severed["resp"] = _post_generate(
                        port, long_payload, timeout=120.0)
                except Exception as e:  # noqa: BLE001 — the router
                    severed["error"] = str(e)  # we kill takes it down
            th = threading.Thread(target=fire_and_forget, daemon=True)
            th.start()
            time.sleep(0.15)  # let the replica start decoding
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            th.join(timeout=30.0)
            proc, port = boot_router()
            aborted = 0
            deadline = time.monotonic() + 45.0
            while time.monotonic() < deadline:
                snap = _telemetry.metrics()
                aborted = snap.get("serving.client_disconnects",
                                   {}).get("value", 0)
                if aborted >= 1:
                    break
                time.sleep(0.2)
            if aborted < 1:
                _diag(0, f"router killed mid-generation but no "
                         f"replica aborted the orphaned request "
                         f"(client_disconnects={aborted}; severed "
                         f"reply: {severed})")
            print(f"[hvd-route] replica aborted the severed router "
                  f"connection (client_disconnects={aborted}); "
                  f"resubmitting through the restarted router",
                  flush=True)
        rl = _post_generate(port, long_payload, timeout=120.0)
        records.append(("long", tuple(rl["tokens"]),
                        rl["finish_reason"]))
        r2 = _post_generate(port, {"tokens": [9, 10, 11],
                                   "max_tokens": 8})
        records.append(("req2", tuple(r2["tokens"]),
                        r2["finish_reason"]))
        _result(0, records)
    finally:
        proc.kill()
        proc.wait()
        for srv in servers:
            srv.close()


def _router_restart_node() -> None:
    """(child helper, no matrix row) The router process of
    ``router_restart``: the REAL RouterServer over HTTP clients to the
    parent scenario's replicas; the parent SIGKILLs it mid-generation
    on the faulted pass."""
    from ..routing import Router, RouterConfig, RouterServer
    from ..routing.replica import HttpReplicaClient

    ports = [int(p) for p in
             os.environ["HVD_TPU_CHAOS_REPLICAS"].split(",")]
    router = Router(RouterConfig(probe_base=0.01))
    for i, port in enumerate(ports):
        router.add_replica(f"r{i}",
                           HttpReplicaClient("127.0.0.1", port))
    RouterServer(
        router, port=int(os.environ["HVD_TPU_CHAOS_ROUTER_PORT"]),
        poll_interval=0.2).start()
    while True:  # serve until the parent kills us
        time.sleep(60.0)


LOCAL_SCENARIOS = {
    "ckpt_flaky": lambda: scenario_ckpt(exhaust=False),
    "ckpt_exhaustion": lambda: scenario_ckpt(exhaust=True),
    "input_stall": scenario_input_stall,
    "serving_disconnect": scenario_serving_disconnect,
    "serving_spec_disconnect": scenario_serving_spec_disconnect,
    "serving_storm": scenario_serving_storm,
    "router_replica_death": scenario_router_replica_death,
    "router_restart": scenario_router_restart,
    "router_restart_node": _router_restart_node,
}


# ---------------------------------------------------------------------------
# The runner
# ---------------------------------------------------------------------------

def _child_env(s: Scenario, faulted: bool,
               extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    env = dict(os.environ)
    env.pop("HVD_TPU_FAULTS", None)
    env.update(s.env)
    if faulted and s.spec:
        env["HVD_TPU_FAULTS"] = s.spec
    env.setdefault("JAX_PLATFORMS", "cpu")
    # hvd-race: fleet children run with the data-race detector and
    # donation sanitizer armed (like HVD_TPU_LOCK_CHECK via env
    # inheritance from conftest) — chaos is exactly where cross-thread
    # interleavings and recovery-path stale reads surface.
    env.setdefault("HVD_TPU_LOCK_CHECK", "1")
    env.setdefault("HVD_TPU_RACE_CHECK", "1")
    env.setdefault("HVD_TPU_DONATION_CHECK", "1")
    if s.kind == "local":
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=4")
        env["XLA_FLAGS"] = " ".join(flags).strip()
        env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update(extra or {})
    return env


@dataclass
class PassResult:
    rc: Optional[int]   # None = killed at the cap (HANG)
    output: str
    results: Dict[int, str]  # rank -> CHAOS_RESULT line payload
    seconds: float


_RESULT_RE = re.compile(
    r"CHAOS_RESULT rank=(\d+) n=(\d+) digest=([0-9a-f]{24})")


def _parse_results(output: str) -> Dict[int, str]:
    # Matched by the exact field shapes (_result writes a 24-hex-char
    # digest), not by line splitting: a concurrent writer on the same
    # fd can interleave a log fragment mid-line (observed: a
    # "[hvd-tree]" relay line glued onto a digest token under tier-1
    # load), and that must not read as a digest mismatch.
    out: Dict[int, str] = {}
    for m in _RESULT_RE.finditer(output):
        out[int(m.group(1))] = f"n={m.group(2)} digest={m.group(3)}"
    return out


def _run_pass(s: Scenario, faulted: bool) -> PassResult:
    t0 = time.monotonic()
    if s.kind == "local":
        procs = [subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.chaos",
             "--scenario", s.name],
            env=_child_env(s, faulted, {"HVD_TPU_RANK": "0"}),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)]
    else:
        port = _free_port()
        # Tree mode: interiors bind relay ports at base+rank; a fresh
        # base per pass keeps parallel passes from colliding (harmless
        # for flat fleets, which never bind them).
        tree_base = _free_port()
        procs = []
        for rank in range(s.np):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.chaos",
                 "--node", str(rank), "--np", str(s.np),
                 "--port", str(port), "--scenario", s.name],
                env=_child_env(s, faulted,
                               {"HVD_TPU_RANK": str(rank),
                                "HVD_TPU_TREE_PORT_BASE":
                                    str(tree_base)}),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
            if rank == 0:
                time.sleep(0.2)  # let the controller bind first
    deadline = t0 + s.cap
    outputs: List[str] = [""] * len(procs)
    hang = False
    for i, p in enumerate(procs):
        remaining = deadline - time.monotonic()
        try:
            out, _ = p.communicate(timeout=max(0.1, remaining))
            outputs[i] = out.decode(errors="replace")
        except subprocess.TimeoutExpired:
            hang = True
            p.kill()
            out, _ = p.communicate()
            outputs[i] = out.decode(errors="replace")
    output = "\n".join(outputs)
    rcs = [p.returncode for p in procs]
    rc: Optional[int] = None if hang else max(rcs)
    return PassResult(rc=rc, output=output,
                      results=_parse_results(output),
                      seconds=time.monotonic() - t0)


def run_scenario(s: Scenario, verbose: bool = False) -> Dict:
    """Run one scenario end to end; returns its report dict."""
    report: Dict = {"scenario": s.name, "expect": s.expect,
                    "spec": s.spec, "cap": s.cap}

    def fail(status: str, detail: str, *passes: PassResult) -> Dict:
        report.update(status=status, detail=detail)
        print(f"  FAIL [{status}] {s.name}: {detail}", flush=True)
        for p in passes:
            tail = "\n".join(p.output.splitlines()[-25:])
            print(f"  ---- pass output tail ----\n{tail}", flush=True)
        return report

    if s.expect == "complete":
        p = _run_pass(s, faulted=False)
        report["seconds"] = p.seconds
        if p.rc is None:
            return fail("HANG", f"still running at the {s.cap:.0f}s "
                                f"cap", p)
        if p.rc != 0:
            return fail("FAIL", f"exit {p.rc}", p)
        report["status"] = "PASS"
        print(f"  PASS {s.name} ({p.seconds:.1f}s)", flush=True)
        return report

    base: Optional[PassResult] = None
    if s.expect == "recover":
        # Diagnostic scenarios need no baseline (nothing is compared;
        # the scenario's env may itself carry the fault, e.g. the
        # grace-expiry hard kill).
        base = _run_pass(s, faulted=False)
        if base.rc is None:
            return fail("HANG", "fault-free pass hit the cap", base)
        if base.rc != 0:
            return fail("FAIL", f"fault-free pass exited {base.rc}",
                        base)
    fp = _run_pass(s, faulted=True)
    report["seconds"] = (base.seconds if base else 0.0) + fp.seconds
    if fp.rc is None:
        return fail("HANG", f"faulted run still alive at the "
                            f"{s.cap:.0f}s cap — the no-hang "
                            f"contract violation", fp)
    if s.expect == "recover":
        if fp.rc != 0:
            return fail("FAIL", f"expected recovery, got exit {fp.rc}",
                        fp)
        if fp.results != base.results:
            return fail(
                "DIVERGED",
                f"recovered but results differ: fault-free "
                f"{base.results} vs faulted {fp.results}", base, fp)
        if s.needle and s.needle not in fp.output:
            return fail("FAIL", f"recovered, but the fault was never "
                                f"exercised ({s.needle!r} not in "
                                f"output)", fp)
    else:  # diagnostic
        if fp.rc == 0:
            return fail("FAIL", "expected a named failure, run "
                                "exited 0", fp)
        if s.needle and s.needle not in fp.output:
            return fail("FAIL", f"failed, but without the diagnostic "
                                f"naming the fault ({s.needle!r} not "
                                f"in output)", fp)
    report["status"] = "PASS"
    print(f"  PASS {s.name} ({report['seconds']:.1f}s)", flush=True)
    if verbose:
        print(fp.output)
    return report


def run_matrix(only: Optional[List[str]] = None,
               verbose: bool = False) -> int:
    todo = ([find(n) for n in only] if only else SCENARIOS)
    print(f"hvd-chaos matrix: {len(todo)} scenario(s)", flush=True)
    reports = []
    for s in todo:
        print(f"- {s.name} [{s.kind} np={s.np if s.kind == 'cp' else 1}"
              f" expect={s.expect}] {s.doc}", flush=True)
        reports.append(run_scenario(s, verbose=verbose))
    failed = [r for r in reports if r.get("status") != "PASS"]
    print(json.dumps({"scenarios": reports,
                      "passed": len(reports) - len(failed),
                      "failed": len(failed)}, indent=1))
    return 1 if failed else 0
