"""horovod_tpu — a TPU-native distributed data-parallel training framework.

Brand-new implementation of the capabilities of Horovod (reference:
WeichenXu123/horovod v0.13.0), re-architected for TPU: ranks resolve from
the JAX process/device mesh instead of MPI_COMM_WORLD, and the MPI/NCCL
collectives become XLA collectives (psum / all_gather / ppermute) compiled
over the pod's ICI/DCN fabric.  See SURVEY.md for the design blueprint and
per-symbol reference citations in each module.

Top-level API (≙ ``import horovod.tensorflow as hvd`` surface,
reference horovod/tensorflow/__init__.py, horovod/torch/__init__.py):

    import horovod_tpu as hvd
    hvd.init()
    hvd.size(), hvd.rank(), hvd.local_size(), hvd.local_rank()
    hvd.allreduce(x, average=True), hvd.allgather(x), hvd.broadcast(x, 0)
    h = hvd.allreduce_async(x); hvd.poll(h); hvd.synchronize(h)
    opt = hvd.DistributedOptimizer(optax.sgd(0.01))
    params = hvd.broadcast_parameters(params, root_rank=0)
"""

from .core.state import (  # noqa: F401
    REPLICA_AXIS,
    NotInitializedError,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    mesh,
    mpi_threads_supported,
    process_count,
    process_index,
    rank,
    replica_id,
    shutdown,
    size,
    start_timeline,
    stop_timeline,
)
from .ops.collective import (  # noqa: F401
    Adasum,
    Average,
    HorovodError,
    Max,
    Min,
    Product,
    Sum,
    add_process_set,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    grouped_allgather,
    grouped_allgather_async,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_reducescatter,
    grouped_reducescatter_async,
    global_process_set,
    join,
    poll,
    quiesce,
    reducescatter,
    reducescatter_async,
    remove_process_set,
    shard,
    synchronize,
)
from .core.features import (  # noqa: F401  (build/feature query shims)
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    native_built,
    nccl_built,
    rocm_built,
    xla_built,
)
from .ops.process_set import ProcessSet  # noqa: F401
from .ops.wire import ReduceOp  # noqa: F401
from .ops.compression import (  # noqa: F401
    Compression,
    get_compression,
    set_compression,
)
from .ops.megakernel import (  # noqa: F401
    compression_state,
    load_compression_state,
)
from .ops.objects import allgather_object, broadcast_object  # noqa: F401
from .ops.sparse import IndexedSlices  # noqa: F401
from .parallel.data import (  # noqa: F401
    DistributedOptimizer,
    broadcast_global_variables,
    broadcast_parameters,
)
from .parallel.input import prefetch_to_device  # noqa: F401
from .parallel.overlap import ChainedLoss  # noqa: F401
from .parallel.pipeline import (  # noqa: F401
    PipelinePlan,
    make_pipeline_train_step,
    schedule_plan,
)
from .parallel.training import barrier_fence  # noqa: F401
from . import elastic  # noqa: F401  (hvd.elastic.State / @hvd.elastic.run)
from . import analysis  # noqa: F401  (hvd.analysis.verify_program & co)
from .analysis.program import verify_program  # noqa: F401
from . import telemetry  # noqa: F401  (hvd.telemetry.flight & registry)
from .telemetry import cluster_metrics, metrics  # noqa: F401
from . import serving  # noqa: F401  (hvd.serving.InferenceEngine & co)
from . import trace  # noqa: F401  (hvd.trace spans & clock alignment)
from .trace.merge import dump_fleet_trace  # noqa: F401
from .trace.watch import StragglerWatch  # noqa: F401
from . import memory  # noqa: F401  (hvd.memory: ledger/planner/oom)
from .memory import MemoryWatch  # noqa: F401
from .ops import fused  # noqa: F401  (hvd.fused: computation-collective
#                                      kernels — matmul_psum & co)

__version__ = "0.1.0"
