"""Validation harness for hvd-spec (shared by bench.py and the test
suite, so the CI gate and the unit tests assert ONE contract instead of
two drifting copies).  Also a user-facing utility: point
:func:`count_spec_dispatches` at an engine wired with a candidate draft
to confirm the steady-state dispatch contract on your own model.
"""

from __future__ import annotations

from typing import Tuple


def zeroed_layer_params(params: dict):
    """Zero every layer's residual contribution (attention output +
    FFN output projections): the model's logits reduce to
    ``ln_f(embed + pos) @ unembed``, independent of depth or width —
    the construction behind :func:`agreement_pair`."""
    import jax.numpy as jnp

    layers = dict(params["layers"])
    layers["wo"] = jnp.zeros_like(layers["wo"])
    layers["w_out"] = jnp.zeros_like(layers["w_out"])
    layers["b_out"] = jnp.zeros_like(layers["b_out"])
    out = dict(params)
    out["layers"] = layers
    return out


def agreement_pair(target_cfg, draft_cfg, seed: int = 0):
    """A ``(target_params, draft_params)`` pair whose greedy argmax
    agrees EXACTLY at every position: both models' layer contributions
    are zeroed (:func:`zeroed_layer_params`) and the draft shares the
    target's embed/pos/ln_f/unembed halves, so their logits are
    bitwise-identical while the draft still pays only its own (smaller)
    layer stack.  Acceptance under the bitwise-greedy rule is therefore
    deterministically 1.0 — the mechanism's upper bound, which is what
    makes the bench's speculative speedup gate reproducible.  Requires
    matching ``vocab_size``/``d_model``/``max_seq_len``."""
    import jax

    from ..models.transformer import init_transformer

    if (draft_cfg.vocab_size != target_cfg.vocab_size
            or draft_cfg.d_model != target_cfg.d_model):
        raise ValueError(
            "agreement_pair needs matching vocab_size and d_model "
            "(the embed/unembed halves are shared)")
    target = zeroed_layer_params(
        init_transformer(jax.random.PRNGKey(seed), target_cfg))
    draft = zeroed_layer_params(
        init_transformer(jax.random.PRNGKey(seed + 1), draft_cfg))
    for k in ("embed", "pos_embed", "ln_f", "unembed"):
        draft[k] = target[k]
    return target, draft


def count_spec_dispatches(engine) -> Tuple[int, int, int]:
    """Run ONE steady-state speculative iteration on ``engine`` (which
    must have active slots — e.g. after a ``step()`` that admitted) and
    return ``(propose_calls, verify_calls, eager_dispatches)``.  The
    hvd-spec dispatch contract is ``(1, 1, 0)``: one draft propose, ONE
    target verify, nothing eager — asserted by both the CI bench gate
    and tests/test_speculative.py through this one implementation."""
    from ..utils import xla_dispatch

    calls = {"verify": 0, "propose": 0}
    vkey = ("verify", engine.spec_tokens + 1)
    pkey = ("draft_propose", engine.spec_tokens)
    v_exec, p_exec = engine._exec[vkey], engine._exec[pkey]
    engine._exec[vkey] = lambda *a: (
        calls.__setitem__("verify", calls["verify"] + 1) or v_exec(*a))
    engine._exec[pkey] = lambda *a: (
        calls.__setitem__("propose", calls["propose"] + 1)
        or p_exec(*a))
    try:
        with xla_dispatch.exact_scope():
            with xla_dispatch.record(all_threads=True) as scope:
                engine.step()
            eager = scope.count
    finally:
        engine._exec[vkey], engine._exec[pkey] = v_exec, p_exec
    return calls["propose"], calls["verify"], eager
