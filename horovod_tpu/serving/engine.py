"""The hvd-serve inference engine: donated AOT prefill/decode executables
over the paged KV cache, driven by the continuous-batching scheduler.

Megakernel-style data plane (docs/inference.md): each serving phase is
ONE compiled XLA program — page-table gather → cache-aware forward
(:func:`..models.transformer.forward_step`) → scatter of the new KV
entries back into the paged store — with the page arrays donated, so a
decode iteration is a single dispatch whose working set updates in
place.  Executables are built ahead of time (``jit(...).lower(...)
.compile()``) and recorded in the PR-5 persistent-cache manifest under
``variant: "serving"`` (ops/megakernel.py ``record_manifest_entry``):
:meth:`InferenceEngine.warm_start` rebuilds every recorded executable
at startup — against a warm ``HVD_TPU_COMPILE_CACHE_DIR`` the XLA
compile is a disk-cache read — so a relaunched serving fleet reaches
full token rate before its first request, and ``/healthz`` reports
NOT_READY until it has.

Bitwise contract (CI-gated by tests/test_serving.py and ``bench.py
--mode serving``): a prefill of the prompt followed by N single-token
decode iterations reproduces, bit for bit, the logits of the
non-incremental :func:`..models.transformer.serving_forward` of the
same tokens — greedy generation is therefore exactly reproducible
across the static/continuous schedulers, batch compositions, slot
assignments, and engine relaunches.  Two rules carry it: every token
block is at least 2 wide (decode pads a discarded dummy column —
XLA:CPU's single-row gemv accumulates differently from the gemm every
other width uses), and comparisons are jit↔jit (the eager path fuses
differently).

Multi-host serving: rank 0 owns the scheduler and the HTTP front door;
workers mirror its per-iteration plan (admissions, then sampled
tokens/evictions) over the control plane's object collectives and run
the identical executables — the same rank-0-decides/broadcast
convention the checkpoint and elastic paths use.  Like every
multi-process data-plane leg, this needs a jax build whose CPU backend
executes np>1 collectives (CI), not the container's 0.4.37.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .. import telemetry as _telemetry
from .. import trace as _trace
from ..analysis import donation as _donation
from ..analysis import lockorder as _lockorder
from ..analysis import threads as _athreads
from ..core.topology import MODEL_AXIS
from ..memory import oom as _oom
from ..memory import planner as _mem_planner
from ..telemetry import flight as _flight
from ..models import transformer as _transformer
from ..ops import megakernel as _megakernel
from .kv_cache import PagedKVCache
from .scheduler import (ContinuousBatchingScheduler, FinishReason,
                        Request)

_M_TTFT = _telemetry.histogram(
    "serving.ttft_seconds", "seconds",
    "time from submission to the first generated token")
_M_TOKEN_LAT = _telemetry.histogram(
    "serving.token_seconds", "seconds",
    "per-token decode latency (one continuous-batching iteration)")
_M_TOKENS = _telemetry.counter(
    "serving.tokens_generated", "tokens sampled across all sequences")
_M_PREFILLS = _telemetry.counter(
    "serving.prefills", "prefill executions (one per admission)")
_M_DECODES = _telemetry.counter(
    "serving.decode_iterations", "batched decode iterations")
_M_WARM = _telemetry.counter(
    "serving.warm_starts", "serving executables AOT-rebuilt at startup")
_M_SPEC_PROPOSED = _telemetry.counter(
    "serving.spec_proposed", "draft tokens proposed per speculative "
    "iteration (spec_tokens per active greedy slot)")
_M_SPEC_ACCEPTED = _telemetry.counter(
    "serving.spec_accepted", "draft tokens the bitwise-greedy verify "
    "accepted (the bonus/correction token is not counted)")
_M_SPEC_RATE = _telemetry.gauge(
    "serving.spec_acceptance_rate", "cumulative spec_accepted / "
    "spec_proposed for this engine")


def _model_dict(cfg) -> dict:
    """One model-identity dict for every identity consumer — the
    prefix-cache fingerprint, the manifest's model field, and the
    draft identity on speculative entries.  A new config field that
    changes compiled programs or KV content belongs HERE, once."""
    return {
        "vocab_size": cfg.vocab_size,
        "d_model": cfg.d_model,
        "n_heads": cfg.n_heads,
        "n_layers": cfg.n_layers,
        "d_ff": cfg.d_ff,
        "max_seq_len": cfg.max_seq_len,
        "num_experts": cfg.num_experts,
        "dtype": jnp.dtype(cfg.dtype).name,
    }


class InferenceEngine:
    """Continuous-batching inference over one transformer LM.

    ``params``/``cfg`` are the training-side parameter pytree and
    :class:`~horovod_tpu.models.transformer.TransformerConfig`.  With a
    ``mesh`` that has a ``model`` axis, the KV head axis and the
    attention/FFN compute shard over it exactly like the training
    forward (the ``parallel/tensor.py`` layout, via GSPMD).  Threading
    contract: the data plane (``step``/``follow``/``generate``/
    ``run_until_idle``) is driven from ONE thread (the serve loop);
    ``submit`` is thread-safe (the scheduler's lock), and the
    drain-family methods — ``drain``, ``import_requests``,
    ``export_requests`` — may run from other threads (the elastic
    resize path) concurrently with the loop, serialized by
    ``_drain_lock``.  ``abort_all`` is the exception: it broadcasts on
    the control plane, so under multiprocess it must be called from
    the serve-loop thread only (between iterations — see its
    docstring); single-process callers may treat it like the rest of
    the drain family.
    """

    def __init__(self, params: Any, cfg, *, mesh=None, max_slots: int = 8,
                 page_size: int = 16, capacity: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 model_axis: str = MODEL_AXIS,
                 prefix_cache: Optional[bool] = None,
                 prefix_pages: Optional[int] = None,
                 draft: Optional[Tuple[Any, Any]] = None,
                 spec_tokens: Optional[int] = None) -> None:
        cap = capacity if capacity is not None else cfg.max_seq_len
        cap = min(cap, cfg.max_seq_len)
        cap -= cap % page_size
        # Compare against the page-floored max_seq_len, or the default
        # capacity (None -> max_seq_len) is spuriously rejected when
        # page_size < max_seq_len < 2*page_size with an unaligned
        # max_seq_len.
        max_cap = cfg.max_seq_len - cfg.max_seq_len % page_size
        if cap < 2 * page_size and cap < max_cap:
            raise ValueError(
                f"capacity {capacity} too small for page_size "
                f"{page_size} (needs >= 2 pages' worth or "
                f"max_seq_len)")
        if cap < 2:
            raise ValueError("KV capacity must be >= 2")
        self.cfg = cfg
        self.mesh = mesh
        self.eos_id = eos_id
        self.max_slots = max_slots
        # Shared-prefix page cache (hvd-spec): on unless the env or the
        # kwarg opts out; hits are bitwise-invisible, so the default is
        # safe.  The fingerprint keys the chain hashes to this model's
        # config — the cache is per-engine, so parameters are fixed
        # once the fingerprint matches.
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "HVD_TPU_PREFIX_CACHE", "1") != "0"
        # The dedicated prefix reserve defaults from the env so the
        # RETUNE actuation path (hvd-tune's prefix_pages knob, applied
        # via HVD_TPU_PREFIX_PAGES) reaches the next engine build
        # without a code change at every call site.
        if prefix_pages is None:
            prefix_pages = int(os.environ.get(
                "HVD_TPU_PREFIX_PAGES", "0"))
        fingerprint = json.dumps(_model_dict(cfg), sort_keys=True)
        # Exported verbatim in /healthz: the router tier keys its
        # prefix-affinity chain hashes off this (routing/affinity.py).
        self.fingerprint = fingerprint
        self.cache = PagedKVCache(
            cfg.n_layers, cfg.n_heads, cfg.d_model // cfg.n_heads,
            max_slots, cap // page_size, page_size,
            dtype=cfg.dtype, mesh=mesh, model_axis=model_axis,
            prefix_cache=prefix_cache, prefix_pages=prefix_pages,
            fingerprint=fingerprint)
        self.capacity = self.cache.capacity
        self.scheduler = ContinuousBatchingScheduler(max_slots,
                                                     self.capacity)
        if mesh is not None and self.cache.page_sharding() is not None:
            rep = NamedSharding(mesh, P())
            params = jax.tree_util.tree_map(
                lambda x: jax.device_put(jnp.asarray(x), rep), params)
        else:
            params = jax.tree_util.tree_map(jnp.asarray, params)
        self.params = params
        # Speculative decoding (hvd-spec): a draft model over the same
        # mesh proposes spec_tokens greedy tokens per iteration; ONE
        # donated verify executable runs the target over the block and
        # accepts via the bitwise-greedy rule.  Draft absent => the
        # decode path is bitwise-unchanged.
        if spec_tokens is None:
            spec_tokens = int(os.environ.get("HVD_TPU_SPEC_TOKENS", "3"))
        self.spec_tokens = spec_tokens
        self._draft_params = None
        self._draft_cfg = None
        self.draft_cache: Optional[PagedKVCache] = None
        if draft is not None:
            # Validated only when a draft is armed: without one the
            # depth is unused, and HVD_TPU_SPEC_TOKENS=0 in the
            # environment must not break draft-less engines.
            if spec_tokens < 1:
                raise ValueError(
                    f"spec_tokens must be >= 1, got {spec_tokens}")
            draft_params, draft_cfg = draft
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab_size {draft_cfg.vocab_size} must "
                    f"match the target's {cfg.vocab_size} (the "
                    f"acceptance rule compares token ids)")
            if draft_cfg.max_seq_len < cap:
                raise ValueError(
                    f"draft max_seq_len {draft_cfg.max_seq_len} must "
                    f"cover the KV capacity {cap}")
            self._draft_cfg = draft_cfg
            # The draft store rides the shared-prefix index too
            # (hvd-spec tail): a prompt-header hit skips the DRAFT
            # prefill as well as the target's.  Its chain hashes are
            # keyed by the DRAFT config's fingerprint — the two caches
            # hold different models' KV, so their indexes must never
            # collide on a shared token prefix.
            self.draft_cache = PagedKVCache(
                draft_cfg.n_layers, draft_cfg.n_heads,
                draft_cfg.d_model // draft_cfg.n_heads,
                max_slots, cap // page_size, page_size,
                dtype=draft_cfg.dtype, mesh=mesh, model_axis=model_axis,
                prefix_cache=prefix_cache,
                fingerprint=json.dumps(_model_dict(draft_cfg),
                                       sort_keys=True),
                ledger_category="serving.draft_kv")
            if mesh is not None and self.cache.page_sharding() is not None:
                rep = NamedSharding(mesh, P())
                draft_params = jax.tree_util.tree_map(
                    lambda x: jax.device_put(jnp.asarray(x), rep),
                    draft_params)
            else:
                draft_params = jax.tree_util.tree_map(jnp.asarray,
                                                      draft_params)
            self._draft_params = draft_params
            # hvd-mem: the draft's replicated parameters are a
            # framework-resident cost the planner's --draft-layers
            # what-if predicts; account the per-process resident bytes.
            from ..memory import ledger as _mem_ledger

            self._draft_ledger_key = id(self)
            if _mem_ledger.enabled():
                _mem_ledger.ledger.alloc(
                    "serving.draft_params",
                    sum(_mem_ledger.resident_nbytes(x) for x in
                        jax.tree_util.tree_leaves(draft_params)),
                    key=self._draft_ledger_key)
            import weakref

            weakref.finalize(self, _mem_ledger.ledger.free,
                             "serving.draft_params",
                             key=self._draft_ledger_key)
            # hvd-tune: armed speculative engines are live-retunable
            # (set_spec_tokens rides RETUNE stream markers) and feed the
            # controller's acceptance-rate sensor.
            from ..tuning import actuation as _actuation

            _actuation.register_spec_engine(self)
        # hvd-tune: every engine (speculative or not) is known to the
        # actuation layer so the prefix_pages knob can live-retune its
        # cache's index cap and price moves via page_global_bytes.
        from ..tuning import actuation as _tune_actuation

        _tune_actuation.register_serving_engine(self)
        self._buckets = [b for b in
                         (2 ** i for i in range(1, 31))
                         if b <= self.capacity]
        if self._buckets[-1] != self.capacity:
            self._buckets.append(self.capacity)
        self._exec: Dict[Tuple, Any] = {}
        self._last_token = np.zeros((max_slots,), np.int32)
        # The second-newest context token per slot — the catch-up
        # column of the draft's propose block (see
        # models/transformer.speculative_propose).
        self._prev_token = np.zeros((max_slots,), np.int32)
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._ready = False
        self._drained = False
        # Serializes drain/abort_all/import_requests: the serve loop's
        # recovery and the elastic thread's drain_commit run
        # concurrently, and "_drained" check-then-acts must be atomic
        # with the scheduler drain they guard (or a recovery could
        # re-open admission after a commit and silently lose requests).
        # Ordering: _drain_lock is taken BEFORE scheduler._lock, never
        # across a collective (which can block indefinitely).
        self._drain_lock = _lockorder.make_lock(
            "serving.InferenceEngine._drain_lock")
        self._manifest_dir: Optional[str] = None  # warm_start override

    # -- readiness / warm start -------------------------------------------
    @property
    def ready(self) -> bool:
        """True once :meth:`warm_start` completed — the ``/healthz``
        readiness bit (NOT_READY before; the load-balancer keeps
        traffic away until the executables exist)."""
        return self._ready

    def mark_unready(self) -> None:
        """Failure latch: flip ``/healthz`` back to NOT_READY.  Called
        when recovery itself failed and the engine's state can no
        longer be trusted — the load balancer drains traffic instead
        of feeding requests into a blackhole."""
        self._ready = False

    def health(self) -> Tuple[bool, dict]:
        """Exporter health contributor (exporter.register_health).
        ``kv_free_pages`` is the hvd-mem satellite: the router tier
        needs admission HEADROOM (can this replica take a long prompt)
        next to queue depth — occupancy alone says nothing about how
        full the occupied slots' page budgets are."""
        prefix = self.cache.prefix_stats()
        return self._ready, {
            "ready": self._ready,
            "queue_depth": self.scheduler.queue_depth(),
            "batch_occupancy": self.scheduler.occupancy(),
            # free_pages() already counts the prefix cache's
            # reclaimable pages, so the router's headroom figure stays
            # honest with a warm prefix index resident.
            "kv_free_pages": self.cache.free_pages(),
            "kv_total_pages": self.cache.total_pages,
            "kv_reclaimable_pages": prefix["reclaimable_pages"],
            "prefix_cached_pages": prefix["cached_pages"],
            # hvd-route: everything the router tier needs to derive
            # this replica's affinity keys lives in one health poll —
            # the page-hash scheme config plus the live index digests.
            "page_size": self.cache.page_size,
            "pages_per_slot": self.cache.pages_per_slot,
            "fingerprint": self.fingerprint,
            "prefix_index": self.cache.export_prefix_hashes(),
            "speculative": self._draft_params is not None,
            "spec_tokens": (self.spec_tokens
                            if self._draft_params is not None else 0),
            "slots": self.max_slots,
            "executables": len(self._exec),
        }

    def warm_start(self, directory: Optional[str] = None) -> int:
        """Build the decode executable plus every serving executable the
        persistent-cache manifest recorded for this model/mesh, then
        mark the engine ready.  On a relaunch with a warm
        ``HVD_TPU_COMPILE_CACHE_DIR`` the compiles are disk-cache
        reads — the fleet serves at full token rate from the first
        request.  A non-None ``directory`` is also where this engine
        RECORDS its executables from now on (read and write sides must
        agree, or a custom warm-start dir never accumulates entries); a
        ``None`` directory keeps a previously chosen one rather than
        reverting to the env default.  Returns the number of manifest
        entries rebuilt."""
        if directory is None:
            directory = self._manifest_dir
        self._manifest_dir = directory
        ident = self._manifest_identity()
        draft_ident = self._draft_model_dict()
        warmed = 0
        for entry in _megakernel.serving_entries(directory):
            if any(entry.get(k) != ident[k]
                   for k in ("model", "mesh", "slots", "page_size",
                             "pages_per_slot")):
                continue
            kind = entry.get("kind")
            # Speculative executables are keyed to the draft model and
            # the speculation depth too: a relaunch with a different
            # draft (or none) must not rebuild a foreign program.
            if kind in ("verify", "draft_propose", "draft_prefill"):
                if (draft_ident is None
                        or entry.get("draft") != draft_ident
                        or entry.get("spec") != self.spec_tokens):
                    continue
            try:
                if kind == "decode":
                    self._decode_exec()
                elif kind == "prefill":
                    b = int(entry.get("bucket") or 0)
                    if b in self._buckets:
                        self._prefill_exec(b)
                    else:
                        continue
                elif kind == "draft_prefill":
                    b = int(entry.get("bucket") or 0)
                    if b in self._buckets:
                        self._prefill_exec(b, draft=True)
                    else:
                        continue
                elif kind == "verify":
                    self._verify_exec()
                elif kind == "draft_propose":
                    self._propose_exec()
                else:
                    continue
                warmed += 1
            except Exception:  # noqa: BLE001 — a stale entry must not
                continue       # block startup; it just compiles lazily
        self._decode_exec()  # readiness == "can decode", manifest or not
        if self._draft_params is not None:
            # Readiness with a draft also means "can speculate": both
            # per-iteration executables exist before the first request.
            self._propose_exec()
            self._verify_exec()
        if warmed:
            _M_WARM.inc(warmed)
        # hvd-mem pre-flight: the engine's PER-DEVICE working set (one
        # KV shard — global/tp when the head axis is sharded — plus
        # one copy of the replicated params) against the per-device
        # HBM capacity — warned HERE, before the load balancer routes
        # traffic at a replica that cannot actually hold its cache.
        # Per-device, not global and not a per-process sum: either of
        # those cries wolf on exactly the large sharded multi-device
        # deployments this check targets (docs/memory.md).
        try:
            from ..memory import ledger as _mem_ledger

            per_device = (_mem_ledger.device_nbytes(self.cache.k_pages)
                          + _mem_ledger.device_nbytes(
                              self.cache.v_pages)
                          + sum(_mem_ledger.device_nbytes(x) for x in
                                jax.tree_util.tree_leaves(self.params)))
            if self.draft_cache is not None:
                per_device += (
                    _mem_ledger.device_nbytes(self.draft_cache.k_pages)
                    + _mem_ledger.device_nbytes(
                        self.draft_cache.v_pages)
                    + sum(_mem_ledger.device_nbytes(x) for x in
                          jax.tree_util.tree_leaves(
                              self._draft_params)))
            _oom.preflight_warn(per_device, "serving.warm_start",
                                "KV shard + replicated params "
                                "(per-device bytes)")
        except Exception:  # noqa: BLE001 — sizing is observability
            pass
        self._ready = True
        return warmed

    # -- manifest ----------------------------------------------------------
    def _mesh_key(self):
        if self.mesh is not None:
            return tuple(self.mesh.devices.flat)
        return (jax.devices()[0],)

    def _manifest_identity(self) -> dict:
        return {
            "variant": "serving",
            "model": _model_dict(self.cfg),
            "slots": self.max_slots,
            "page_size": self.cache.page_size,
            "pages_per_slot": self.cache.pages_per_slot,
            "mesh": _megakernel.mesh_fingerprint(self._mesh_key()),
        }

    def _draft_model_dict(self) -> Optional[dict]:
        if self._draft_cfg is None:
            return None
        return _model_dict(self._draft_cfg)

    def _record(self, kind: str, bucket: Optional[int]) -> None:
        entry = dict(self._manifest_identity())
        entry["kind"] = kind
        entry["bucket"] = bucket
        if kind in ("verify", "draft_propose", "draft_prefill"):
            entry["draft"] = self._draft_model_dict()
            entry["spec"] = self.spec_tokens
        _megakernel.record_manifest_entry(entry, self._manifest_dir)

    # -- executables -------------------------------------------------------
    def _aot(self, key: Tuple, fn, args: Tuple) -> Any:
        """Compile ``fn`` for ``args``' shapes/shardings (donating the
        page arrays at positions 1 and 2) and cache the executable."""
        compiled = self._exec.get(key)
        if compiled is not None:
            return compiled
        avals = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), args)
        jfn = jax.jit(fn, donate_argnums=(1, 2))
        compiled = jfn.lower(*avals).compile()
        # hvd-mem: harvest compiled.memory_analysis() per serving
        # executable (prefill buckets + decode) into the planner's
        # per-mesh table, where the backend implements the query.
        label = "serving/" + "/".join(str(k) for k in key)
        _mem_planner.record_compiled(label, compiled)

        # hvd-race donation sanitizer: every serving dispatch donates
        # the page arrays (positions 1, 2); routing the executable
        # through the registry turns a stale re-dispatch of donated
        # pages (a forgotten replace_pages) into a DonationError naming
        # this executable instead of XLA's opaque deletion error.
        def guarded(*call_args, _raw=compiled, _label=label):
            return _donation.guard_dispatch(_label, _raw, call_args,
                                            (1, 2))

        self._exec[key] = guarded
        self._record(key[0], key[1] if len(key) > 1 else None)
        return guarded

    def _rep(self, x) -> jnp.ndarray:
        """Tiny control array → device, replicated under a mesh."""
        a = jnp.asarray(x)
        if self.mesh is not None and self.cache.page_sharding() is not None:
            a = jax.device_put(a, NamedSharding(self.mesh, P()))
        return a

    def _decode_exec(self) -> Any:
        cfg, cache, B = self.cfg, self.cache, self.max_slots
        ps, pps, n_pages = (cache.page_size, cache.pages_per_slot,
                            cache.n_pages)
        L, H = cfg.n_layers, cfg.n_heads
        hd = cfg.d_model // H

        def kernel(params, k_pages, v_pages, table, lengths, tokens):
            k_view = k_pages[:, table].reshape(L, B, pps * ps, H, hd)
            v_view = v_pages[:, table].reshape(L, B, pps * ps, H, hd)
            # Width-2 block: [token, dummy]; the dummy column keeps the
            # gemms off XLA:CPU's bitwise-divergent single-row path and
            # is never sampled nor scattered.  The scheduler evicts at
            # prompt+generated == capacity, so the deepest decode here
            # runs at length == capacity-2 and the block always fits
            # the view; forward_step itself stays exact one position
            # further (it drops, not clamps, a row past the capacity).
            blk = jnp.stack([tokens, jnp.zeros_like(tokens)], axis=1)
            logits, k_new, v_new = _transformer.forward_step(
                params, blk, lengths, k_view, v_view, cfg)
            pos = jnp.clip(lengths, 0, None)
            page = table[jnp.arange(B), pos // ps]
            flat = page * ps + pos % ps
            kf = k_pages.reshape(L, n_pages * ps, H, hd)
            vf = v_pages.reshape(L, n_pages * ps, H, hd)
            kf = kf.at[:, flat].set(k_new[:, :, 0])
            vf = vf.at[:, flat].set(v_new[:, :, 0])
            return (logits[:, 0], kf.reshape(k_pages.shape),
                    vf.reshape(v_pages.shape))

        table, lengths = cache.device_tables()
        args = (self.params, cache.k_pages, cache.v_pages, table,
                lengths, self._rep(np.zeros((B,), np.int32)))
        return self._aot(("decode",), kernel, args)

    def _prefill_exec(self, bucket: int, draft: bool = False) -> Any:
        """Prefill executable, START-aware: ``start`` is the number of
        already-cached positions (0 for a cold prefill; the shared
        prefix length on a prefix-cache hit, so only the suffix runs
        through the model), ``n_valid`` the real token count in the
        padded ``tokens`` block — the last real token's logits are what
        admission samples from.  ``draft=True`` builds the same program
        over the draft model/cache (cold draft prefill on admission)."""
        cfg = self._draft_cfg if draft else self.cfg
        cache = self.draft_cache if draft else self.cache
        params = self._draft_params if draft else self.params
        ps, pps, n_pages = (cache.page_size, cache.pages_per_slot,
                            cache.n_pages)
        cap = cache.capacity
        L, H = cfg.n_layers, cfg.n_heads
        hd = cfg.d_model // H

        def kernel(params, k_pages, v_pages, table_row, start, n_valid,
                   tokens):
            k_view = k_pages[:, table_row].reshape(L, 1, pps * ps, H, hd)
            v_view = v_pages[:, table_row].reshape(L, 1, pps * ps, H, hd)
            logits, k_new, v_new = _transformer.forward_step(
                params, tokens, start, k_view, v_view, cfg)
            idx = start[0] + jnp.arange(bucket, dtype=jnp.int32)
            # Positions past the capacity (a deep suffix's padding) and
            # pad positions whose page is unmapped both land in trash
            # page 0; real positions are mapped by construction.
            page = jnp.where(
                idx < cap,
                table_row[0, jnp.clip(idx // ps, 0, pps - 1)], 0)
            flat = page * ps + idx % ps
            kf = k_pages.reshape(L, n_pages * ps, H, hd)
            vf = v_pages.reshape(L, n_pages * ps, H, hd)
            kf = kf.at[:, flat].set(k_new[:, 0])
            vf = vf.at[:, flat].set(v_new[:, 0])
            return (logits[0, n_valid[0] - 1],
                    kf.reshape(k_pages.shape), vf.reshape(v_pages.shape))

        args = (params, cache.k_pages, cache.v_pages,
                self._rep(np.zeros((1, pps), np.int32)),
                self._rep(np.zeros((1,), np.int32)),
                self._rep(np.ones((1,), np.int32)),
                self._rep(np.zeros((1, bucket), np.int32)))
        key = ("draft_prefill" if draft else "prefill", bucket)
        return self._aot(key, kernel, args)

    def _verify_exec(self) -> Any:
        """The speculative-decoding verify program: ONE donated target
        dispatch over the ``spec_tokens + 1``-wide block ``[pending,
        d_1..d_spec]`` for every slot, returning the full per-position
        logits (the host applies the bitwise-greedy acceptance rule to
        them — the same float32 argmax the non-speculative path runs,
        so accepted tokens are exactly the non-speculative greedy
        tokens) and scattering the block's KV.  Rejected positions'
        entries are rolled back host-side (the write cursor simply does
        not advance over them) and overwritten by the next iteration's
        block before they could ever unmask."""
        cfg, cache, B = self.cfg, self.cache, self.max_slots
        W = self.spec_tokens + 1
        ps, pps, n_pages = (cache.page_size, cache.pages_per_slot,
                            cache.n_pages)
        cap = cache.capacity
        L, H = cfg.n_layers, cfg.n_heads
        hd = cfg.d_model // H

        def kernel(params, k_pages, v_pages, table, lengths, blocks):
            k_view = k_pages[:, table].reshape(L, B, pps * ps, H, hd)
            v_view = v_pages[:, table].reshape(L, B, pps * ps, H, hd)
            logits, k_new, v_new = _transformer.forward_step(
                params, blocks, lengths, k_view, v_view, cfg)
            pos = (jnp.clip(lengths, 0, None)[:, None]
                   + jnp.arange(W, dtype=jnp.int32)[None, :])
            page = jnp.where(
                pos < cap,
                jnp.take_along_axis(table,
                                    jnp.clip(pos // ps, 0, pps - 1),
                                    axis=1), 0)
            flat = page * ps + pos % ps
            kf = k_pages.reshape(L, n_pages * ps, H, hd)
            vf = v_pages.reshape(L, n_pages * ps, H, hd)
            kf = kf.at[:, flat].set(k_new)
            vf = vf.at[:, flat].set(v_new)
            return (logits, kf.reshape(k_pages.shape),
                    vf.reshape(v_pages.shape))

        table, lengths = cache.device_tables()
        args = (self.params, cache.k_pages, cache.v_pages, table,
                lengths, self._rep(np.zeros((B, W), np.int32)))
        return self._aot(("verify", W), kernel, args)

    def _propose_exec(self) -> Any:
        """The draft's propose program: ONE donated dispatch unrolling
        ``spec_tokens`` greedy draft steps per slot
        (models/transformer.speculative_propose) and scattering the
        derived draft KV back into the draft's paged store."""
        dcfg, dcache, B = self._draft_cfg, self.draft_cache, \
            self.max_slots
        m = self.spec_tokens
        ps, pps, n_pages = (dcache.page_size, dcache.pages_per_slot,
                            dcache.n_pages)
        cap = dcache.capacity
        L, H = dcfg.n_layers, dcfg.n_heads
        hd = dcfg.d_model // H

        def kernel(params, k_pages, v_pages, table, lengths, prev,
                   pending):
            k_view = k_pages[:, table].reshape(L, B, pps * ps, H, hd)
            v_view = v_pages[:, table].reshape(L, B, pps * ps, H, hd)
            sp = lengths - 1
            proposals, kc, vc = _transformer.speculative_propose(
                params, prev, pending, sp, k_view, v_view, dcfg, m)
            pos = sp[:, None] + jnp.arange(m + 1, dtype=jnp.int32)[None]
            page = jnp.where(
                (pos >= 0) & (pos < cap),
                jnp.take_along_axis(table,
                                    jnp.clip(pos // ps, 0, pps - 1),
                                    axis=1), 0)
            flat = page * ps + jnp.where(pos >= 0, pos % ps, 0)
            kf = k_pages.reshape(L, n_pages * ps, H, hd)
            vf = v_pages.reshape(L, n_pages * ps, H, hd)
            kf = kf.at[:, flat].set(kc)
            vf = vf.at[:, flat].set(vc)
            return (proposals, kf.reshape(k_pages.shape),
                    vf.reshape(v_pages.shape))

        table, lengths = dcache.device_tables()
        args = (self._draft_params, dcache.k_pages, dcache.v_pages,
                table, lengths, self._rep(np.zeros((B,), np.int32)),
                self._rep(np.zeros((B,), np.int32)))
        return self._aot(("draft_propose", m), kernel, args)

    def _bucket_for(self, n: int) -> int:
        n = max(2, min(n, self.capacity))
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    # -- request surface ---------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               eos_id: Optional[int] = None, temperature: float = 0.0,
               seed: int = 0, arrival: int = 0,
               prefix: Optional[List[int]] = None) -> Request:
        """``prefix`` (relaunch continuations) is attached BEFORE the
        request enters the queue: a live serve loop may admit and
        sample it immediately, and the sampling rng keys on
        ``len(prefix) + len(generated)``."""
        req = Request(prompt=[int(t) for t in prompt],
                      max_new_tokens=max_new_tokens,
                      eos_id=self.eos_id if eos_id is None else eos_id,
                      temperature=temperature, seed=seed,
                      arrival=arrival)
        if prefix is not None:
            req.prefix = list(prefix)
        req.t_submit = time.perf_counter()
        return self.scheduler.submit(req)

    def generate(self, prompt: List[int], max_new_tokens: int = 32,
                 **kw) -> List[int]:
        """Synchronous convenience: submit + drive to completion."""
        req = self.submit(prompt, max_new_tokens, **kw)
        self.run_until_idle()
        return req.result(timeout=0)

    def run_until_idle(self, max_iterations: int = 1_000_000) -> int:
        """Drive :meth:`step` until queue and batch are empty; returns
        iterations run."""
        it = 0
        while not self.scheduler.idle() and it < max_iterations:
            self.step()
            it += 1
        return it

    # -- the continuous-batching iteration --------------------------------
    def step(self, now: Optional[int] = None, admit: bool = True) -> bool:
        """ONE iteration: admit into free slots (prefill each new
        sequence and sample its first token from the prefill logits —
        TTFT pays no decode-batching delay), then one batched decode
        over every active slot — sequences finish and admit mid-stream,
        no batch boundary.  ``now`` gates admission on logical arrival
        stamps (trace replay); None admits anything queued.
        ``admit=False`` skips admission entirely — that is the whole
        difference between this engine and a static batcher, and
        exactly how ``bench.py --mode serving`` builds its baseline
        (admit only at batch boundaries).  Returns whether any work
        ran.

        Multi-host: rank 0 (the only rank with a scheduler) broadcasts
        the admission plan, then post-prefill state, then the sampled
        tokens, so :meth:`follow` on worker ranks mirrors the cache and
        runs the identical executables in the same order."""
        mp = self._multiprocess()
        admitted = self._admit(now) if admit else []
        if mp:
            self._bcast({"stop": False,
                         "admit": [(slot, list(req.prompt))
                                   for slot, req in admitted]})
        for slot, req in admitted:
            self._prefill_and_sample(slot, req)
        # Clean abort of disconnected clients' slots (hvd-chaos): the
        # eviction happens HERE, at the iteration boundary on the
        # serve-loop thread — the only thread that may free KV slots —
        # and rides the step broadcast's evict list so follower cache
        # mirrors free the same pages (a handler-thread free would
        # silently desync the fleet).  _free_slot covers the draft's
        # pages too (disconnect mid-speculation).
        cancelled = [s for s in self.scheduler.evict_cancelled()
                     if self.cache.length(s) >= 0]
        for slot in cancelled:
            self._free_slot(slot)
        active = self.scheduler.active()
        # Page allocation (the host-side step that can raise — out of
        # pages) runs BEFORE the decode announcement: once a follower
        # reads a non-empty "decode" list it enters the compiled
        # program's collectives and cannot be reached by an abort
        # marker, so everything fallible on the host must happen first.
        # A speculative iteration writes spec_tokens positions past the
        # current length (target) and spec_tokens - 1 (draft), so the
        # whole block's pages map here; writes past the capacity drop
        # into trash inside the kernels.  An all-temperature batch
        # falls back to plain decode — sampled slots never consult
        # proposals, so propose + wide verify would be pure overhead
        # (the draft cache may lag for those slots; greedy slots only
        # ever ride spec iterations, which advance both caches in
        # lockstep, so their draft mirror stays exact).
        spec = (self._draft_params is not None
                and any(req.temperature <= 0.0 for _, req in active))
        depth = self.spec_tokens if spec else 0
        self._ensure_block(active, depth)
        if mp:
            # Post-prefill sync: first sampled tokens + which slots
            # survived into the decode batch (a max_new_tokens=1
            # admission can finish at prefill).
            self._bcast({
                "last": {s: int(self._last_token[s])
                         for s, _ in active},
                "decode": [s for s, _ in active],
                "spec": spec,
                "evict": cancelled + [s for s, _ in admitted
                                      if self.cache.length(s) < 0]})
        if active:
            if spec:
                self._speculative_iteration(active)
            else:
                self._decode_iteration(active)
        return bool(admitted or active)

    def _admit(self, now: Optional[int]) -> List[Tuple[int, Request]]:
        """Headroom-gated admission: the scheduler prices each
        candidate's prefill against the KV page budget (free list +
        the prefix cache's reclaimable pages) BEFORE burning a slot,
        so a request can never be admitted only to fail page
        allocation mid-iteration.  ``admission_cost`` is exact about
        prefix hits: referenced shared pages are free, reclaimable
        ones cost their LRU slot.  Under the default sizing the gate
        is a structural safety net — a free slot always implies
        headroom — but it keeps overcommitted or future configs
        honest (the pricing is pure: no refcounts move here)."""
        return self.scheduler.admit(
            now, page_budget=self.cache.free_pages(),
            pages_needed=lambda req:
                self.cache.admission_cost(req.prompt))

    def _ensure_block(self, active, depth: int) -> None:
        for slot, _ in active:
            ln = self.cache.length(slot)
            if ln < 0:
                continue
            self.cache.ensure(slot, min(ln + depth, self.capacity - 1))
            if depth and self.draft_cache is not None:
                self.draft_cache.ensure(
                    slot, min(ln + depth - 1,
                              self.draft_cache.capacity - 1))

    def _sample(self, req: Request, logits: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        z = (logits - logits.max()) / req.temperature
        p = np.exp(z)
        p /= p.sum()
        # Keyed on request-local state only (seed + decode position),
        # never on scheduler history (rid/slot), so a sampled rollout
        # reproduces across engines, relaunches, and batch mixes.
        rng = np.random.default_rng(
            (req.seed, len(req.prefix) + len(req.generated)))
        return int(rng.choice(len(p), p=p))

    def _free_slot(self, slot: int) -> None:
        """Release one slot's KV everywhere it exists: the target's
        pages (prefix refcounts decrement inside ``free_slot``) AND the
        draft's — a client disconnect mid-speculation must not strand
        draft pages (hvd-chaos).  Idempotent like the underlying
        frees."""
        self.cache.free_slot(slot)
        if self.draft_cache is not None:
            self.draft_cache.free_slot(slot)

    def _feed(self, slot: int, req: Request,
              token: int) -> Optional[str]:
        """Record one sampled/accepted token; returns the finish
        reason when this token ended the sequence (the speculative
        path stops feeding its block there), else None."""
        if not req.generated:
            req.t_first_token = time.perf_counter()
            _M_TTFT.observe(req.t_first_token - req.t_submit)
        _M_TOKENS.inc()
        # expect=req: a concurrent drain may have evicted the slot
        # mid-iteration — the token is then discarded (the exported
        # continuation reproduces it) instead of poisoning the step.
        reason = self.scheduler.feed(slot, token, expect=req)
        if reason is not None:
            req.t_done = time.perf_counter()
            self._free_slot(slot)  # idempotent vs the drain
            if _trace.enabled():
                # hvd-trace serving span: the whole request lifetime
                # (submit -> completion), reconstructed from the wall
                # stamps the engine already keeps — serving load on the
                # shared mesh is visible next to training cycles in
                # the fleet trace.
                now = time.monotonic()
                _trace.span(
                    "serving.request", "serving",
                    now - (req.t_done - req.t_submit), now,
                    args={"rid": req.rid,
                          "tokens": len(req.generated),
                          "reason": reason})
        else:
            self._last_token[slot] = token
        return reason

    def _prefill(self, slot: int, req: Request,
                 prompt: Optional[List[int]] = None) -> np.ndarray:
        """Admission prefill.  With a prefix-cache hit the shared pages
        map copy-free and ONLY the suffix runs through the model (the
        KV a suffix prefill derives is bitwise-identical to a cold
        full prefill's: every gemm is row-wise over M>=2 blocks, the
        same discipline the prefill+decode ≡ non-incremental contract
        already rides).  The completed prompt's full pages publish into
        the index afterwards, so the NEXT request sharing the header
        hits.  With a draft model, the draft's prefill rides its OWN
        shared-prefix index the same way (hvd-spec tail): a repeated
        header skips the draft prefill too, and the suffix-only draft
        KV is bitwise-identical to the cold full prefill's by the same
        M>=2 gemm discipline — the acceptance rule sees identical
        proposals either way."""
        prompt = list(req.prompt) if prompt is None else prompt
        n = len(prompt)
        shared = self.cache.lookup_prefix(prompt)
        n_shared = len(shared) * self.cache.page_size
        self.cache.begin_slot(slot, n, prefix_pages=shared)
        suffix = prompt[n_shared:]
        bucket = self._bucket_for(len(suffix))
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(suffix)] = suffix
        compiled = self._prefill_exec(bucket)
        with _oom.guard(f"serving/prefill/{bucket}"):
            last, kp, vp = compiled(
                self.params, self.cache.k_pages, self.cache.v_pages,
                self._rep(self.cache.table_row(slot)),
                self._rep(np.asarray([n_shared], np.int32)),
                self._rep(np.asarray([len(suffix)], np.int32)),
                self._rep(tokens))
        self.cache.replace_pages(kp, vp)
        self.cache.publish_prefix(slot, prompt)
        if self._draft_params is not None:
            dshared = self.draft_cache.lookup_prefix(prompt)
            dn_shared = len(dshared) * self.draft_cache.page_size
            self.draft_cache.begin_slot(slot, n, prefix_pages=dshared)
            dsuffix = prompt[dn_shared:]
            dbucket = self._bucket_for(len(dsuffix))
            dtokens = np.zeros((1, dbucket), np.int32)
            dtokens[0, :len(dsuffix)] = dsuffix
            dcompiled = self._prefill_exec(dbucket, draft=True)
            with _oom.guard(f"serving/draft_prefill/{dbucket}"):
                _, dkp, dvp = dcompiled(
                    self._draft_params, self.draft_cache.k_pages,
                    self.draft_cache.v_pages,
                    self._rep(self.draft_cache.table_row(slot)),
                    self._rep(np.asarray([dn_shared], np.int32)),
                    self._rep(np.asarray([len(dsuffix)], np.int32)),
                    self._rep(dtokens))
            self.draft_cache.replace_pages(dkp, dvp)
            self.draft_cache.publish_prefix(slot, prompt)
        self._prev_token[slot] = prompt[-1]
        _M_PREFILLS.inc()
        return np.asarray(last)

    def _decode_iteration(self, active) -> np.ndarray:
        """One batched decode over ``active``; the caller (step) has
        already run ``cache.ensure`` for every slot."""
        t0 = time.perf_counter()
        table, lengths = self.cache.device_tables()
        tokens = np.zeros((self.max_slots,), np.int32)
        for slot, _ in active:
            tokens[slot] = self._last_token[slot]
        compiled = self._decode_exec()
        with _oom.guard("serving/decode"):
            logits, kp, vp = compiled(
                self.params, self.cache.k_pages, self.cache.v_pages,
                table, lengths, self._rep(tokens))
        self.cache.replace_pages(kp, vp)
        logits_np = np.asarray(logits)
        fed = {}
        evicted = []
        for slot, req in active:
            self.cache.advance(slot)  # the input token's KV landed
            token = self._sample(req, logits_np[slot])
            fed[slot] = token
            self._feed(slot, req, token)
            if self.cache.length(slot) < 0:
                evicted.append(slot)
        if self._multiprocess():
            self._bcast({"tokens": fed, "evict": evicted})
        _M_DECODES.inc()
        _M_TOKEN_LAT.observe(time.perf_counter() - t0)
        return logits_np

    def _prefill_and_sample(self, slot: int, req: Request) -> None:
        last = self._prefill(slot, req)
        self._feed(slot, req, self._sample(req, last))

    # -- speculative decoding ---------------------------------------------
    def _spec_dispatch(self, slots: Sequence[int]):
        """The speculative iteration's two dispatches — draft propose,
        then target verify — shared verbatim by rank 0 and
        :meth:`follow` so the fleet's page arrays stay identical.
        Returns ``(proposals [B, spec_tokens], logits [B, spec_tokens
        + 1, vocab])`` as numpy."""
        B = self.max_slots
        prev = np.zeros((B,), np.int32)
        pending = np.zeros((B,), np.int32)
        for s in slots:
            prev[s] = self._prev_token[s]
            pending[s] = self._last_token[s]
        dtable, dlengths = self.draft_cache.device_tables()
        compiled = self._propose_exec()
        with _oom.guard(f"serving/draft_propose/{self.spec_tokens}"):
            proposals, dk, dv = compiled(
                self._draft_params, self.draft_cache.k_pages,
                self.draft_cache.v_pages, dtable, dlengths,
                self._rep(prev), self._rep(pending))
        self.draft_cache.replace_pages(dk, dv)
        props = np.asarray(proposals)
        W = self.spec_tokens + 1
        blocks = np.zeros((B, W), np.int32)
        for s in slots:
            blocks[s, 0] = pending[s]
            blocks[s, 1:] = props[s]
        table, lengths = self.cache.device_tables()
        compiled = self._verify_exec()
        with _oom.guard(f"serving/verify/{W}"):
            logits, kp, vp = compiled(
                self.params, self.cache.k_pages, self.cache.v_pages,
                table, lengths, self._rep(blocks))
        self.cache.replace_pages(kp, vp)
        return props, np.asarray(logits)

    def _speculative_iteration(self, active) -> None:
        """One speculative iteration over ``active``: propose + verify
        (two dispatches total — the draft's and the target's), then the
        host-side bitwise-greedy acceptance.  For a greedy slot the
        accepted tokens plus the correction/bonus token are EXACTLY the
        tokens non-speculative greedy decode would emit (the verify
        logits are bitwise-equal to the decode executable's at every
        position — the M>=2 gemm discipline — and the acceptance rule
        is the same float32 argmax), so the engine's bitwise contract
        survives any draft, any acceptance pattern, any batch mix.  A
        temperature slot samples from the block's first position only —
        bitwise what the decode path would sample.  Rejected tail:
        the write cursor (cache lengths) just does not advance over it;
        the pages stay masked and the next block overwrites them."""
        t0 = time.perf_counter()
        m = self.spec_tokens
        props, logits_np = self._spec_dispatch([s for s, _ in active])
        fed: Dict[int, int] = {}
        prev: Dict[int, int] = {}
        advance: Dict[int, int] = {}
        evicted: List[int] = []
        for slot, req in active:
            if req.temperature <= 0.0:
                greedy = np.argmax(logits_np[slot], axis=-1)
                accept = 0
                while (accept < m
                       and int(props[slot, accept])
                       == int(greedy[accept])):
                    accept += 1
                emitted = [int(props[slot, j]) for j in range(accept)]
                emitted.append(int(greedy[accept]))
                # Greedy slots only: a temperature slot never consults
                # the proposals (accept == 0 by construction), so
                # counting it would dilute spec_acceptance_rate — the
                # gauge operators size spec_tokens by.
                self._spec_proposed += m
                self._spec_accepted += accept
                _M_SPEC_PROPOSED.inc(m)
                if accept:
                    _M_SPEC_ACCEPTED.inc(accept)
            else:
                emitted = [self._sample(req, logits_np[slot, 0])]
                accept = 0
            last_before = int(self._last_token[slot])
            finished = False
            for t in emitted:
                if self._feed(slot, req, t) is not None:
                    finished = True
                    break
            if finished or self.cache.length(slot) < 0:
                evicted.append(slot)
                continue
            # The accepted inputs' KV is now valid: pending plus the
            # accepted drafts (the bonus token is the new pending — its
            # KV lands next iteration).
            n_adv = 1 + accept
            for _ in range(n_adv):
                self.cache.advance(slot)
                self.draft_cache.advance(slot)
            self._prev_token[slot] = (emitted[-2] if len(emitted) >= 2
                                      else last_before)
            fed[slot] = int(self._last_token[slot])
            prev[slot] = int(self._prev_token[slot])
            advance[slot] = n_adv
        if self._spec_proposed:
            _M_SPEC_RATE.set(self._spec_accepted / self._spec_proposed)
        if self._multiprocess():
            self._bcast({"tokens": fed, "prev": prev,
                         "advance": advance, "evict": evicted})
        _M_DECODES.inc()
        _M_TOKEN_LAT.observe(time.perf_counter() - t0)

    @property
    def spec_acceptance_rate(self) -> Optional[float]:
        """Cumulative accepted/proposed draft-token ratio (None before
        the first speculative iteration)."""
        if not self._spec_proposed:
            return None
        return self._spec_accepted / self._spec_proposed

    def set_spec_tokens(self, n: int) -> None:
        """hvd-tune live retune (tuning/actuation.py): change the
        speculative depth between iterations.  The propose/verify
        programs are keyed by depth, so the next iteration compiles (or
        reuses) the executables for the new block size — no flush."""
        n = int(n)
        if n < 1:
            raise ValueError(f"spec_tokens must be >= 1, got {n}")
        self.spec_tokens = n

    def spec_token_bytes(self) -> int:
        """Per-spec-token byte cost for the hvd-mem pricing of
        spec_tokens retunes: one target + one draft KV token column per
        slot (the verify writes target KV for every proposed token)."""
        per_tok = 0
        for cache in (self.cache, self.draft_cache):
            if cache is not None:
                per_tok += cache.page_global_bytes // cache.page_size
        return per_tok * self.max_slots

    # -- multi-host mirroring ---------------------------------------------
    def _multiprocess(self) -> bool:
        try:
            from ..core import state as _state

            return (_state.is_initialized()
                    and _state.global_state().multiprocess
                    and _state.global_state().process_count > 1)
        except Exception:  # noqa: BLE001 — serving works without init
            return False

    def _bcast(self, obj):
        from ..ops.objects import broadcast_object

        return broadcast_object(obj, root_rank=0, name="hvd-serve-plan")

    def follow(self) -> bool:
        """Worker-rank iteration mirroring ONE rank-0 :meth:`step`:
        receive the admission plan (prefill those slots), the
        post-prefill sync (first tokens + decode batch + early
        evictions), run the identical decode executable when rank 0
        does, then apply its sampled tokens/evictions to the local
        cache mirror.  Returns False when rank 0 announced shutdown
        (:meth:`stop_followers`).  Worker ranks have no scheduler —
        rank 0 decides, the data plane stays SPMD.

        Any of the three receptions may instead carry rank 0's
        ``abort`` marker (:meth:`abort_all` after a poisoned step died
        mid-iteration): the worker mirrors the recovery by freeing
        every cache slot and returning, keeping the fleet's caches
        identical for the next iteration."""
        plan = self._bcast(None)
        if plan.get("stop"):
            return False
        if plan.get("abort"):
            self._free_all_slots()
            return True
        for slot, prompt in plan.get("admit", ()):
            self._prefill(slot, Request(prompt=list(prompt)),
                          prompt=list(prompt))
        sync = self._bcast(None)
        if sync.get("abort"):
            self._free_all_slots()
            return True
        for slot, token in sync.get("last", {}).items():
            self._last_token[int(slot)] = int(token)
        for slot in sync.get("evict", ()):
            if self.cache.length(int(slot)) >= 0:
                self._free_slot(int(slot))
        decode = [int(s) for s in sync.get("decode", ())]
        if decode:
            spec = bool(sync.get("spec")) \
                and self._draft_params is not None
            self._ensure_block([(s, None) for s in decode],
                               self.spec_tokens if spec else 0)
            if spec:
                # Same two dispatches as rank 0 (_spec_dispatch), then
                # apply ITS acceptance results — host argmax is
                # deterministic, but the broadcast keeps the mirror
                # trivially exact.
                self._spec_dispatch(decode)
            else:
                table, lengths = self.cache.device_tables()
                tokens = np.zeros((self.max_slots,), np.int32)
                for slot in decode:
                    tokens[slot] = self._last_token[slot]
                compiled = self._decode_exec()
                with _oom.guard("serving/decode"):
                    _, kp, vp = compiled(
                        self.params, self.cache.k_pages,
                        self.cache.v_pages, table, lengths,
                        self._rep(tokens))
                self.cache.replace_pages(kp, vp)
            fed = self._bcast(None)
            if fed.get("abort"):
                # Rank 0's decode/speculative iteration died before
                # broadcasting the sampled tokens; it freed everything
                # — mirror that (and skip the advance: rank 0 never
                # advanced).
                self._free_all_slots()
                return True
            if spec:
                for slot, n_adv in fed.get("advance", {}).items():
                    for _ in range(int(n_adv)):
                        self.cache.advance(int(slot))
                        self.draft_cache.advance(int(slot))
                for slot, token in fed.get("prev", {}).items():
                    self._prev_token[int(slot)] = int(token)
            else:
                for slot in decode:
                    self.cache.advance(slot)
            for slot, token in fed.get("tokens", {}).items():
                self._last_token[int(slot)] = int(token)
            for slot in fed.get("evict", ()):
                if self.cache.length(int(slot)) >= 0:
                    self._free_slot(int(slot))
        return True

    def stop_followers(self) -> None:
        if self._multiprocess():
            self._bcast({"stop": True})

    # -- elastic drain / resume -------------------------------------------
    @staticmethod
    def _export_request(req: Request) -> dict:
        """A request as a resubmittable continuation: prompt extended
        by what it generated so far (the bitwise prefill≡decode
        contract makes the continuation reproduce the uninterrupted
        greedy rollout).  A queued request has ``generated == []``, so
        this reduces to its original submission.  ``generated`` is read
        ONCE: export_requests() can run concurrently with the serve
        loop's feed(), and deriving the three fields from different
        generation states would commit an internally inconsistent
        continuation."""
        gen = list(req.generated)
        return {
            "prompt": list(req.prompt) + gen,
            "generated_prefix": list(req.prefix) + gen,
            "max_new_tokens": req.max_new_tokens - len(gen),
            "eos_id": req.eos_id, "temperature": req.temperature,
            "seed": req.seed,
        }

    def export_requests(self) -> List[dict]:
        """Queued + in-flight work as resubmittable dicts (one atomic
        scheduler snapshot — a request admitted concurrently cannot fall
        between the active and pending halves).  Does not stop the
        engine — pair with :meth:`drain` for the elastic resize path
        (:class:`horovod_tpu.elastic.ServingState`).
        """
        active, pending = self.scheduler.snapshot()
        return [self._export_request(req)
                for req in [r for _, r in active] + pending]

    def drain(self) -> List[dict]:
        """Serving-fleet resize, step 1: capture every queued and
        in-flight request as a continuation, then evict everything and
        stop admission.  The export is built from exactly the requests
        the scheduler's drain removed (one lock hold), so a submission
        racing the drain is either exported or rejected — never lost.
        The returned list (same format as :meth:`export_requests`) is
        what the elastic commit persists; a relaunched engine resubmits
        it via :meth:`import_requests`."""
        with self._drain_lock:
            self._drained = True
            drained, pending = self._drain_and_finish(
                FinishReason.DRAINED)
        return [self._export_request(req) for req in drained + pending]

    def _free_all_slots(self) -> None:
        for slot in range(self.max_slots):
            if self.cache.length(slot) >= 0:
                self._free_slot(slot)

    # -- shared-prefix index export / rebuild ------------------------------
    def export_prefix_index(self) -> List[List[int]]:
        """The prefix cache's maximal cached chains as token-id lists
        (hash → token ids) — what ``elastic.ServingState.drain_commit``
        persists next to the continuations so a relaunched fleet
        rebuilds the shared pages instead of re-prefilling every
        cached prefix cold."""
        return self.cache.export_prefixes()

    def seed_prefixes(self, prefixes: Sequence[Sequence[int]]) -> int:
        """Rebuild exported prefixes into this engine's cache: each
        chain prefills ONCE through a ghost page row (no decode slot
        burned) and publishes with refcount zero — immediately
        hittable, reclaimable under pressure.  Returns the number of
        pages seeded."""
        if not self.cache.prefix_enabled:
            return 0
        seeded = 0
        ps = self.cache.page_size
        for chain in prefixes:
            tokens = [int(t) for t in chain]
            n_pages = min(len(tokens) // ps,
                          self.cache.pages_per_slot)
            if n_pages <= 0:
                continue
            tokens = tokens[:n_pages * ps]
            # +[0] sentinel: lookup_prefix only matches STRICT
            # prefixes; the sentinel never reaches a full page, so
            # this checks whether all n_pages are already cached.
            if len(self.cache.lookup_prefix(tokens + [0])) >= n_pages:
                continue
            row = self.cache.alloc_ghost(n_pages)
            n = len(tokens)
            bucket = self._bucket_for(n)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :n] = tokens
            try:
                compiled = self._prefill_exec(bucket)
                with _oom.guard(f"serving/prefill/{bucket}"):
                    _, kp, vp = compiled(
                        self.params, self.cache.k_pages,
                        self.cache.v_pages, self._rep(row),
                        self._rep(np.zeros((1,), np.int32)),
                        self._rep(np.asarray([n], np.int32)),
                        self._rep(toks))
            except Exception as e:  # noqa: BLE001 — seeding is an
                # optimization: one failed chain must neither strand
                # its ghost pages (the sizing invariant would silently
                # erode) nor abort the elastic restore that still has
                # requests to resubmit after this.
                self.cache.free_ghost(row)
                _telemetry.exception_event(
                    "serve-seed-prefix",
                    f"dropping {n_pages}-page prefix seed: "
                    f"{type(e).__name__}: {e}")
                continue
            self.cache.replace_pages(kp, vp)
            seeded += self.cache.publish_ghost(row, tokens)
        return seeded

    def _drain_and_finish(self, reason: str):
        """The shared eviction sequence (caller holds ``_drain_lock``):
        scheduler drain with ``reason``, free every KV slot, and finish
        the still-queued requests' Python objects with the same reason
        — their blocked /generate handlers fail fast instead of hanging
        to the client timeout (the relaunch path resubmits NEW Request
        objects from the export, so finishing these loses nothing).
        Returns ``(drained, pending)``."""
        drained, pending = self.scheduler.drain(reason)
        self._free_all_slots()
        for req in pending:
            req.finish_reason = reason
            req.done.set()
        return drained, pending

    def abort_all(self) -> List[Request]:
        """Error recovery (the serve loop's poisoned-step path):
        atomically evict and FAIL every queued and in-flight request —
        ``finish_reason`` is ``"error"`` before ``done`` is set, so a
        blocked ``/generate`` handler can never observe a stale reason —
        free the KV slots, and re-open admission.  Unlike :meth:`drain`
        nothing is exported: callers answer the failed requests
        immediately instead of requeueing them.  Returns the failed
        requests (raced submissions included).

        Admission re-opens ONLY when no elastic :meth:`drain` is
        pending (checked under the same lock the drain holds, so the
        recovery cannot interleave with a concurrent drain_commit and
        resume after it): if the loop's recovery fires after a drain
        committed, resuming here would admit requests the commit never
        captured — silently lost at relaunch.

        Multi-host: broadcasts an abort marker so blocked
        :meth:`follow` ranks (waiting for the sync/tokens of the step
        that just died) free their cache mirrors too — without it the
        fleet's caches diverge and every later decode breaks the
        bitwise contract."""
        # The class threading contract, machine-checked (hvd-race):
        # under multiprocess only the serve-loop thread may call
        # abort_all; a stamped runtime thread of any other role
        # entering here raises ThreadRoleError.  Unstamped (user/main)
        # threads pass — single-process callers may treat abort_all
        # like the rest of the drain family.
        _athreads.require("serve-loop", "InferenceEngine.abort_all")
        # Broadcast OUTSIDE the lock: a wedged control plane blocks a
        # collective forever (no timeout), and holding _drain_lock
        # across it would deadlock the elastic thread's drain/import
        # too.  Under multiprocess only the serve-loop thread may call
        # abort_all (the class threading contract), so the marker
        # cannot interleave with a concurrent step()'s broadcasts — a
        # follower consuming an abort where it expected a plan/sync
        # would silently desynchronize the fleet's caches.
        if self._multiprocess():
            try:
                self._bcast({"abort": True})
            except Exception:  # noqa: BLE001 — a dead control
                pass  # plane must not stop the LOCAL recovery
        with self._drain_lock:
            drained, pending = self._drain_and_finish(
                FinishReason.ERROR)
            if not self._drained:
                self.scheduler.resume()
        return drained + pending

    def abort_request(self, req: Request,
                      reason: str = FinishReason.CLIENT_DISCONNECT
                      ) -> str:
        """Clean abort of ONE request (the /generate client vanished,
        hvd-chaos hardening): a queued request finishes immediately; an
        active one is marked and evicted by the serve loop at its next
        iteration boundary — the existing eviction path, so the KV slot
        is released identically on every rank.  Returns the scheduler's
        "queued"/"active"/"gone" disposition."""
        disposition = self.scheduler.cancel(req, reason)
        _flight.record("serve_abort_request", req.rid, reason,
                       disposition)
        return disposition

    def import_requests(self, exported: List[dict]) -> List[Request]:
        """Resubmit a drained export (relaunch path).  Continuation
        requests keep their already-generated prefix, so callers see
        uninterrupted results.  The whole resume+resubmit runs under
        the drain lock: a concurrent abort_all/drain landing mid-loop
        would otherwise make ``submit`` raise and silently drop the
        not-yet-resubmitted tail of the committed export.  A
        continuation this engine cannot admit (its prompt outgrew a
        SHRUNK capacity across the resize) is skipped with a flight-
        recorder event — one oversized request must not abort the loop
        and drop the rest of the committed export with it."""
        with self._drain_lock:
            if self._drained:
                self.scheduler.resume()
                self._drained = False
            out = []
            for d in exported:
                if d.get("max_new_tokens", 0) <= 0:
                    continue
                try:
                    out.append(self.submit(
                        d["prompt"], max_new_tokens=d["max_new_tokens"],
                        eos_id=d.get("eos_id"),
                        temperature=d.get("temperature", 0.0),
                        seed=d.get("seed", 0),
                        prefix=d.get("generated_prefix", [])))
                except ValueError as e:
                    _telemetry.exception_event(
                        "serve-import",
                        f"dropping unresumable continuation "
                        f"({len(d['prompt'])} prompt tokens vs "
                        f"capacity {self.capacity}): {e}")
        return out
