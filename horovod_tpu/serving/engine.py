"""The hvd-serve inference engine: donated AOT prefill/decode executables
over the paged KV cache, driven by the continuous-batching scheduler.

Megakernel-style data plane (docs/inference.md): each serving phase is
ONE compiled XLA program — page-table gather → cache-aware forward
(:func:`..models.transformer.forward_step`) → scatter of the new KV
entries back into the paged store — with the page arrays donated, so a
decode iteration is a single dispatch whose working set updates in
place.  Executables are built ahead of time (``jit(...).lower(...)
.compile()``) and recorded in the PR-5 persistent-cache manifest under
``variant: "serving"`` (ops/megakernel.py ``record_manifest_entry``):
:meth:`InferenceEngine.warm_start` rebuilds every recorded executable
at startup — against a warm ``HVD_TPU_COMPILE_CACHE_DIR`` the XLA
compile is a disk-cache read — so a relaunched serving fleet reaches
full token rate before its first request, and ``/healthz`` reports
NOT_READY until it has.

Bitwise contract (CI-gated by tests/test_serving.py and ``bench.py
--mode serving``): a prefill of the prompt followed by N single-token
decode iterations reproduces, bit for bit, the logits of the
non-incremental :func:`..models.transformer.serving_forward` of the
same tokens — greedy generation is therefore exactly reproducible
across the static/continuous schedulers, batch compositions, slot
assignments, and engine relaunches.  Two rules carry it: every token
block is at least 2 wide (decode pads a discarded dummy column —
XLA:CPU's single-row gemv accumulates differently from the gemm every
other width uses), and comparisons are jit↔jit (the eager path fuses
differently).

Multi-host serving: rank 0 owns the scheduler and the HTTP front door;
workers mirror its per-iteration plan (admissions, then sampled
tokens/evictions) over the control plane's object collectives and run
the identical executables — the same rank-0-decides/broadcast
convention the checkpoint and elastic paths use.  Like every
multi-process data-plane leg, this needs a jax build whose CPU backend
executes np>1 collectives (CI), not the container's 0.4.37.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .. import telemetry as _telemetry
from .. import trace as _trace
from ..analysis import lockorder as _lockorder
from ..core.topology import MODEL_AXIS
from ..memory import oom as _oom
from ..memory import planner as _mem_planner
from ..telemetry import flight as _flight
from ..models import transformer as _transformer
from ..ops import megakernel as _megakernel
from .kv_cache import PagedKVCache
from .scheduler import (ContinuousBatchingScheduler, FinishReason,
                        Request)

_M_TTFT = _telemetry.histogram(
    "serving.ttft_seconds", "seconds",
    "time from submission to the first generated token")
_M_TOKEN_LAT = _telemetry.histogram(
    "serving.token_seconds", "seconds",
    "per-token decode latency (one continuous-batching iteration)")
_M_TOKENS = _telemetry.counter(
    "serving.tokens_generated", "tokens sampled across all sequences")
_M_PREFILLS = _telemetry.counter(
    "serving.prefills", "prefill executions (one per admission)")
_M_DECODES = _telemetry.counter(
    "serving.decode_iterations", "batched decode iterations")
_M_WARM = _telemetry.counter(
    "serving.warm_starts", "serving executables AOT-rebuilt at startup")


class InferenceEngine:
    """Continuous-batching inference over one transformer LM.

    ``params``/``cfg`` are the training-side parameter pytree and
    :class:`~horovod_tpu.models.transformer.TransformerConfig`.  With a
    ``mesh`` that has a ``model`` axis, the KV head axis and the
    attention/FFN compute shard over it exactly like the training
    forward (the ``parallel/tensor.py`` layout, via GSPMD).  Threading
    contract: the data plane (``step``/``follow``/``generate``/
    ``run_until_idle``) is driven from ONE thread (the serve loop);
    ``submit`` is thread-safe (the scheduler's lock), and the
    drain-family methods — ``drain``, ``import_requests``,
    ``export_requests`` — may run from other threads (the elastic
    resize path) concurrently with the loop, serialized by
    ``_drain_lock``.  ``abort_all`` is the exception: it broadcasts on
    the control plane, so under multiprocess it must be called from
    the serve-loop thread only (between iterations — see its
    docstring); single-process callers may treat it like the rest of
    the drain family.
    """

    def __init__(self, params: Any, cfg, *, mesh=None, max_slots: int = 8,
                 page_size: int = 16, capacity: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 model_axis: str = MODEL_AXIS) -> None:
        cap = capacity if capacity is not None else cfg.max_seq_len
        cap = min(cap, cfg.max_seq_len)
        cap -= cap % page_size
        # Compare against the page-floored max_seq_len, or the default
        # capacity (None -> max_seq_len) is spuriously rejected when
        # page_size < max_seq_len < 2*page_size with an unaligned
        # max_seq_len.
        max_cap = cfg.max_seq_len - cfg.max_seq_len % page_size
        if cap < 2 * page_size and cap < max_cap:
            raise ValueError(
                f"capacity {capacity} too small for page_size "
                f"{page_size} (needs >= 2 pages' worth or "
                f"max_seq_len)")
        if cap < 2:
            raise ValueError("KV capacity must be >= 2")
        self.cfg = cfg
        self.mesh = mesh
        self.eos_id = eos_id
        self.max_slots = max_slots
        self.cache = PagedKVCache(
            cfg.n_layers, cfg.n_heads, cfg.d_model // cfg.n_heads,
            max_slots, cap // page_size, page_size,
            dtype=cfg.dtype, mesh=mesh, model_axis=model_axis)
        self.capacity = self.cache.capacity
        self.scheduler = ContinuousBatchingScheduler(max_slots,
                                                     self.capacity)
        if mesh is not None and self.cache.page_sharding() is not None:
            rep = NamedSharding(mesh, P())
            params = jax.tree_util.tree_map(
                lambda x: jax.device_put(jnp.asarray(x), rep), params)
        else:
            params = jax.tree_util.tree_map(jnp.asarray, params)
        self.params = params
        self._buckets = [b for b in
                         (2 ** i for i in range(1, 31))
                         if b <= self.capacity]
        if self._buckets[-1] != self.capacity:
            self._buckets.append(self.capacity)
        self._exec: Dict[Tuple, Any] = {}
        self._last_token = np.zeros((max_slots,), np.int32)
        self._ready = False
        self._drained = False
        # Serializes drain/abort_all/import_requests: the serve loop's
        # recovery and the elastic thread's drain_commit run
        # concurrently, and "_drained" check-then-acts must be atomic
        # with the scheduler drain they guard (or a recovery could
        # re-open admission after a commit and silently lose requests).
        # Ordering: _drain_lock is taken BEFORE scheduler._lock, never
        # across a collective (which can block indefinitely).
        self._drain_lock = _lockorder.make_lock(
            "serving.InferenceEngine._drain_lock")
        self._manifest_dir: Optional[str] = None  # warm_start override

    # -- readiness / warm start -------------------------------------------
    @property
    def ready(self) -> bool:
        """True once :meth:`warm_start` completed — the ``/healthz``
        readiness bit (NOT_READY before; the load-balancer keeps
        traffic away until the executables exist)."""
        return self._ready

    def mark_unready(self) -> None:
        """Failure latch: flip ``/healthz`` back to NOT_READY.  Called
        when recovery itself failed and the engine's state can no
        longer be trusted — the load balancer drains traffic instead
        of feeding requests into a blackhole."""
        self._ready = False

    def health(self) -> Tuple[bool, dict]:
        """Exporter health contributor (exporter.register_health).
        ``kv_free_pages`` is the hvd-mem satellite: the router tier
        needs admission HEADROOM (can this replica take a long prompt)
        next to queue depth — occupancy alone says nothing about how
        full the occupied slots' page budgets are."""
        return self._ready, {
            "ready": self._ready,
            "queue_depth": self.scheduler.queue_depth(),
            "batch_occupancy": self.scheduler.occupancy(),
            "kv_free_pages": self.cache.free_pages(),
            "kv_total_pages": self.cache.total_pages,
            "slots": self.max_slots,
            "executables": len(self._exec),
        }

    def warm_start(self, directory: Optional[str] = None) -> int:
        """Build the decode executable plus every serving executable the
        persistent-cache manifest recorded for this model/mesh, then
        mark the engine ready.  On a relaunch with a warm
        ``HVD_TPU_COMPILE_CACHE_DIR`` the compiles are disk-cache
        reads — the fleet serves at full token rate from the first
        request.  A non-None ``directory`` is also where this engine
        RECORDS its executables from now on (read and write sides must
        agree, or a custom warm-start dir never accumulates entries); a
        ``None`` directory keeps a previously chosen one rather than
        reverting to the env default.  Returns the number of manifest
        entries rebuilt."""
        if directory is None:
            directory = self._manifest_dir
        self._manifest_dir = directory
        ident = self._manifest_identity()
        warmed = 0
        for entry in _megakernel.serving_entries(directory):
            if any(entry.get(k) != ident[k]
                   for k in ("model", "mesh", "slots", "page_size",
                             "pages_per_slot")):
                continue
            try:
                if entry.get("kind") == "decode":
                    self._decode_exec()
                elif entry.get("kind") == "prefill":
                    b = int(entry.get("bucket") or 0)
                    if b in self._buckets:
                        self._prefill_exec(b)
                    else:
                        continue
                else:
                    continue
                warmed += 1
            except Exception:  # noqa: BLE001 — a stale entry must not
                continue       # block startup; it just compiles lazily
        self._decode_exec()  # readiness == "can decode", manifest or not
        if warmed:
            _M_WARM.inc(warmed)
        # hvd-mem pre-flight: the engine's PER-DEVICE working set (one
        # KV shard — global/tp when the head axis is sharded — plus
        # one copy of the replicated params) against the per-device
        # HBM capacity — warned HERE, before the load balancer routes
        # traffic at a replica that cannot actually hold its cache.
        # Per-device, not global and not a per-process sum: either of
        # those cries wolf on exactly the large sharded multi-device
        # deployments this check targets (docs/memory.md).
        try:
            from ..memory import ledger as _mem_ledger

            per_device = (_mem_ledger.device_nbytes(self.cache.k_pages)
                          + _mem_ledger.device_nbytes(
                              self.cache.v_pages)
                          + sum(_mem_ledger.device_nbytes(x) for x in
                                jax.tree_util.tree_leaves(self.params)))
            _oom.preflight_warn(per_device, "serving.warm_start",
                                "KV shard + replicated params "
                                "(per-device bytes)")
        except Exception:  # noqa: BLE001 — sizing is observability
            pass
        self._ready = True
        return warmed

    # -- manifest ----------------------------------------------------------
    def _mesh_key(self):
        if self.mesh is not None:
            return tuple(self.mesh.devices.flat)
        return (jax.devices()[0],)

    def _manifest_identity(self) -> dict:
        return {
            "variant": "serving",
            "model": {
                "vocab_size": self.cfg.vocab_size,
                "d_model": self.cfg.d_model,
                "n_heads": self.cfg.n_heads,
                "n_layers": self.cfg.n_layers,
                "d_ff": self.cfg.d_ff,
                "max_seq_len": self.cfg.max_seq_len,
                "dtype": jnp.dtype(self.cfg.dtype).name,
            },
            "slots": self.max_slots,
            "page_size": self.cache.page_size,
            "pages_per_slot": self.cache.pages_per_slot,
            "mesh": _megakernel.mesh_fingerprint(self._mesh_key()),
        }

    def _record(self, kind: str, bucket: Optional[int]) -> None:
        entry = dict(self._manifest_identity())
        entry["kind"] = kind
        entry["bucket"] = bucket
        _megakernel.record_manifest_entry(entry, self._manifest_dir)

    # -- executables -------------------------------------------------------
    def _aot(self, key: Tuple, fn, args: Tuple) -> Any:
        """Compile ``fn`` for ``args``' shapes/shardings (donating the
        page arrays at positions 1 and 2) and cache the executable."""
        compiled = self._exec.get(key)
        if compiled is not None:
            return compiled
        avals = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding), args)
        jfn = jax.jit(fn, donate_argnums=(1, 2))
        compiled = jfn.lower(*avals).compile()
        # hvd-mem: harvest compiled.memory_analysis() per serving
        # executable (prefill buckets + decode) into the planner's
        # per-mesh table, where the backend implements the query.
        _mem_planner.record_compiled(
            "serving/" + "/".join(str(k) for k in key), compiled)
        self._exec[key] = compiled
        self._record(key[0], key[1] if len(key) > 1 else None)
        return compiled

    def _rep(self, x) -> jnp.ndarray:
        """Tiny control array → device, replicated under a mesh."""
        a = jnp.asarray(x)
        if self.mesh is not None and self.cache.page_sharding() is not None:
            a = jax.device_put(a, NamedSharding(self.mesh, P()))
        return a

    def _decode_exec(self) -> Any:
        cfg, cache, B = self.cfg, self.cache, self.max_slots
        ps, pps, n_pages = (cache.page_size, cache.pages_per_slot,
                            cache.n_pages)
        L, H = cfg.n_layers, cfg.n_heads
        hd = cfg.d_model // H

        def kernel(params, k_pages, v_pages, table, lengths, tokens):
            k_view = k_pages[:, table].reshape(L, B, pps * ps, H, hd)
            v_view = v_pages[:, table].reshape(L, B, pps * ps, H, hd)
            # Width-2 block: [token, dummy]; the dummy column keeps the
            # gemms off XLA:CPU's bitwise-divergent single-row path and
            # is never sampled nor scattered.  The scheduler evicts at
            # prompt+generated == capacity, so the deepest decode here
            # runs at length == capacity-2 and the block always fits
            # the view; forward_step itself stays exact one position
            # further (it drops, not clamps, a row past the capacity).
            blk = jnp.stack([tokens, jnp.zeros_like(tokens)], axis=1)
            logits, k_new, v_new = _transformer.forward_step(
                params, blk, lengths, k_view, v_view, cfg)
            pos = jnp.clip(lengths, 0, None)
            page = table[jnp.arange(B), pos // ps]
            flat = page * ps + pos % ps
            kf = k_pages.reshape(L, n_pages * ps, H, hd)
            vf = v_pages.reshape(L, n_pages * ps, H, hd)
            kf = kf.at[:, flat].set(k_new[:, :, 0])
            vf = vf.at[:, flat].set(v_new[:, :, 0])
            return (logits[:, 0], kf.reshape(k_pages.shape),
                    vf.reshape(v_pages.shape))

        table, lengths = cache.device_tables()
        args = (self.params, cache.k_pages, cache.v_pages, table,
                lengths, self._rep(np.zeros((B,), np.int32)))
        return self._aot(("decode",), kernel, args)

    def _prefill_exec(self, bucket: int) -> Any:
        cfg, cache = self.cfg, self.cache
        ps, pps, n_pages = (cache.page_size, cache.pages_per_slot,
                            cache.n_pages)
        L, H = cfg.n_layers, cfg.n_heads
        hd = cfg.d_model // H

        def kernel(params, k_pages, v_pages, table_row, length, tokens):
            k_view = k_pages[:, table_row].reshape(L, 1, pps * ps, H, hd)
            v_view = v_pages[:, table_row].reshape(L, 1, pps * ps, H, hd)
            logits, k_new, v_new = _transformer.forward_step(
                params, tokens, jnp.zeros((1,), jnp.int32),
                k_view, v_view, cfg)
            i = jnp.arange(bucket)
            page = table_row[0, i // ps]
            flat = page * ps + i % ps  # pad positions land in trash
            kf = k_pages.reshape(L, n_pages * ps, H, hd)
            vf = v_pages.reshape(L, n_pages * ps, H, hd)
            kf = kf.at[:, flat].set(k_new[:, 0])
            vf = vf.at[:, flat].set(v_new[:, 0])
            return (logits[0, length[0] - 1],
                    kf.reshape(k_pages.shape), vf.reshape(v_pages.shape))

        args = (self.params, cache.k_pages, cache.v_pages,
                self._rep(np.zeros((1, pps), np.int32)),
                self._rep(np.ones((1,), np.int32)),
                self._rep(np.zeros((1, bucket), np.int32)))
        return self._aot(("prefill", bucket), kernel, args)

    def _bucket_for(self, n: int) -> int:
        n = max(2, min(n, self.capacity))
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    # -- request surface ---------------------------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int = 32,
               eos_id: Optional[int] = None, temperature: float = 0.0,
               seed: int = 0, arrival: int = 0,
               prefix: Optional[List[int]] = None) -> Request:
        """``prefix`` (relaunch continuations) is attached BEFORE the
        request enters the queue: a live serve loop may admit and
        sample it immediately, and the sampling rng keys on
        ``len(prefix) + len(generated)``."""
        req = Request(prompt=[int(t) for t in prompt],
                      max_new_tokens=max_new_tokens,
                      eos_id=self.eos_id if eos_id is None else eos_id,
                      temperature=temperature, seed=seed,
                      arrival=arrival)
        if prefix is not None:
            req.prefix = list(prefix)
        req.t_submit = time.perf_counter()
        return self.scheduler.submit(req)

    def generate(self, prompt: List[int], max_new_tokens: int = 32,
                 **kw) -> List[int]:
        """Synchronous convenience: submit + drive to completion."""
        req = self.submit(prompt, max_new_tokens, **kw)
        self.run_until_idle()
        return req.result(timeout=0)

    def run_until_idle(self, max_iterations: int = 1_000_000) -> int:
        """Drive :meth:`step` until queue and batch are empty; returns
        iterations run."""
        it = 0
        while not self.scheduler.idle() and it < max_iterations:
            self.step()
            it += 1
        return it

    # -- the continuous-batching iteration --------------------------------
    def step(self, now: Optional[int] = None, admit: bool = True) -> bool:
        """ONE iteration: admit into free slots (prefill each new
        sequence and sample its first token from the prefill logits —
        TTFT pays no decode-batching delay), then one batched decode
        over every active slot — sequences finish and admit mid-stream,
        no batch boundary.  ``now`` gates admission on logical arrival
        stamps (trace replay); None admits anything queued.
        ``admit=False`` skips admission entirely — that is the whole
        difference between this engine and a static batcher, and
        exactly how ``bench.py --mode serving`` builds its baseline
        (admit only at batch boundaries).  Returns whether any work
        ran.

        Multi-host: rank 0 (the only rank with a scheduler) broadcasts
        the admission plan, then post-prefill state, then the sampled
        tokens, so :meth:`follow` on worker ranks mirrors the cache and
        runs the identical executables in the same order."""
        mp = self._multiprocess()
        admitted = self.scheduler.admit(now) if admit else []
        if mp:
            self._bcast({"stop": False,
                         "admit": [(slot, list(req.prompt))
                                   for slot, req in admitted]})
        for slot, req in admitted:
            self._prefill_and_sample(slot, req)
        # Clean abort of disconnected clients' slots (hvd-chaos): the
        # eviction happens HERE, at the iteration boundary on the
        # serve-loop thread — the only thread that may free KV slots —
        # and rides the step broadcast's evict list so follower cache
        # mirrors free the same pages (a handler-thread free would
        # silently desync the fleet).
        cancelled = [s for s in self.scheduler.evict_cancelled()
                     if self.cache.length(s) >= 0]
        for slot in cancelled:
            self.cache.free_slot(slot)
        active = self.scheduler.active()
        # Page allocation (the host-side step that can raise — out of
        # pages) runs BEFORE the decode announcement: once a follower
        # reads a non-empty "decode" list it enters the compiled
        # program's collectives and cannot be reached by an abort
        # marker, so everything fallible on the host must happen first.
        for slot, _ in active:
            self.cache.ensure(slot, self.cache.length(slot))
        if mp:
            # Post-prefill sync: first sampled tokens + which slots
            # survived into the decode batch (a max_new_tokens=1
            # admission can finish at prefill).
            self._bcast({
                "last": {s: int(self._last_token[s])
                         for s, _ in active},
                "decode": [s for s, _ in active],
                "evict": cancelled + [s for s, _ in admitted
                                      if self.cache.length(s) < 0]})
        if active:
            self._decode_iteration(active)
        return bool(admitted or active)

    def _sample(self, req: Request, logits: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        z = (logits - logits.max()) / req.temperature
        p = np.exp(z)
        p /= p.sum()
        # Keyed on request-local state only (seed + decode position),
        # never on scheduler history (rid/slot), so a sampled rollout
        # reproduces across engines, relaunches, and batch mixes.
        rng = np.random.default_rng(
            (req.seed, len(req.prefix) + len(req.generated)))
        return int(rng.choice(len(p), p=p))

    def _feed(self, slot: int, req: Request, token: int) -> None:
        if not req.generated:
            req.t_first_token = time.perf_counter()
            _M_TTFT.observe(req.t_first_token - req.t_submit)
        _M_TOKENS.inc()
        # expect=req: a concurrent drain may have evicted the slot
        # mid-iteration — the token is then discarded (the exported
        # continuation reproduces it) instead of poisoning the step.
        reason = self.scheduler.feed(slot, token, expect=req)
        if reason is not None:
            req.t_done = time.perf_counter()
            self.cache.free_slot(slot)  # idempotent vs the drain
            if _trace.enabled():
                # hvd-trace serving span: the whole request lifetime
                # (submit -> completion), reconstructed from the wall
                # stamps the engine already keeps — serving load on the
                # shared mesh is visible next to training cycles in
                # the fleet trace.
                now = time.monotonic()
                _trace.span(
                    "serving.request", "serving",
                    now - (req.t_done - req.t_submit), now,
                    args={"rid": req.rid,
                          "tokens": len(req.generated),
                          "reason": reason})
        else:
            self._last_token[slot] = token

    def _prefill(self, slot: int, req: Request,
                 prompt: Optional[List[int]] = None) -> np.ndarray:
        prompt = list(req.prompt) if prompt is None else prompt
        n = len(prompt)
        self.cache.begin_slot(slot, n)
        bucket = self._bucket_for(n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prompt
        compiled = self._prefill_exec(bucket)
        with _oom.guard(f"serving/prefill/{bucket}"):
            last, kp, vp = compiled(
                self.params, self.cache.k_pages, self.cache.v_pages,
                self._rep(self.cache.table_row(slot)),
                self._rep(np.asarray([n], np.int32)),
                self._rep(tokens))
        self.cache.replace_pages(kp, vp)
        _M_PREFILLS.inc()
        return np.asarray(last)

    def _decode_iteration(self, active) -> np.ndarray:
        """One batched decode over ``active``; the caller (step) has
        already run ``cache.ensure`` for every slot."""
        t0 = time.perf_counter()
        table, lengths = self.cache.device_tables()
        tokens = np.zeros((self.max_slots,), np.int32)
        for slot, _ in active:
            tokens[slot] = self._last_token[slot]
        compiled = self._decode_exec()
        with _oom.guard("serving/decode"):
            logits, kp, vp = compiled(
                self.params, self.cache.k_pages, self.cache.v_pages,
                table, lengths, self._rep(tokens))
        self.cache.replace_pages(kp, vp)
        logits_np = np.asarray(logits)
        fed = {}
        evicted = []
        for slot, req in active:
            self.cache.advance(slot)  # the input token's KV landed
            token = self._sample(req, logits_np[slot])
            fed[slot] = token
            self._feed(slot, req, token)
            if self.cache.length(slot) < 0:
                evicted.append(slot)
        if self._multiprocess():
            self._bcast({"tokens": fed, "evict": evicted})
        _M_DECODES.inc()
        _M_TOKEN_LAT.observe(time.perf_counter() - t0)
        return logits_np

    def _prefill_and_sample(self, slot: int, req: Request) -> None:
        last = self._prefill(slot, req)
        self._feed(slot, req, self._sample(req, last))

    # -- multi-host mirroring ---------------------------------------------
    def _multiprocess(self) -> bool:
        try:
            from ..core import state as _state

            return (_state.is_initialized()
                    and _state.global_state().multiprocess
                    and _state.global_state().process_count > 1)
        except Exception:  # noqa: BLE001 — serving works without init
            return False

    def _bcast(self, obj):
        from ..ops.objects import broadcast_object

        return broadcast_object(obj, root_rank=0, name="hvd-serve-plan")

    def follow(self) -> bool:
        """Worker-rank iteration mirroring ONE rank-0 :meth:`step`:
        receive the admission plan (prefill those slots), the
        post-prefill sync (first tokens + decode batch + early
        evictions), run the identical decode executable when rank 0
        does, then apply its sampled tokens/evictions to the local
        cache mirror.  Returns False when rank 0 announced shutdown
        (:meth:`stop_followers`).  Worker ranks have no scheduler —
        rank 0 decides, the data plane stays SPMD.

        Any of the three receptions may instead carry rank 0's
        ``abort`` marker (:meth:`abort_all` after a poisoned step died
        mid-iteration): the worker mirrors the recovery by freeing
        every cache slot and returning, keeping the fleet's caches
        identical for the next iteration."""
        plan = self._bcast(None)
        if plan.get("stop"):
            return False
        if plan.get("abort"):
            self._free_all_slots()
            return True
        for slot, prompt in plan.get("admit", ()):
            self._prefill(slot, Request(prompt=list(prompt)),
                          prompt=list(prompt))
        sync = self._bcast(None)
        if sync.get("abort"):
            self._free_all_slots()
            return True
        for slot, token in sync.get("last", {}).items():
            self._last_token[int(slot)] = int(token)
        for slot in sync.get("evict", ()):
            if self.cache.length(int(slot)) >= 0:
                self.cache.free_slot(int(slot))
        decode = [int(s) for s in sync.get("decode", ())]
        if decode:
            for slot in decode:
                self.cache.ensure(slot, self.cache.length(slot))
            table, lengths = self.cache.device_tables()
            tokens = np.zeros((self.max_slots,), np.int32)
            for slot in decode:
                tokens[slot] = self._last_token[slot]
            compiled = self._decode_exec()
            with _oom.guard("serving/decode"):
                _, kp, vp = compiled(
                    self.params, self.cache.k_pages, self.cache.v_pages,
                    table, lengths, self._rep(tokens))
            self.cache.replace_pages(kp, vp)
            fed = self._bcast(None)
            if fed.get("abort"):
                # Rank 0's _decode_iteration died before broadcasting
                # the sampled tokens; it freed everything — mirror
                # that (and skip the advance: rank 0 never advanced).
                self._free_all_slots()
                return True
            for slot in decode:
                self.cache.advance(slot)
            for slot, token in fed.get("tokens", {}).items():
                self._last_token[int(slot)] = int(token)
            for slot in fed.get("evict", ()):
                if self.cache.length(int(slot)) >= 0:
                    self.cache.free_slot(int(slot))
        return True

    def stop_followers(self) -> None:
        if self._multiprocess():
            self._bcast({"stop": True})

    # -- elastic drain / resume -------------------------------------------
    @staticmethod
    def _export_request(req: Request) -> dict:
        """A request as a resubmittable continuation: prompt extended
        by what it generated so far (the bitwise prefill≡decode
        contract makes the continuation reproduce the uninterrupted
        greedy rollout).  A queued request has ``generated == []``, so
        this reduces to its original submission.  ``generated`` is read
        ONCE: export_requests() can run concurrently with the serve
        loop's feed(), and deriving the three fields from different
        generation states would commit an internally inconsistent
        continuation."""
        gen = list(req.generated)
        return {
            "prompt": list(req.prompt) + gen,
            "generated_prefix": list(req.prefix) + gen,
            "max_new_tokens": req.max_new_tokens - len(gen),
            "eos_id": req.eos_id, "temperature": req.temperature,
            "seed": req.seed,
        }

    def export_requests(self) -> List[dict]:
        """Queued + in-flight work as resubmittable dicts (one atomic
        scheduler snapshot — a request admitted concurrently cannot fall
        between the active and pending halves).  Does not stop the
        engine — pair with :meth:`drain` for the elastic resize path
        (:class:`horovod_tpu.elastic.ServingState`).
        """
        active, pending = self.scheduler.snapshot()
        return [self._export_request(req)
                for req in [r for _, r in active] + pending]

    def drain(self) -> List[dict]:
        """Serving-fleet resize, step 1: capture every queued and
        in-flight request as a continuation, then evict everything and
        stop admission.  The export is built from exactly the requests
        the scheduler's drain removed (one lock hold), so a submission
        racing the drain is either exported or rejected — never lost.
        The returned list (same format as :meth:`export_requests`) is
        what the elastic commit persists; a relaunched engine resubmits
        it via :meth:`import_requests`."""
        with self._drain_lock:
            self._drained = True
            drained, pending = self._drain_and_finish(
                FinishReason.DRAINED)
        return [self._export_request(req) for req in drained + pending]

    def _free_all_slots(self) -> None:
        for slot in range(self.max_slots):
            if self.cache.length(slot) >= 0:
                self.cache.free_slot(slot)

    def _drain_and_finish(self, reason: str):
        """The shared eviction sequence (caller holds ``_drain_lock``):
        scheduler drain with ``reason``, free every KV slot, and finish
        the still-queued requests' Python objects with the same reason
        — their blocked /generate handlers fail fast instead of hanging
        to the client timeout (the relaunch path resubmits NEW Request
        objects from the export, so finishing these loses nothing).
        Returns ``(drained, pending)``."""
        drained, pending = self.scheduler.drain(reason)
        self._free_all_slots()
        for req in pending:
            req.finish_reason = reason
            req.done.set()
        return drained, pending

    def abort_all(self) -> List[Request]:
        """Error recovery (the serve loop's poisoned-step path):
        atomically evict and FAIL every queued and in-flight request —
        ``finish_reason`` is ``"error"`` before ``done`` is set, so a
        blocked ``/generate`` handler can never observe a stale reason —
        free the KV slots, and re-open admission.  Unlike :meth:`drain`
        nothing is exported: callers answer the failed requests
        immediately instead of requeueing them.  Returns the failed
        requests (raced submissions included).

        Admission re-opens ONLY when no elastic :meth:`drain` is
        pending (checked under the same lock the drain holds, so the
        recovery cannot interleave with a concurrent drain_commit and
        resume after it): if the loop's recovery fires after a drain
        committed, resuming here would admit requests the commit never
        captured — silently lost at relaunch.

        Multi-host: broadcasts an abort marker so blocked
        :meth:`follow` ranks (waiting for the sync/tokens of the step
        that just died) free their cache mirrors too — without it the
        fleet's caches diverge and every later decode breaks the
        bitwise contract."""
        # Broadcast OUTSIDE the lock: a wedged control plane blocks a
        # collective forever (no timeout), and holding _drain_lock
        # across it would deadlock the elastic thread's drain/import
        # too.  Under multiprocess only the serve-loop thread may call
        # abort_all (the class threading contract), so the marker
        # cannot interleave with a concurrent step()'s broadcasts — a
        # follower consuming an abort where it expected a plan/sync
        # would silently desynchronize the fleet's caches.
        if self._multiprocess():
            try:
                self._bcast({"abort": True})
            except Exception:  # noqa: BLE001 — a dead control
                pass  # plane must not stop the LOCAL recovery
        with self._drain_lock:
            drained, pending = self._drain_and_finish(
                FinishReason.ERROR)
            if not self._drained:
                self.scheduler.resume()
        return drained + pending

    def abort_request(self, req: Request,
                      reason: str = FinishReason.CLIENT_DISCONNECT
                      ) -> str:
        """Clean abort of ONE request (the /generate client vanished,
        hvd-chaos hardening): a queued request finishes immediately; an
        active one is marked and evicted by the serve loop at its next
        iteration boundary — the existing eviction path, so the KV slot
        is released identically on every rank.  Returns the scheduler's
        "queued"/"active"/"gone" disposition."""
        disposition = self.scheduler.cancel(req, reason)
        _flight.record("serve_abort_request", req.rid, reason,
                       disposition)
        return disposition

    def import_requests(self, exported: List[dict]) -> List[Request]:
        """Resubmit a drained export (relaunch path).  Continuation
        requests keep their already-generated prefix, so callers see
        uninterrupted results.  The whole resume+resubmit runs under
        the drain lock: a concurrent abort_all/drain landing mid-loop
        would otherwise make ``submit`` raise and silently drop the
        not-yet-resubmitted tail of the committed export.  A
        continuation this engine cannot admit (its prompt outgrew a
        SHRUNK capacity across the resize) is skipped with a flight-
        recorder event — one oversized request must not abort the loop
        and drop the rest of the committed export with it."""
        with self._drain_lock:
            if self._drained:
                self.scheduler.resume()
                self._drained = False
            out = []
            for d in exported:
                if d.get("max_new_tokens", 0) <= 0:
                    continue
                try:
                    out.append(self.submit(
                        d["prompt"], max_new_tokens=d["max_new_tokens"],
                        eos_id=d.get("eos_id"),
                        temperature=d.get("temperature", 0.0),
                        seed=d.get("seed", 0),
                        prefix=d.get("generated_prefix", [])))
                except ValueError as e:
                    _telemetry.exception_event(
                        "serve-import",
                        f"dropping unresumable continuation "
                        f"({len(d['prompt'])} prompt tokens vs "
                        f"capacity {self.capacity}): {e}")
        return out
