"""hvd-serve: continuous-batching inference over the training mesh.

The serving runtime the north star's "heavy traffic from millions of
users" scenario needs (ROADMAP open item 4; docs/inference.md).  Four
pieces, each its own module:

* :mod:`~horovod_tpu.serving.scheduler` — request queue + iteration-
  level continuous-batching scheduler: a new request joins the decode
  batch the moment a slot frees, a finished sequence evicts
  immediately; no batch-boundary barrier.  Pure Python — unit-testable
  without XLA.
* :mod:`~horovod_tpu.serving.kv_cache` — paged KV cache: fixed-size
  pages recycled through a free list, head axis sharded with the
  ``parallel/tensor.py`` tensor-parallel layout so serving reuses the
  training partition.
* :mod:`~horovod_tpu.serving.engine` — prefill and decode compiled as
  donated AOT executables (megakernel-style: gather → forward →
  scatter in ONE program), recorded in the PR-5 persistent-cache
  manifest so :meth:`InferenceEngine.warm_start` brings a relaunched
  serving fleet back to full token rate before the first request.
* :mod:`~horovod_tpu.serving.server` — the HTTP front door: ``/generate``
  registered on the telemetry exporter's route registry, ``/healthz``
  NOT_READY until warm start completes (the load-balancer contract).

Elastic integration rides :class:`horovod_tpu.elastic.ServingState`:
drain in-flight sequences, commit the queue, relaunch, resume from the
warm cache.
"""

from __future__ import annotations

from .scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    FinishReason,
    Request,
)
from .kv_cache import PagedKVCache  # noqa: F401
from .engine import InferenceEngine  # noqa: F401
from .server import LMServer  # noqa: F401
