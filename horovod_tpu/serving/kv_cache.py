"""Paged KV cache: fixed-size pages, free-list recycling, TP sharding.

Storage is two device arrays per engine —
``k_pages``/``v_pages: [n_layers, n_pages, page_size, n_heads,
head_dim]`` — plus a HOST page table (``[max_slots, pages_per_slot]``
int32, numpy) mapping each decode slot's logical positions onto
physical pages.  Pages are allocated on demand as a sequence grows and
recycled through a free list the moment the scheduler evicts it, so
slot reuse never copies or zeroes KV data: the next sequence simply
maps fresh pages and the old values become unreachable (masked by
:func:`..models.transformer.cache_attention` long before they are
overwritten).

Page 0 is the reserved *trash* page: unmapped table entries point at
it, so the executables' scatters of padded/inactive positions land
somewhere harmless instead of needing per-position predication.
Nothing ever reads trash through an unmasked attention row (entry
``j`` is only unmasked for ``j <= q_pos < length``, and every position
``< length`` is mapped by construction); written values are finite, so
masked rows contribute exact zeros regardless of trash content — the
bitwise contract does not depend on it.

Tensor parallelism: the head axis is sharded over the mesh's ``model``
axis with a ``NamedSharding`` — the SAME partition
``parallel/tensor.py`` gives the training attention (heads
column-parallel), so a model served on its training mesh reuses the
training layout and GSPMD partitions prefill/decode along heads with
no code change here.

**Shared-prefix page cache** (hvd-spec, docs/inference.md): completed
prompt-prefix pages are hashed — a page-aligned CHAIN hash over the
token ids, keyed by the engine's model/config fingerprint, so the hash
of page ``j`` commits to every token before it — into a refcounted
read-only index.  A new request whose prompt extends a cached prefix
maps those pages into its page table copy-free (``begin_slot``'s
``prefix_pages``) and prefills only the suffix; repeated system
prompts, few-shot headers and RAG contexts become page-table lookups.
Shared pages are never written (decode/verify scatters target
positions ``>= length > shared coverage`` by construction) and never
freed while referenced: ``free_slot`` decrements refcounts, and a page
whose count reaches zero parks in an LRU of *reclaimable* cached pages
— still index-hittable, recycled only when the free list runs dry.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..analysis import races as _races
from ..core.topology import MODEL_AXIS
from ..memory import ledger as _mem
from ..routing.affinity import chain_hashes as _chain_hash_scheme

# hvd-mem satellite: free-page headroom next to serving.batch_occupancy
# — the ROADMAP-item-2 router tier dispatches on how much KV room a
# replica has LEFT, not just how deep its queue is.  Push-fed (set
# under the cache lock at every page-management transition), so it is
# current in /healthz, the FRAME_METRICS fleet pull and every flight
# dump's tail.
_M_KV_FREE = _telemetry.gauge(
    "serving.kv_free_pages",
    "KV pages available for allocation: free list + reclaimable "
    "prefix-cache pages (admission headroom)")
_M_KV_TOTAL = _telemetry.gauge(
    "serving.kv_total_pages",
    "allocatable KV pages (capacity; excludes the trash page)")
_M_KV_RECLAIM = _telemetry.gauge(
    "serving.kv_reclaimable_pages",
    "unreferenced prefix-cache pages (reclaimed LRU-first when the "
    "free list runs dry; counted inside kv_free_pages)")
_M_PREFIX_CACHED = _telemetry.gauge(
    "serving.prefix_cached_pages",
    "pages currently held by the shared-prefix index (referenced + "
    "reclaimable)")
_M_PREFIX_HITS = _telemetry.counter(
    "serving.prefix_hits",
    "admissions that mapped at least one cached prefix page copy-free")
_M_PREFIX_PAGES = _telemetry.counter(
    "serving.prefix_pages_shared",
    "cached prefix pages mapped into admitted slots (copy-free)")
_M_PREFIX_BYTES = _telemetry.counter(
    "serving.prefix_bytes_saved",
    "KV bytes NOT recomputed thanks to prefix-cache hits (global "
    "logical bytes of the shared pages)")
_M_PREFIX_HITS_DRAFT = _telemetry.counter(
    "serving.prefix_hits_draft",
    "admissions whose speculative DRAFT prefill mapped cached prefix "
    "pages copy-free (the target's hits stay in serving.prefix_hits)")


@_races.race_checked
class PagedKVCache:
    """The paged store for one :class:`~horovod_tpu.serving.engine.
    InferenceEngine`.  The host-side bookkeeping (page table, lengths,
    free list) is guarded by an internal lock: the serve loop mutates
    it every iteration, and the engine's drain family
    (``_free_all_slots``) may run concurrently from the elastic
    thread — ``free_slot`` is idempotent and ``advance`` is a no-op on
    a freed slot, so an eviction racing the loop can never double-free
    a page or resurrect a slot.  The DEVICE page arrays are still
    single-writer (only the serve loop dispatches executables)."""

    def __init__(self, n_layers: int, n_heads: int, head_dim: int,
                 max_slots: int, pages_per_slot: int, page_size: int,
                 dtype=jnp.float32, mesh=None,
                 model_axis: str = MODEL_AXIS,
                 prefix_cache: bool = False, prefix_pages: int = 0,
                 fingerprint: str = "",
                 ledger_category: str = "serving.kv_pages") -> None:
        if pages_per_slot < 1 or page_size < 1:
            raise ValueError("pages_per_slot and page_size must be >= 1")
        if prefix_pages < 0:
            raise ValueError(f"prefix_pages must be >= 0, got "
                             f"{prefix_pages}")
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.head_dim = head_dim
        self.max_slots = max_slots
        self.pages_per_slot = pages_per_slot
        self.page_size = page_size
        self.capacity = pages_per_slot * page_size  # per sequence
        # +1: trash page; +prefix_pages: dedicated headroom for the
        # shared-prefix index (the --prefix-pages planner what-if) so a
        # busy fleet is not forced to thrash cached prefixes against
        # live slots.
        self.prefix_enabled = bool(prefix_cache)
        self.prefix_pages = int(prefix_pages) if prefix_cache else 0
        self.n_pages = (1 + max_slots * pages_per_slot
                        + self.prefix_pages)
        self.dtype = dtype
        self.mesh = mesh
        self.model_axis = model_axis
        self._fingerprint = fingerprint.encode()
        self._ledger_category = ledger_category

        shape = (n_layers, self.n_pages, page_size, n_heads, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        sh = self.page_sharding()
        if sh is not None:
            k = jax.device_put(k, sh)
            v = jax.device_put(v, sh)
        self.k_pages = k
        self.v_pages = v

        self._lock = _lockorder.make_lock("serving.PagedKVCache._lock")
        self._free: List[int] = list(range(1, self.n_pages))
        # guarded_by: _lock
        self._table = np.zeros((max_slots, pages_per_slot), np.int32)
        self._lengths = np.full((max_slots,), -1, np.int32)
        # -- shared-prefix index (all guarded_by: _lock) ------------------
        # chain hash -> physical page holding that page-aligned prefix's
        # KV; _page_hash is the reverse map (page -> hash), _page_tokens
        # keeps the token ids per entry for the elastic export,
        # _refcount counts slots currently mapping a shared page, and
        # _lru holds unreferenced cached pages in reclaim order.
        self._index: Dict[bytes, int] = {}
        self._page_hash: Dict[int, bytes] = {}
        self._page_tokens: Dict[bytes, List[int]] = {}
        self._refcount: Dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # Live index-size target (hvd-tune's prefix_pages retune knob):
        # None = unbounded.  The device-side reserve is fixed at
        # construction; this caps how many pages the INDEX may hold —
        # shrink trims the reclaimable LRU, grow just lifts the cap
        # (pages come from the shared pool as prompts publish).
        self._prefix_target: Optional[int] = None  # guarded_by: _lock
        if ledger_category == "serving.kv_pages":
            _M_KV_TOTAL.set(self.total_pages)
        self._set_page_gauges_locked()
        # hvd-mem: the page arrays are THE serving framework buffer —
        # account the bytes RESIDENT on this process (addressable
        # shards: a tp-sharded store holds global/tp per rank) for the
        # store's lifetime (keyed, released by gc: replace_pages swaps
        # same-shape donated outputs, so the figure is constant while
        # the engine lives).  Dedicated prefix pages are partitioned
        # into their own ledger category (the SAME per-page byte model
        # memory/planner.prefix_pages_bytes predicts with), so
        # plan-vs-ledger stays exact with a prefix reserve resident.
        self._ledger_key = id(self)
        resident = _mem.resident_nbytes(k) + _mem.resident_nbytes(v)
        # n_pages divides both factors of the array shape, so the
        # partition is exact integer arithmetic.
        self._page_resident_bytes = resident // self.n_pages
        prefix_resident = self._page_resident_bytes * self.prefix_pages
        if _mem.enabled():
            _mem.ledger.alloc(self._ledger_category,
                              resident - prefix_resident,
                              key=self._ledger_key)
            if prefix_resident:
                _mem.ledger.alloc("serving.prefix_pages",
                                  prefix_resident, key=self._ledger_key)
        weakref.finalize(self, _mem.ledger.free, self._ledger_category,
                         key=self._ledger_key)
        if prefix_resident:
            weakref.finalize(self, _mem.ledger.free,
                             "serving.prefix_pages",
                             key=self._ledger_key)

    # -- sharding ----------------------------------------------------------
    def page_sharding(self) -> Optional[NamedSharding]:
        """NamedSharding for the page arrays (heads over the model
        axis), or None when the mesh has no model axis to shard over —
        the training partition, reused for serving."""
        if self.mesh is None or self.model_axis not in getattr(
                self.mesh, "axis_names", ()):
            return None
        tp = self.mesh.shape[self.model_axis]
        if tp <= 1:
            return None
        if self.n_heads % tp != 0:
            raise ValueError(
                f"tensor-parallel degree {tp} must divide n_heads "
                f"({self.n_heads}) to shard the KV head axis")
        return NamedSharding(self.mesh,
                             P(None, None, None, self.model_axis, None))

    # -- gauges ------------------------------------------------------------
    def _set_page_gauges_locked(self) -> None:
        # Only the primary (target) store owns the process-global
        # serving.* page gauges; a draft store (its own ledger
        # category) must not clobber them.
        if self._ledger_category != "serving.kv_pages":
            return
        _M_KV_FREE.set(len(self._free) + len(self._lru))
        _M_KV_RECLAIM.set(len(self._lru))
        _M_PREFIX_CACHED.set(len(self._page_hash))

    # -- page management ---------------------------------------------------
    def begin_slot(self, slot: int, n_tokens: int,
                   prefix_pages: Sequence[int] = ()) -> None:
        """Map pages for a freshly admitted sequence's first
        ``n_tokens`` positions (the prompt) and set its length.
        ``prefix_pages`` (from :meth:`lookup_prefix`) are mapped
        COPY-FREE as the leading read-only pages: each gets a
        reference (it leaves the reclaimable LRU while mapped) and
        only the remainder allocates fresh pages — the suffix is all
        the caller prefills."""
        with self._lock:
            if self._lengths[slot] >= 0:
                raise ValueError(f"slot {slot} already active")
            self._table[slot] = 0
            for j, page in enumerate(prefix_pages):
                if self._page_hash.get(int(page)) is None:
                    raise ValueError(
                        f"page {page} is not a cached prefix page")
                self._table[slot, j] = int(page)
                self._ref_page_locked(int(page))
            self._lengths[slot] = 0
            self._ensure_locked(slot, n_tokens - 1)
            self._lengths[slot] = n_tokens
            if prefix_pages:
                # Split by store: the target's hits stay on the
                # historical serving.prefix_hits family; a DRAFT
                # store's hits (its own ledger category) count on the
                # draft counter so the hvd-spec satellite's win is
                # observable separately (hvd-route retunes on the sum).
                if self._ledger_category == "serving.kv_pages":
                    _M_PREFIX_HITS.inc()
                    _M_PREFIX_PAGES.inc(len(prefix_pages))
                    _M_PREFIX_BYTES.inc(
                        len(prefix_pages) * self.page_global_bytes)
                else:
                    _M_PREFIX_HITS_DRAFT.inc()
            self._set_page_gauges_locked()

    def ensure(self, slot: int, pos: int) -> None:
        """Map pages so position ``pos`` of ``slot`` is writable.
        A no-op on a freed slot: the serve loop reads ``length`` and
        calls this as two separate lock holds, so a drain landing
        between them must not map pages into the freed slot — its own
        idempotence check would then never recycle them (a permanent
        page leak), and ``begin_slot`` zeroes the row on reuse."""
        with self._lock:
            if self._lengths[slot] < 0:
                return
            self._ensure_locked(slot, pos)

    def _alloc_page_locked(self) -> int:
        """One allocatable page: free list first, then the LRU of
        unreferenced cached prefix pages (refcount-aware eviction — a
        REFERENCED shared page is never a candidate by construction:
        it is absent from both pools)."""
        if self._free:
            return self._free.pop(0)
        if self._lru:
            page, _ = self._lru.popitem(last=False)
            self._drop_index_locked(page)
            return page
        raise RuntimeError(
            "paged KV cache out of pages (free list and prefix-cache "
            "LRU both empty) — sizing guarantees this cannot happen "
            "while every slot stays within pages_per_slot")

    def _drop_index_locked(self, page: int) -> None:
        key = self._page_hash.pop(page, None)
        if key is not None:
            self._index.pop(key, None)
            self._page_tokens.pop(key, None)
        self._refcount.pop(page, None)

    def _ref_page_locked(self, page: int) -> None:
        self._refcount[page] = self._refcount.get(page, 0) + 1
        self._lru.pop(page, None)

    def _unref_page_locked(self, page: int) -> None:
        rc = self._refcount.get(page, 0) - 1
        if rc <= 0:
            self._refcount.pop(page, None)
            self._lru[page] = None
            self._lru.move_to_end(page)
        else:
            self._refcount[page] = rc

    def _ensure_locked(self, slot: int, pos: int) -> None:
        if pos >= self.capacity:
            raise ValueError(
                f"position {pos} exceeds per-slot capacity "
                f"{self.capacity}")
        for p in range(pos // self.page_size + 1):
            if self._table[slot, p] == 0:
                self._table[slot, p] = self._alloc_page_locked()
        self._set_page_gauges_locked()

    def advance(self, slot: int) -> int:
        """One decoded token was written at the current length; map the
        page first via :meth:`ensure`.  Returns the new length, or -1
        without advancing when the slot was freed by a concurrent
        eviction (a drain racing the loop must not resurrect it)."""
        with self._lock:
            if self._lengths[slot] < 0:
                return -1
            self._lengths[slot] += 1
            return int(self._lengths[slot])

    def free_slot(self, slot: int) -> None:
        """Evict: recycle the slot's pages.  Refcount-aware: a page the
        prefix index holds is UNREFERENCED (parked in the reclaimable
        LRU when its count reaches zero — never put on the free list
        while cached), every other page goes back on the free list.
        Idempotent — a second free of the same slot (the serve loop
        and a concurrent drain both evicting) is a no-op, never a
        double-insert into the free list."""
        with self._lock:
            if self._lengths[slot] < 0:
                return
            for p in range(self.pages_per_slot):
                page = int(self._table[slot, p])
                if page != 0:
                    if page in self._page_hash:
                        self._unref_page_locked(page)
                    else:
                        self._free.append(page)
            self._table[slot] = 0
            self._lengths[slot] = -1
            self._set_page_gauges_locked()

    # -- shared-prefix index -----------------------------------------------
    @property
    def page_global_bytes(self) -> int:
        """GLOBAL logical KV bytes of one page (K + V, all layers) —
        the byte model memory/planner.prefix_pages_bytes shares."""
        return (2 * self.n_layers * self.page_size * self.n_heads
                * self.head_dim * jnp.dtype(self.dtype).itemsize)

    def _chain_hashes(self, tokens: Sequence[int],
                      n_pages: int) -> List[bytes]:
        """Chain hash per page boundary: ``h_j`` commits to the model
        fingerprint AND every token of pages ``0..j`` — a hit on page
        ``j`` implies the whole prefix matches, so the index needs no
        token comparison on lookup.  Delegates to the jax-free
        ``routing.affinity`` scheme: the router tier derives these SAME
        keys from /healthz exports for prefix-affinity dispatch, and a
        silent divergence would zero the fleet's affinity hit rate
        (tests/test_routing.py gates byte-identity)."""
        return _chain_hash_scheme(self._fingerprint, tokens,
                                  self.page_size, n_pages)

    def lookup_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Physical pages of the longest cached page-aligned STRICT
        prefix of ``tokens`` (at least one suffix token always remains
        to prefill — the admission needs its logits to sample from).
        Pure: no refcounts move until :meth:`begin_slot` maps the
        pages, so the admission-headroom gate can call this freely."""
        if not self.prefix_enabled or not tokens:
            return []
        max_pages = min((len(tokens) - 1) // self.page_size,
                        self.pages_per_slot)
        if max_pages <= 0:
            return []
        hashes = self._chain_hashes(tokens, max_pages)
        pages: List[int] = []
        with self._lock:
            for key in hashes:
                page = self._index.get(key)
                if page is None:
                    break
                pages.append(page)
        return pages

    def admission_cost(self, tokens: Sequence[int]) -> int:
        """How much of the :meth:`free_pages` budget admitting this
        prompt consumes, EXACTLY: fresh pages for the unshared tail,
        plus one unit per shared prefix page currently parked in the
        reclaimable LRU (mapping it moves it to referenced — out of
        the pool — while a page other slots already reference costs
        nothing).  The scheduler's page-budget gate prices admissions
        with this; under the default sizing it is a safety net (a
        free slot always implies enough headroom), but the arithmetic
        stays honest for overcommitted configs."""
        if not tokens:
            return 0
        total = -(-len(tokens) // self.page_size)
        if not self.prefix_enabled:
            return total
        max_pages = min((len(tokens) - 1) // self.page_size,
                        self.pages_per_slot)
        hashes = self._chain_hashes(tokens, max_pages) if max_pages \
            else []
        with self._lock:
            shared = 0
            lru_hits = 0
            for key in hashes:
                page = self._index.get(key)
                if page is None:
                    break
                shared += 1
                if page in self._lru:
                    lru_hits += 1
        return total - shared + lru_hits

    def publish_prefix(self, slot: int, tokens: Sequence[int]) -> int:
        """Insert ``slot``'s fully-prefilled prompt pages into the
        index (pages entirely covered by ``tokens`` — pad garbage past
        the prompt never lands in a published page).  Pages already
        indexed (including the slot's own looked-up prefix) are
        skipped; newly published pages become shared with the slot
        holding the first reference.  Returns how many pages were newly
        published."""
        if not self.prefix_enabled:
            return 0
        ps = self.page_size
        n_full = min(len(tokens) // ps, self.pages_per_slot)
        if n_full <= 0:
            return 0
        hashes = self._chain_hashes(tokens, n_full)
        published = 0
        with self._lock:
            if self._lengths[slot] < 0:
                return 0
            for j in range(n_full):
                page = int(self._table[slot, j])
                if page == 0:
                    break
                key = hashes[j]
                if key in self._index or page in self._page_hash:
                    continue
                if (self._prefix_target is not None
                        and len(self._page_hash)
                        >= self._prefix_target):
                    break  # retuned cap reached — stop publishing
                self._index[key] = page
                self._page_hash[page] = key
                self._page_tokens[key] = [int(t)
                                          for t in tokens[:(j + 1) * ps]]
                self._refcount[page] = self._refcount.get(page, 0) + 1
                published += 1
            if published:
                self._set_page_gauges_locked()
        return published

    def alloc_ghost(self, n_pages: int) -> np.ndarray:
        """A ``[1, pages_per_slot]`` table row of ``n_pages`` freshly
        allocated pages bound to NO slot — the elastic seed path
        (:meth:`publish_ghost`) prefills cached prefixes through it on
        a relaunched engine without burning a decode slot."""
        if not 0 < n_pages <= self.pages_per_slot:
            raise ValueError(
                f"ghost prefix needs 1..{self.pages_per_slot} pages, "
                f"got {n_pages}")
        row = np.zeros((1, self.pages_per_slot), np.int32)
        with self._lock:
            for j in range(n_pages):
                row[0, j] = self._alloc_page_locked()
        return row

    def free_ghost(self, row: np.ndarray) -> None:
        """Return a ghost row's pages to the free list WITHOUT
        indexing them — the seed path's failure cleanup (a prefill
        that raised must not strand allocated pages outside every
        pool, or the sizing invariant silently erodes)."""
        with self._lock:
            for page in row[0]:
                if int(page) != 0:
                    self._free.append(int(page))
            self._set_page_gauges_locked()

    def publish_ghost(self, row: np.ndarray,
                      tokens: Sequence[int]) -> int:
        """Index the ghost row's prefilled pages with refcount zero
        (straight into the reclaimable LRU — hittable, evictable).
        Pages whose chain hash is already indexed go back on the free
        list.  Returns the newly indexed page count."""
        ps = self.page_size
        n_pages = sum(1 for p in row[0] if p != 0)
        n_full = min(len(tokens) // ps, n_pages)
        hashes = self._chain_hashes(tokens, n_full)
        published = 0
        with self._lock:
            for j in range(self.pages_per_slot):
                page = int(row[0, j])
                if page == 0:
                    continue
                key = hashes[j] if j < n_full else None
                if (key is not None and key not in self._index
                        and (self._prefix_target is None
                             or len(self._page_hash)
                             < self._prefix_target)):
                    self._index[key] = page
                    self._page_hash[page] = key
                    self._page_tokens[key] = [
                        int(t) for t in tokens[:(j + 1) * ps]]
                    self._lru[page] = None
                    self._lru.move_to_end(page)
                    published += 1
                else:
                    self._free.append(page)
            self._set_page_gauges_locked()
        return published

    def export_prefixes(self) -> List[List[int]]:
        """The cached prefixes as token-id lists, MAXIMAL chains only
        (an entry that is a strict prefix of another cached entry is
        implied by it — seeding the long chain republishes every page
        boundary).  The elastic drain exports this so a relaunched
        fleet rebuilds the shared pages instead of re-prefilling every
        cached prefix cold."""
        with self._lock:
            chains = sorted((list(t) for t in self._page_tokens.values()),
                            key=len, reverse=True)
        out: List[List[int]] = []
        for c in chains:
            if not any(len(k) > len(c) and k[:len(c)] == c for k in out):
                out.append(c)
        return out

    def export_prefix_hashes(self, limit: int = 512) -> List[str]:
        """The index keys as hex chain-hash digests, most recently
        published last, bounded to ``limit`` (newest kept) — the
        /healthz affinity export the router tier matches its
        router-side header hashes against.  Hex (not token chains):
        the router needs membership, not reconstruction, and the
        payload stays small on a hot index."""
        with self._lock:
            keys = list(self._index)
        return [k.hex() for k in keys[-int(limit):]]

    def set_prefix_target(self, n_pages: Optional[int]) -> int:
        """Retune the live index-size cap (hvd-tune's ``prefix_pages``
        knob).  Shrinking evicts reclaimable LRU pages back to the
        free list until the index fits (REFERENCED shared pages are
        untouchable — the cap converges as slots release them);
        growing just lifts the cap.  Returns the index size after the
        trim."""
        with self._lock:
            self._prefix_target = None if n_pages is None \
                else max(0, int(n_pages))
            if self._prefix_target is not None:
                while (len(self._page_hash) > self._prefix_target
                       and self._lru):
                    page, _ = self._lru.popitem(last=False)
                    self._drop_index_locked(page)
                    self._free.append(page)
                self._set_page_gauges_locked()
            return len(self._page_hash)

    def reclaimable_pages(self) -> int:
        """Unreferenced cached prefix pages — allocatable on demand, so
        they count toward admission headroom."""
        with self._lock:
            return len(self._lru)

    def prefix_stats(self) -> Dict[str, int]:
        """Index occupancy for /healthz and tests."""
        with self._lock:
            return {
                "cached_pages": len(self._page_hash),
                "referenced_pages": len(self._refcount),
                "reclaimable_pages": len(self._lru),
            }

    def length(self, slot: int) -> int:
        with self._lock:
            return int(self._lengths[slot])

    @property
    def total_pages(self) -> int:
        """Allocatable pages (the trash page is never handed out) —
        the ONE place the reserved-page invariant is priced in."""
        return self.n_pages - 1

    def free_pages(self) -> int:
        """Pages available for allocation: the free list PLUS the
        unreferenced cached prefix pages (reclaimed LRU-first on
        demand) — the honest admission-headroom figure /healthz and
        the scheduler's page-budget gate consume."""
        with self._lock:
            return len(self._free) + len(self._lru)

    def table_row(self, slot: int) -> np.ndarray:
        """One slot's page-table row, ``[1, pages_per_slot]`` (a copy —
        the live table may be mutated by a concurrent eviction)."""
        with self._lock:
            return self._table[slot:slot + 1].copy()

    # -- device views ------------------------------------------------------
    def device_tables(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(page_table, lengths) as device arrays for the executables
        (replicated under a mesh — they are tiny)."""
        with self._lock:
            table_np = self._table.copy()
            lengths_np = self._lengths.copy()
        table = jnp.asarray(table_np)
        lengths = jnp.asarray(lengths_np)
        if self.mesh is not None and self.page_sharding() is not None:
            rep = NamedSharding(self.mesh, P())
            table = jax.device_put(table, rep)
            lengths = jax.device_put(lengths, rep)
        return table, lengths

    def replace_pages(self, k_pages, v_pages) -> None:
        """Install the executables' donated-output page arrays (the old
        references were consumed by the dispatch)."""
        self.k_pages = k_pages
        self.v_pages = v_pages
