"""Paged KV cache: fixed-size pages, free-list recycling, TP sharding.

Storage is two device arrays per engine —
``k_pages``/``v_pages: [n_layers, n_pages, page_size, n_heads,
head_dim]`` — plus a HOST page table (``[max_slots, pages_per_slot]``
int32, numpy) mapping each decode slot's logical positions onto
physical pages.  Pages are allocated on demand as a sequence grows and
recycled through a free list the moment the scheduler evicts it, so
slot reuse never copies or zeroes KV data: the next sequence simply
maps fresh pages and the old values become unreachable (masked by
:func:`..models.transformer.cache_attention` long before they are
overwritten).

Page 0 is the reserved *trash* page: unmapped table entries point at
it, so the executables' scatters of padded/inactive positions land
somewhere harmless instead of needing per-position predication.
Nothing ever reads trash through an unmasked attention row (entry
``j`` is only unmasked for ``j <= q_pos < length``, and every position
``< length`` is mapped by construction); written values are finite, so
masked rows contribute exact zeros regardless of trash content — the
bitwise contract does not depend on it.

Tensor parallelism: the head axis is sharded over the mesh's ``model``
axis with a ``NamedSharding`` — the SAME partition
``parallel/tensor.py`` gives the training attention (heads
column-parallel), so a model served on its training mesh reuses the
training layout and GSPMD partitions prefill/decode along heads with
no code change here.
"""

from __future__ import annotations

import weakref
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .. import telemetry as _telemetry
from ..analysis import lockorder as _lockorder
from ..core.topology import MODEL_AXIS
from ..memory import ledger as _mem

# hvd-mem satellite: free-page headroom next to serving.batch_occupancy
# — the ROADMAP-item-2 router tier dispatches on how much KV room a
# replica has LEFT, not just how deep its queue is.  Push-fed (set
# under the cache lock at every page-management transition), so it is
# current in /healthz, the FRAME_METRICS fleet pull and every flight
# dump's tail.
_M_KV_FREE = _telemetry.gauge(
    "serving.kv_free_pages",
    "KV pages on the free list (admission headroom)")
_M_KV_TOTAL = _telemetry.gauge(
    "serving.kv_total_pages",
    "allocatable KV pages (capacity; excludes the trash page)")


class PagedKVCache:
    """The paged store for one :class:`~horovod_tpu.serving.engine.
    InferenceEngine`.  The host-side bookkeeping (page table, lengths,
    free list) is guarded by an internal lock: the serve loop mutates
    it every iteration, and the engine's drain family
    (``_free_all_slots``) may run concurrently from the elastic
    thread — ``free_slot`` is idempotent and ``advance`` is a no-op on
    a freed slot, so an eviction racing the loop can never double-free
    a page or resurrect a slot.  The DEVICE page arrays are still
    single-writer (only the serve loop dispatches executables)."""

    def __init__(self, n_layers: int, n_heads: int, head_dim: int,
                 max_slots: int, pages_per_slot: int, page_size: int,
                 dtype=jnp.float32, mesh=None,
                 model_axis: str = MODEL_AXIS) -> None:
        if pages_per_slot < 1 or page_size < 1:
            raise ValueError("pages_per_slot and page_size must be >= 1")
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.head_dim = head_dim
        self.max_slots = max_slots
        self.pages_per_slot = pages_per_slot
        self.page_size = page_size
        self.capacity = pages_per_slot * page_size  # per sequence
        self.n_pages = 1 + max_slots * pages_per_slot  # +1: trash page
        self.dtype = dtype
        self.mesh = mesh
        self.model_axis = model_axis

        shape = (n_layers, self.n_pages, page_size, n_heads, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        sh = self.page_sharding()
        if sh is not None:
            k = jax.device_put(k, sh)
            v = jax.device_put(v, sh)
        self.k_pages = k
        self.v_pages = v

        self._lock = _lockorder.make_lock("serving.PagedKVCache._lock")
        self._free: List[int] = list(range(1, self.n_pages))
        # guarded_by: _lock
        self._table = np.zeros((max_slots, pages_per_slot), np.int32)
        self._lengths = np.full((max_slots,), -1, np.int32)
        _M_KV_TOTAL.set(self.total_pages)
        _M_KV_FREE.set(len(self._free))
        # hvd-mem: the page arrays are THE serving framework buffer —
        # account the bytes RESIDENT on this process (addressable
        # shards: a tp-sharded store holds global/tp per rank) for the
        # store's lifetime (keyed, released by gc: replace_pages swaps
        # same-shape donated outputs, so the figure is constant while
        # the engine lives).
        self._ledger_key = id(self)
        if _mem.enabled():
            _mem.ledger.alloc("serving.kv_pages",
                              _mem.resident_nbytes(k)
                              + _mem.resident_nbytes(v),
                              key=self._ledger_key)
        weakref.finalize(self, _mem.ledger.free, "serving.kv_pages",
                         key=self._ledger_key)

    # -- sharding ----------------------------------------------------------
    def page_sharding(self) -> Optional[NamedSharding]:
        """NamedSharding for the page arrays (heads over the model
        axis), or None when the mesh has no model axis to shard over —
        the training partition, reused for serving."""
        if self.mesh is None or self.model_axis not in getattr(
                self.mesh, "axis_names", ()):
            return None
        tp = self.mesh.shape[self.model_axis]
        if tp <= 1:
            return None
        if self.n_heads % tp != 0:
            raise ValueError(
                f"tensor-parallel degree {tp} must divide n_heads "
                f"({self.n_heads}) to shard the KV head axis")
        return NamedSharding(self.mesh,
                             P(None, None, None, self.model_axis, None))

    # -- page management ---------------------------------------------------
    def begin_slot(self, slot: int, n_tokens: int) -> None:
        """Map pages for a freshly admitted sequence's first
        ``n_tokens`` positions (the prompt) and set its length."""
        with self._lock:
            if self._lengths[slot] >= 0:
                raise ValueError(f"slot {slot} already active")
            self._table[slot] = 0
            self._lengths[slot] = 0
            self._ensure_locked(slot, n_tokens - 1)
            self._lengths[slot] = n_tokens

    def ensure(self, slot: int, pos: int) -> None:
        """Map pages so position ``pos`` of ``slot`` is writable.
        A no-op on a freed slot: the serve loop reads ``length`` and
        calls this as two separate lock holds, so a drain landing
        between them must not map pages into the freed slot — its own
        idempotence check would then never recycle them (a permanent
        page leak), and ``begin_slot`` zeroes the row on reuse."""
        with self._lock:
            if self._lengths[slot] < 0:
                return
            self._ensure_locked(slot, pos)

    def _ensure_locked(self, slot: int, pos: int) -> None:
        if pos >= self.capacity:
            raise ValueError(
                f"position {pos} exceeds per-slot capacity "
                f"{self.capacity}")
        for p in range(pos // self.page_size + 1):
            if self._table[slot, p] == 0:
                if not self._free:
                    raise RuntimeError(
                        "paged KV cache out of pages (free list empty) "
                        "— sizing guarantees this cannot happen while "
                        "every slot stays within pages_per_slot")
                self._table[slot, p] = self._free.pop(0)
        _M_KV_FREE.set(len(self._free))

    def advance(self, slot: int) -> int:
        """One decoded token was written at the current length; map the
        page first via :meth:`ensure`.  Returns the new length, or -1
        without advancing when the slot was freed by a concurrent
        eviction (a drain racing the loop must not resurrect it)."""
        with self._lock:
            if self._lengths[slot] < 0:
                return -1
            self._lengths[slot] += 1
            return int(self._lengths[slot])

    def free_slot(self, slot: int) -> None:
        """Evict: recycle the slot's pages onto the free list.
        Idempotent — a second free of the same slot (the serve loop
        and a concurrent drain both evicting) is a no-op, never a
        double-insert into the free list."""
        with self._lock:
            if self._lengths[slot] < 0:
                return
            for p in range(self.pages_per_slot):
                page = int(self._table[slot, p])
                if page != 0:
                    self._free.append(page)
            self._table[slot] = 0
            self._lengths[slot] = -1
            _M_KV_FREE.set(len(self._free))

    def length(self, slot: int) -> int:
        with self._lock:
            return int(self._lengths[slot])

    @property
    def total_pages(self) -> int:
        """Allocatable pages (the trash page is never handed out) —
        the ONE place the reserved-page invariant is priced in."""
        return self.n_pages - 1

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def table_row(self, slot: int) -> np.ndarray:
        """One slot's page-table row, ``[1, pages_per_slot]`` (a copy —
        the live table may be mutated by a concurrent eviction)."""
        with self._lock:
            return self._table[slot:slot + 1].copy()

    # -- device views ------------------------------------------------------
    def device_tables(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(page_table, lengths) as device arrays for the executables
        (replicated under a mesh — they are tiny)."""
        with self._lock:
            table_np = self._table.copy()
            lengths_np = self._lengths.copy()
        table = jnp.asarray(table_np)
        lengths = jnp.asarray(lengths_np)
        if self.mesh is not None and self.page_sharding() is not None:
            rep = NamedSharding(self.mesh, P())
            table = jax.device_put(table, rep)
            lengths = jax.device_put(lengths, rep)
        return table, lengths

    def replace_pages(self, k_pages, v_pages) -> None:
        """Install the executables' donated-output page arrays (the old
        references were consumed by the dispatch)."""
        self.k_pages = k_pages
        self.v_pages = v_pages
