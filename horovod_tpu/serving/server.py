"""The hvd-serve HTTP front door: ``/generate`` on the telemetry
exporter's route registry.

One listener per process (docs/inference.md "The load-balancer
contract"): serving does NOT bind its own port — it registers routes on
the exporter's process-global :class:`~horovod_tpu.telemetry.exporter.
RouteRegistry`, so ``/generate``, ``/metrics`` and ``/healthz`` share
the server ``hvd.init()`` started on ``HVD_TPU_METRICS_PORT`` (or one
the :class:`LMServer` starts itself when none is running).  ``/healthz``
reports ``NOT_READY`` (HTTP 503) until the engine's ``warm_start``
completes, then ``ok`` with queue depth and batch occupancy — exactly
what a load balancer needs to keep traffic off a still-compiling
relaunch and to spread it by load afterwards.

``POST /generate`` accepts JSON with either ``tokens`` (a list of ids)
or ``text`` (encoded with the checkpoint's tokenizer — the byte
tokenizer maps UTF-8 bytes to ids, so any ``vocab_size >= 256`` model
serves raw text), plus optional ``max_tokens``, ``temperature``,
``seed``.  The handler blocks until the scheduler evicts the sequence
and returns the completion with TTFT and per-token latency for that
request.  Handlers run on the exporter's per-request threads; the
engine loop runs on the server's own thread — the scheduler lock is the
only shared state.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Optional, Tuple

from .. import telemetry as _telemetry
from ..analysis import threads as _athreads
from ..telemetry import exporter as _exporter
from .engine import InferenceEngine
from .scheduler import FinishReason

HEALTH_KEY = "serving"
GENERATE_PATH = "/generate"
# Fleet hooks for the hvd-route tier (docs/routing.md): a router (or
# operator) drains this replica for scale-down, resumes a drained
# export into it on boot, or reads its live prefix index to warm-seed
# a newcomer.  All three ride the elastic serving payload helpers, so
# an HTTP drain/resume is the same migration the in-process
# ServingState path performs.
DRAIN_PATH = "/drain"
RESUME_PATH = "/resume"
PREFIXES_PATH = "/prefixes"

# finish_reason -> (HTTP status, message) for requests that did not
# complete normally.  500: the serve loop's error recovery failed it.
# 503: an elastic drain evicted it mid-flight — the engine exported a
# continuation for the relaunched fleet, but THIS handler's request
# object never completes, so the client retries (consistent with the
# 503 a drained submit gets).
_FAILURE_STATUS = {
    FinishReason.ERROR: (
        500, "generation failed (engine error); partial tokens "
             "included"),
    FinishReason.DRAINED: (
        503, "generation interrupted by a serving-fleet drain; retry "
             "against the relaunched fleet"),
    # 499 (nginx convention): the client closed before the response;
    # nobody reads this body, but a late/raced completion must not
    # render as a 200.
    FinishReason.CLIENT_DISCONNECT: (
        499, "client disconnected mid-generation; slot released"),
}

_M_CLIENT_DISCONNECTS = _telemetry.counter(
    "serving.client_disconnects", "clients that vanished mid-generate "
    "(slot released via the abort path)")
_M_CP_LOSSES = _telemetry.counter(
    "serving.control_plane_losses", "serve loops degraded to 503+drain "
    "after a persistent control-plane loss")


def encode_text(text: str, vocab_size: int) -> list:
    """Byte tokenizer: UTF-8 bytes as token ids (needs vocab >= 256)."""
    if vocab_size < 256:
        raise ValueError(
            f"the byte tokenizer needs vocab_size >= 256, got "
            f"{vocab_size}; send token ids instead")
    return list(text.encode("utf-8"))


def decode_tokens(tokens: list, vocab_size: int) -> Optional[str]:
    """Inverse byte tokenizer (None when ids fall outside byte range)."""
    if vocab_size < 256 or any(not 0 <= t < 256 for t in tokens):
        return None
    return bytes(tokens).decode("utf-8", errors="replace")


class LMServer:
    """Engine loop thread + route registration.

    ``start()`` warm-starts the engine (readiness flips the shared
    ``/healthz``), spawns the continuous-batching loop, and registers
    ``/generate``.  When no exporter is live (``hvd.init()`` without
    ``HVD_TPU_METRICS_PORT``, or no init at all) and ``port`` is given,
    it starts one — same registry, so the endpoints are identical
    either way.

    ``routes`` opts out of the process-global route registry: pass a
    private :class:`~horovod_tpu.telemetry.exporter.RouteRegistry` and
    the server binds its own exporter to it — the way a multi-replica
    fleet (hvd-route: several replicas behind one Router in a single
    process, as in chaos' ``router_replica_death``) keeps each
    replica's ``/generate``+``/healthz`` from clobbering the others'.
    A private registry requires ``port`` (0 for ephemeral)."""

    def __init__(self, engine: InferenceEngine,
                 port: Optional[int] = None,
                 host: str = "127.0.0.1",
                 routes: Optional[_exporter.RouteRegistry] = None
                 ) -> None:
        self.engine = engine
        self._port = port
        self._host = host
        self._routes = routes
        if routes is not None and port is None:
            raise ValueError("a private route registry needs its own "
                             "exporter: pass port (0 for ephemeral)")
        self._own_exporter: Optional[_exporter.MetricsExporter] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        if self._own_exporter is not None:
            return self._own_exporter.port
        exp = self._shared_exporter()
        return exp.port if exp is not None else None

    def _shared_exporter(self):
        try:
            from ..core import state as _state

            return _state.global_state().metrics_exporter
        except Exception:  # noqa: BLE001 — serving works without init
            return None

    def start(self, warm_start_dir: Optional[str] = None) -> "LMServer":
        routes = (self._routes if self._routes is not None
                  else _exporter.routes())
        # Readiness first: a probing load balancer sees NOT_READY from
        # the instant the process answers, not a 404 window.
        routes.register_health(HEALTH_KEY, self.engine.health)
        self.engine.warm_start(warm_start_dir)
        # pass_client: the blocking /generate handler watches its
        # client connection and aborts the slot when it vanishes
        # (hvd-chaos hardening; exporter.ClientProbe).
        routes.register(GENERATE_PATH, self._handle_generate,
                        methods=("POST",), pass_client=True)
        routes.register(DRAIN_PATH, self._handle_drain,
                        methods=("POST",))
        routes.register(RESUME_PATH, self._handle_resume,
                        methods=("POST",))
        routes.register(PREFIXES_PATH, self._handle_prefixes,
                        methods=("GET",))
        if self._routes is not None:
            # The shared exporter serves the GLOBAL registry; private
            # routes always get their own front door.
            self._own_exporter = _exporter.start_exporter(
                _telemetry.registry(), self._port, host=self._host,
                routes=self._routes)
        elif self._shared_exporter() is None and self._port is not None:
            self._own_exporter = _exporter.start_exporter(
                _telemetry.registry(), self._port, host=self._host)
        self._thread = threading.Thread(
            target=self._loop, name="hvd-serve-loop", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self.engine.stop_followers()
        routes = (self._routes if self._routes is not None
                  else _exporter.routes())
        routes.unregister(GENERATE_PATH)
        routes.unregister(DRAIN_PATH)
        routes.unregister(RESUME_PATH)
        routes.unregister(PREFIXES_PATH)
        routes.unregister_health(HEALTH_KEY)
        if self._own_exporter is not None:
            self._own_exporter.close()
            self._own_exporter = None

    def __enter__(self) -> "LMServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the serve loop ----------------------------------------------------
    def _control_plane_lost(self) -> bool:
        """Persistent control-plane loss: the runtime poisoned itself
        (a peer died / the reconnect grace expired).  Serving over the
        training mesh cannot make progress past this — degrade instead
        of wedging (hvd-chaos no-hang contract)."""
        try:
            from ..core import state as _state

            st = _state.global_state()
            return bool(st.initialized and st.multiprocess
                        and st.peer_shutdown)
        except Exception:  # noqa: BLE001 — serving works without init
            return False

    def _loop(self) -> None:  # thread: serve-loop
        _athreads.set_role("serve-loop")
        degraded = False
        while not self._stop.is_set():
            if not degraded and self._control_plane_lost():
                # Graceful degradation, once: stop admission (new
                # /generate → 503), evict in-flight sequences as
                # DRAINED (their blocked handlers answer 503 instead
                # of hanging to the client timeout), and flip /healthz
                # NOT_READY so the load balancer drains traffic.
                degraded = True
                _M_CP_LOSSES.inc()
                _telemetry.error_event(
                    "hvd-serve: control plane lost; draining and "
                    "reporting NOT_READY (503) until relaunch")
                try:
                    self.engine.drain()
                except Exception as e:  # noqa: BLE001 — degradation
                    # must not kill the loop it is protecting
                    _telemetry.exception_event(
                        "serve-degrade", f"{type(e).__name__}: {e}")
                self.engine.mark_unready()
            if self.engine.scheduler.idle():
                # Park until a submission wakes us; short timeout so a
                # racing submit-after-idle-check is picked up anyway.
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                self.engine.step()
            except Exception as e:  # noqa: BLE001 — the loop must
                # survive one bad batch; the flight recorder keeps the
                # forensics, every caught-up request fails FAST (not at
                # its HTTP timeout) — abort_all fails exactly the
                # requests its drain removed, so a submission racing
                # the recovery cannot be silently lost — and the KV
                # slots/pages are freed so the next request serves
                # normally.
                _telemetry.exception_event("serve-loop",
                                           f"{type(e).__name__}: {e}")
                try:
                    self.engine.abort_all()
                except Exception as e2:  # noqa: BLE001 — a recovery
                    # that raises must not kill this thread: a dead
                    # serve loop with a still-ready /healthz blackholes
                    # every future request until its client timeout.
                    # But a FAILED recovery may have left admission
                    # closed and requests unanswered — flip /healthz
                    # to NOT_READY so the load balancer drains traffic
                    # instead of feeding the blackhole.
                    _telemetry.exception_event(
                        "serve-loop-recovery",
                        f"{type(e2).__name__}: {e2}")
                    self.engine.mark_unready()

    # -- /generate ---------------------------------------------------------
    def _handle_generate(self, query: str, body: bytes,
                         client=None) -> Tuple[int, bytes, str]:
        try:
            payload = json.loads(body.decode() or "{}")
        except ValueError:
            return (400, b'{"error": "invalid JSON"}\n',
                    "application/json")
        vocab = self.engine.cfg.vocab_size
        tokens = payload.get("tokens")
        if tokens is None and "text" in payload:
            try:
                tokens = encode_text(payload["text"], vocab)
            except ValueError as e:
                return (400, json.dumps({"error": str(e)}).encode(),
                        "application/json")
        if not tokens:
            return (400, b'{"error": "need tokens or text"}\n',
                    "application/json")
        if any(not 0 <= int(t) < vocab for t in tokens):
            return (400, json.dumps(
                {"error": f"token ids must be in [0, {vocab})"}).encode(),
                "application/json")
        try:
            req = self.engine.submit(
                [int(t) for t in tokens],
                max_new_tokens=int(payload.get("max_tokens", 32)),
                temperature=float(payload.get("temperature", 0.0)),
                seed=int(payload.get("seed", 0)))
        except ValueError as e:
            return (400, json.dumps({"error": str(e)}).encode(),
                    "application/json")
        except RuntimeError as e:
            # The scheduler is draining (elastic resize or the error
            # recovery's brief window) — a retryable server state, not
            # a malformed request.
            return (503, json.dumps({"error": str(e)}).encode(),
                    "application/json")
        self._wake.set()
        timeout = float(payload.get("timeout", 120.0))
        t0 = time.perf_counter()
        # Block for the completion in short slices, watching the client
        # connection between slices: a client that disconnected
        # mid-generation releases its slot through the abort path
        # instead of burning decode iterations on tokens nobody will
        # read (hvd-chaos hardening; counted below).
        deadline = t0 + timeout
        while True:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                return (504, json.dumps(
                    {"error": "generation timed out", "rid": req.rid}
                ).encode(), "application/json")
            try:
                out = req.result(timeout=min(0.2, remaining))
                break
            except TimeoutError:
                if client is not None and client.disconnected():
                    _M_CLIENT_DISCONNECTS.inc()
                    disposition = self.engine.abort_request(req)
                    print(f"[hvd-serve] client of request {req.rid} "
                          f"disconnected mid-generation; slot "
                          f"released ({disposition})", file=sys.stderr)
                    self._wake.set()  # let the loop evict promptly
                    # The body goes nowhere (the client is gone); the
                    # status keeps the access path honest.
                    return (499, json.dumps(
                        {"error": "client disconnected",
                         "rid": req.rid}).encode(), "application/json")
        fail = _FAILURE_STATUS.get(req.finish_reason)
        if fail is not None:
            # Failures are explicit statuses, never a 200 that only
            # finish_reason distinguishes from success (partial tokens
            # included either way).
            code, msg = fail
            return (code, (json.dumps({
                "error": msg,
                "rid": req.rid, "finish_reason": req.finish_reason,
                "tokens": out}) + "\n").encode(), "application/json")
        total = time.perf_counter() - t0
        resp = {
            "rid": req.rid,
            "tokens": out,
            "finish_reason": req.finish_reason,
            "ttft_ms": round((req.t_first_token - req.t_submit) * 1e3, 3)
            if req.t_first_token else None,
            "total_ms": round(total * 1e3, 3),
            "tokens_per_sec": round(len(out) / total, 1) if total else None,
        }
        text = decode_tokens(out, vocab)
        if text is not None:
            resp["text"] = text
        return (200, (json.dumps(resp) + "\n").encode(),
                "application/json")

    # -- fleet hooks (hvd-route) -------------------------------------------
    def _handle_drain(self, query: str,
                      body: bytes) -> Tuple[int, bytes, str]:
        """Scale-down: drain the engine (in-flight handlers answer 503
        with their partials — the router resubmits those as
        continuations), export queued work + the prefix index for the
        caller to donate, and flip /healthz NOT_READY so the fleet
        stops routing here."""
        from .. import elastic as _elastic

        exported = self.engine.drain()
        payload = _elastic.serving_export_payload(self.engine, exported)
        self.engine.mark_unready()
        self._wake.set()  # let the loop notice the emptied scheduler
        return (200, (json.dumps(payload) + "\n").encode(),
                "application/json")

    def _handle_resume(self, query: str,
                       body: bytes) -> Tuple[int, bytes, str]:
        """Boot/scale-up: install a drained export (requests resubmit,
        prefix chains ghost-seed the cache) and reopen admission.  A
        replica that was drained NOT_READY warm-starts back to ready —
        executables come from the compile cache, so this is cheap on a
        relaunch."""
        from .. import elastic as _elastic

        try:
            payload = json.loads(body.decode() or "{}")
        except ValueError:
            return (400, b'{"error": "invalid JSON"}\n',
                    "application/json")
        if not self.engine.ready:
            self.engine.warm_start()
        if isinstance(payload, dict) and not payload.get("requests"):
            # Prefix-only donation (an autoscaler warming this replica
            # from a peer's index): ghost-seed WITHOUT the wholesale
            # drain-and-replace — a live replica's in-flight work
            # survives the gift.
            if payload.get("prefixes"):
                self.engine.seed_prefixes(payload["prefixes"])
            installed = []
        else:
            installed = _elastic.serving_install_payload(self.engine,
                                                         payload)
        self._wake.set()
        return (200, (json.dumps(
            {"installed": len(installed),
             "ready": self.engine.ready}) + "\n").encode(),
            "application/json")

    def _handle_prefixes(self, query: str,
                         body: bytes) -> Tuple[int, bytes, str]:
        """The live prefix index as maximal token chains — the
        autoscaler's boot-seed source (no drain required)."""
        return (200, (json.dumps(
            {"prefixes": self.engine.export_prefix_index()})
            + "\n").encode(), "application/json")
